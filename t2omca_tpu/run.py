"""Experiment driver (C4): the train loop with the reference's cadences.

Re-creates ``run``/``run_sequential``/``evaluate_sequential``
(``/root/reference/per_run.py:20-309``) without sacred: config comes from the
frozen-dataclass config tree (``config.py``), experiment identity is the
unique token (``{name}_seed{seed}_{map}_{datetime}``, ``per_run.py:42``), and
sinks are console + TensorBoard + JSONL (M9).

Structure of one iteration (reference ``per_run.py:212-288``):
rollout → insert → (if can_sample ∧ episode gate) sample → train → feed
``|TD|+1e-6`` back as priorities (Q9) → cadenced test/log/checkpoint.
Every device-side stage is a jitted pure function; the Python loop only
sequences them and moves scalars to the logger.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from contextlib import nullcontext
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from .components.episode_buffer import (BufferState, PrioritizedReplayBuffer,
                                        ReplayBuffer)
from .config import TrainConfig, sanity_check, unique_token
from .controllers.basic_mac import MAC_REGISTRY
from .envs.registry import make_env
from .learners.qmix_learner import LEARNER_REGISTRY, LearnerState
from .runners import RUNNER_REGISTRY
from .runners.episode_runner import EpisodeRunner
from .runners.parallel_runner import ParallelRunner, RunnerState
from .obs import memwatch as obs_memwatch
from .obs import pulse as obs_pulse
from .obs import sight as obs_sight
from .obs import spans as obs_spans
from .parallel import distributed as dist
from .utils import elastic, resilience, watchdog
from .utils.checkpoint import (find_checkpoint, load_checkpoint,
                               load_checkpoint_sharded, prune_checkpoints,
                               save_checkpoint, save_checkpoint_shards)
from .utils.logging import Logger
from .utils.profiling import StageTimer, TraceWindow
from .utils.stats import StatsAccumulator
from .utils.timehelper import time_left, time_str


@struct.dataclass
class TrainState:
    """The full checkpointable state (SURVEY.md §5(4): exact resume)."""

    learner: LearnerState
    runner: RunnerState
    buffer: BufferState
    episode: jnp.ndarray      # () int32 — episodes collected


def superstep_eligible(cfg: TrainConfig) -> bool:
    """Whether the fused K-iteration superstep program serves this config
    (the ``ops/query_slice.py`` eligibility-predicate pattern): K > 1
    requested AND the replay ring is device-resident — the host-RAM
    buffer's insert/sample are host calls and cannot live inside one XLA
    program, so ``buffer_cpu_only`` configs keep the classic
    three-program path at any ``superstep`` value."""
    return cfg.superstep > 1 and not cfg.replay.buffer_cpu_only


def sebulba_eligible(cfg: TrainConfig) -> bool:
    """Whether the Sebulba decoupled actor/learner loop serves this
    config (``parallel/sebulba.py``; the ``superstep_eligible``
    predicate pattern): ``sebulba.actor_devices > 0`` opts in, and
    ``sanity_check`` has already rejected the incompatible combinations
    (host-RAM replay, dp_devices, superstep > 1)."""
    return cfg.sebulba.actor_devices > 0


def _strong(tree):
    """Drop weak_type from every chained output: the driver feeds these
    back as inputs, and a weak-typed leaf (e.g. from a Python-scalar
    jnp.where branch) makes the output aval differ from the strong input
    aval — forcing a silent second compile of the whole program on loop
    iteration 2. astype(same-dtype) is a no-op in XLA but strips the
    weak flag."""
    return jax.tree.map(lambda x: x.astype(x.dtype), tree)


def _squeeze0(tree):
    """Drop the leading size-1 population axis from every leaf — the
    P=1 graftpop layout bridge (``population_superstep_program``). Pure
    layout ops; MUST stay the exact inverse of :func:`_expand0` — the
    P=1 bit-parity contract stands on both programs using the same
    bridge."""
    return jax.tree.map(lambda x: jnp.squeeze(x, 0), tree)


def _expand0(tree):
    """Restore the leading population axis ``_squeeze0`` dropped."""
    return jax.tree.map(lambda x: x[None], tree)


@dataclasses.dataclass
class Experiment:
    """Built components + jitted programs for one config."""

    # class-level (not a field): whether any build() in this process has
    # pinned jax_default_prng_impl yet — a later build that CHANGES the
    # impl is the hazardous case worth a RuntimeWarning
    _prng_impl_pinned = False

    cfg: TrainConfig
    env: object
    mac: object
    learner: object
    runner: ParallelRunner
    buffer: ReplayBuffer
    episode_runner: EpisodeRunner

    @classmethod
    def build(cls, cfg: TrainConfig) -> "Experiment":
        cfg = sanity_check(cfg)
        # process-global by necessity: raw PRNGKey arrays carry no impl
        # tag, so every split/draw in the jitted programs resolves the
        # impl from this config. "rbg" = XLA RngBitGenerator, the TPU
        # hardware generator — much cheaper than threefry for the
        # rollout's many small draws. Key shapes differ (4 vs 2 uint32),
        # so checkpoints are impl-specific (shape-validated restore names
        # the mismatch). Only touched when the value actually changes, and
        # a mid-process switch warns loudly: keys made or programs traced
        # under the previous impl (an earlier Experiment build in this
        # process, caller-created keys) mis-resolve under the new one —
        # interleave cross-impl Experiments at your own risk.
        want = {"threefry": "threefry2x32"}.get(cfg.prng_impl, cfg.prng_impl)
        have = jax.config.jax_default_prng_impl
        if have != want:
            if cls._prng_impl_pinned:
                import warnings
                warnings.warn(
                    f"Experiment.build switches jax_default_prng_impl "
                    f"{have!r} -> {want!r} mid-process: PRNG keys and "
                    f"jitted programs from earlier builds in this process "
                    f"resolve against the NEW impl and will break or "
                    f"silently draw different streams; rebuild (or avoid "
                    f"holding) anything created under the old impl",
                    RuntimeWarning, stacklevel=2)
            jax.config.update("jax_default_prng_impl", want)
        cls._prng_impl_pinned = True
        env = make_env(cfg.env_args)
        env_info = env.get_env_info()
        mac = MAC_REGISTRY[cfg.mac].build(cfg, env_info)
        learner = LEARNER_REGISTRY[cfg.learner].build(cfg, mac, env_info)
        runner_cls = RUNNER_REGISTRY[cfg.runner]
        runner = runner_cls(env, mac, cfg)
        from .ops.query_slice import entity_store_eligible
        buf_kw = dict(
            capacity=cfg.replay.buffer_size,
            episode_limit=cfg.env_args.episode_limit,
            n_agents=env_info["n_agents"],
            n_actions=env_info["n_actions"],
            obs_dim=env_info["obs_shape"],
            state_dim=env_info["state_shape"],
            store_dtype=cfg.replay.store_dtype,
        )
        if not cfg.replay.buffer_cpu_only:
            buf_kw["compact_obs"] = entity_store_eligible(cfg)
        if cfg.replay.buffer_cpu_only:
            # host-RAM replay with the device-side PER sample (reference
            # buffer_cpu_only semantics: storage on CPU, samples to
            # device; the priority vector is device-mirrored so index
            # selection + importance weights run as one device program)
            from .components.host_replay import HostReplayBuffer
            buffer = HostReplayBuffer(
                alpha=cfg.replay.per_alpha, beta0=cfg.replay.per_beta,
                t_max=cfg.t_max, prioritized=cfg.replay.prioritized,
                **buf_kw)
        else:
            buf_cls = (PrioritizedReplayBuffer if cfg.replay.prioritized
                       else ReplayBuffer)
            if cfg.replay.prioritized:
                buf_kw.update(alpha=cfg.replay.per_alpha,
                              beta0=cfg.replay.per_beta, t_max=cfg.t_max)
            buffer = buf_cls(**buf_kw)
        episode_runner = EpisodeRunner(env, mac, cfg)
        return cls(cfg=cfg, env=env, mac=mac, learner=learner, runner=runner,
                   buffer=buffer, episode_runner=episode_runner)

    # ------------------------------------------------------------------ state

    @property
    def host_buffer(self) -> bool:
        return getattr(self.buffer, "is_host", False)

    def init_train_state(self, seed: int) -> TrainState:
        k_learner, k_runner = jax.random.split(jax.random.PRNGKey(seed))
        return TrainState(
            learner=self.learner.init_state(k_learner),
            runner=self.runner.init_state(k_runner),
            # host buffers keep their state outside the jitted pytree
            buffer=None if self.host_buffer else self.buffer.init(),
            episode=jnp.zeros((), jnp.int32),
        )

    # ------------------------------------------------------------------ programs

    def jitted_programs(self, constrain_batch=None, constrain_runner=None,
                        constrain_buffer=None, constrain_learner=None,
                        donate: bool = False):
        """→ (rollout, insert, train_iter) jitted programs.

        The ``constrain_*`` hooks are optional identity-shaped functions
        applied to program outputs — the multi-chip path
        (``parallel.DataParallel``) injects ``with_sharding_constraint``
        through them so both paths share one program definition. They
        cover every value the driver loop CHAINS back in as an input
        (episode batches, runner state, replay state, learner state):
        without the output constraints GSPMD is free to choose different
        output shardings than the canonical input placement, and the
        second-and-later iterations of the loop would silently compile
        and run a differently-sharded program.

        ``donate=True`` donates the replay ring to ``insert`` and the train
        state to ``train_iter`` — XLA then updates both in place instead of
        copying the (largest-on-chip) buffer arrays every call. Only for
        callers that never reuse the pre-call state (the ``run_sequential``
        loop replaces it immediately); benches/tests that re-time a program
        on the same inputs must keep the default."""
        runner, buffer, learner, cfg = (self.runner, self.buffer,
                                        self.learner, self.cfg)
        constrain = constrain_batch or (lambda b: b)
        c_runner = constrain_runner or (lambda rs: rs)
        c_buffer = constrain_buffer or (lambda b: b)
        c_learner = constrain_learner or (lambda l: l)

        def _rollout(params, rs, test_mode):
            rs2, batch, stats = runner.run(params, rs, test_mode=test_mode)
            return _strong(c_runner(rs2)), constrain(batch), stats

        rollout = jax.jit(_rollout, static_argnames="test_mode")

        if self.host_buffer:
            # storage lives in host RAM (reference buffer_cpu_only): insert
            # and sample are host calls, only learner.train is jitted
            train = jax.jit(learner.train)

            def insert(_ts_buffer, batch):
                buffer.insert_episode_batch(batch)
                return None

            def train_iter_host(ts: TrainState, key: jax.Array,
                                t_env: jnp.ndarray):
                # host RNG owns the stratum uniforms; key seeds noise/
                # dropout (train ignores it for pure configs). sample()
                # first consumes the PREVIOUS iteration's deferred
                # priority feedback — the |TD| / finite-flag fetch is
                # started asynchronously below and never blocks this
                # iteration (one ~0.66 s tunnel round-trip per train
                # iter removed, BASELINE.md); the non-finite guard
                # stays in the flush (a tripped step leaves the
                # priority mirrors untouched). Index selection and
                # importance weights run as ONE device program over the
                # mirrored priority vector (PR 13) — zero sum-tree
                # ctypes crossings on this path
                batch, idx, weights = buffer.sample(cfg.batch_size,
                                                    int(t_env))
                learner_state, info = train(ts.learner, batch, weights,
                                            t_env, ts.episode, key)
                buffer.defer_priority_update(idx, info["td_errors_abs"],
                                             info["all_finite"])
                if cfg.obs.sight.enabled and buffer.prioritized:
                    # host-replay twin of the in-graph PER health read:
                    # pure numpy over the host priority mirror — zero
                    # device traffic on the buffer_cpu_only path
                    info = dict(info, **buffer.sight_priority_info())
                return ts.replace(learner=learner_state), info

            return rollout, insert, train_iter_host

        def _insert(state, batch):
            return _strong(c_buffer(buffer.insert_episode_batch(state,
                                                                batch)))

        insert = jax.jit(_insert, donate_argnums=(0,) if donate else ())

        def _train_iter(ts: TrainState, key: jax.Array, t_env: jnp.ndarray):
            """sample → train → priority feedback, as one program."""
            k_sample, k_learn = jax.random.split(key)
            batch, idx, weights = buffer.sample(
                ts.buffer, k_sample, cfg.batch_size, t_env)
            learner_state, info = learner.train(
                ts.learner, constrain(batch), weights, t_env, ts.episode,
                k_learn)
            # non-finite guard (valid=): a tripped step must not scatter
            # NaN priorities into the ring (they would win every PER
            # draw forever) — the buffer writes back the episodes'
            # EXISTING stored values instead, value-identical to not
            # updating, with no host sync and no full-ring select
            buf = buffer.update_priorities(
                ts.buffer, idx, info["td_errors_abs"] + 1e-6,      # Q9
                valid=info["all_finite"])
            # graftsight PER health: one masked reduce over the
            # post-update priority vector, inside this same program
            # (docs/OBSERVABILITY.md §6 — zero extra dispatches;
            # no-op unless the static gate + prioritized replay apply)
            info = obs_sight.maybe_buffer_info(cfg, info, buf)
            return _strong(ts.replace(learner=c_learner(learner_state),
                                      buffer=c_buffer(buf))), info

        return rollout, insert, jax.jit(
            _train_iter, donate_argnums=(0,) if donate else ())

    def superstep_program(self, k: int, constrain_batch=None,
                          constrain_runner=None, constrain_buffer=None,
                          constrain_learner=None, donate: bool = False):
        """→ jitted ``superstep(ts, keys, t_env0) -> (ts', stacked_stats,
        stacked_infos)`` — the Anakin/Podracer fusion (PAPERS.md): rollout
        → in-place ring insert → gate-checked sample+train as ONE XLA
        program, ``lax.scan``-ed ``k`` iterations per dispatch.

        Amortizes the per-dispatch overhead (~0.66 s under the axon
        tunnel, BASELINE.md) over ``k`` full train iterations, and never
        materializes the ``(B, T+1, ...)`` episode batch between rollout
        and insert: the rollout scan's time-major emission scatters
        straight into the (donated → in-place) replay ring
        (``ReplayBuffer.insert_time_major``).

        Contract with the classic three-program loop (pinned by
        tests/test_superstep.py):

        * the train gate ``episodes_in_buffer >= batch_size AND episode
          >= accumulated_episodes`` is traced arithmetic on the carried
          counters — a ``lax.cond``, so skipped sub-iterations pay no
          train compute;
        * ``keys`` is the ``(k, key)`` stack of per-sub-iteration train
          keys. The driver splits its key stream ONLY for sub-iterations
          whose gate fires (it mirrors the counters host-side, exactly
          like the classic loop's host gate) and passes zeros for skipped
          rows, so the consumed key stream — and therefore training — is
          bit-identical to the K=1 loop;
        * epsilon/beta schedules thread through as functions of the
          carried ``t_env``: sub-iteration ``i`` trains at ``t_env0 +
          (i+1)·B·T``, matching the host counter the classic loop passes;
        * ``stacked_stats``/``stacked_infos`` come back shaped ``(k,
          ...)`` and feed the host accumulators once per dispatch; info
          rows of skipped sub-iterations are aval-matched zeros with
          ``all_finite=True`` (``QMixLearner.train_info_zeros``) and are
          dropped by the driver via its host gate mirror.

        ``donate=True`` donates the full TrainState — ring, learner and
        runner state update in place across the superstep. Host-RAM
        replay configs are ineligible (``superstep_eligible``)."""
        return jax.jit(
            self._superstep_fn(k, constrain_batch, constrain_runner,
                               constrain_buffer, constrain_learner),
            donate_argnums=(0,) if donate else ())

    def _superstep_fn(self, k: int, constrain_batch=None,
                      constrain_runner=None, constrain_buffer=None,
                      constrain_learner=None):
        """The unjitted superstep body — shared by the classic jit
        (``superstep_program``) and the graftpop population vmap
        (``population_superstep_program``). ``spec`` (an optional
        graftpop ``PopulationSpec`` of per-member traced scalars)
        threads the member's epsilon scale into the rollout, its PER
        exponent into the ring writes, and its lr scale into the
        learner update; ``None`` (the classic path) compiles the exact
        pre-population program — every graftprog fingerprint pinned."""
        if self.host_buffer:
            raise ValueError(
                "superstep_program requires the device-resident replay "
                "ring; buffer_cpu_only configs use the three-program "
                "path (superstep_eligible)")
        if k < 1:
            raise ValueError(f"superstep k must be >= 1, got {k}")
        runner, buffer, learner, cfg = (self.runner, self.buffer,
                                        self.learner, self.cfg)
        constrain = constrain_batch or (lambda b: b)
        c_runner = constrain_runner or (lambda rs: rs)
        c_buffer = constrain_buffer or (lambda b: b)
        c_learner = constrain_learner or (lambda l: l)
        steps_per_rollout = cfg.batch_size_run * cfg.env_args.episode_limit

        def _superstep(ts: TrainState, keys: jax.Array,
                       t_env0: jnp.ndarray, spec=None):
            alpha = None if spec is None else spec.per_alpha
            roll_kw = {}
            if spec is not None:
                roll_kw["eps_scale"] = spec.eps_scale
                if cfg.population.scenario_salt:
                    roll_kw["member"] = spec.member

            def _train(op):
                ts, key, t_env = op
                # identical key/arithmetic threading to _train_iter above
                k_sample, k_learn = jax.random.split(key)
                batch, idx, weights = buffer.sample(
                    ts.buffer, k_sample, cfg.batch_size, t_env)
                learner_state, info = learner.train(
                    ts.learner, constrain(batch), weights, t_env,
                    ts.episode, k_learn, spec=spec)
                buf = buffer.update_priorities(
                    ts.buffer, idx, info["td_errors_abs"] + 1e-6,  # Q9
                    valid=info["all_finite"], alpha=alpha)
                return ts.replace(learner=c_learner(learner_state),
                                  buffer=c_buffer(buf)), _sight_buf(info,
                                                                    buf)

            def _sight_buf(info, buf):
                # graftsight PER health, in-graph (the shared definition
                # — see _train_iter). BOTH cond branches route through
                # this so the info pytrees stay aval-identical (the skip
                # branch reads the untouched ring)
                return obs_sight.maybe_buffer_info(cfg, info, buf)

            def _skip(op):
                ts, _, _ = op
                return ts, _sight_buf(
                    learner.train_info_zeros(cfg.batch_size), ts.buffer)

            def _body(ts: TrainState, xs):
                key, t_env = xs
                rs, tm, stats = runner.run_raw(ts.learner.params["agent"],
                                               ts.runner, test_mode=False,
                                               **roll_kw)
                buf = buffer.insert_time_major(ts.buffer, tm, alpha=alpha)
                ts = ts.replace(runner=c_runner(rs), buffer=c_buffer(buf),
                                episode=ts.episode + cfg.batch_size_run)
                gate = (buffer.can_sample(ts.buffer, cfg.batch_size)
                        & (ts.episode >= cfg.accumulated_episodes))
                ts, info = jax.lax.cond(gate, _train, _skip,
                                        (ts, key, t_env))
                return _strong(ts), (stats, _strong(info))

            t_envs = (jnp.asarray(t_env0, jnp.int32)
                      + jnp.arange(1, k + 1, dtype=jnp.int32)
                      * steps_per_rollout)
            ts, (stats, infos) = jax.lax.scan(_body, ts, (keys, t_envs))
            return ts, stats, infos

        return _superstep

    def population_superstep_program(self, k: int, donate: bool = False):
        """→ jitted ``superstep_pop(ts, keys, t_env0, spec) -> (ts',
        stacked_stats, stacked_infos)`` — graftpop (docs/POPULATION.md):
        the SAME fused superstep body vmapped over a leading ``(P,)``
        population axis of the full train state, per-member ``(P, k)``
        key stacks and the :class:`~t2omca_tpu.population.PopulationSpec`
        of per-member hyperparameter scalars. ``t_env0`` stays a shared
        scalar (the counters evolve identically across members). ONE
        donated dispatch advances all P members; outputs come back with
        the extra leading ``(P,)`` axis on every stats/info leaf.

        P=1 deliberately bypasses ``jax.vmap``: the member axis is
        squeezed inside the jit and the UNBATCHED superstep body runs
        directly (axis-restored on the way out — pure layout ops), so a
        single-member population lowers the classic program's exact
        arithmetic and stays BIT-identical to the classic loop. A
        batched rank would not: XLA's batched reduces reassociate f32
        sums (data-dependent 1-ULP drift in gradient accumulations —
        measured on CPU), which is also why P>=2 members pin
        bit-parity only against EACH OTHER (same batched kernel), not
        against their solo runs (docs/POPULATION.md §parity). When the
        P=1 spec is statically NEUTRAL (no grids, no scenario salt, no
        PBT) the spec seams drop out entirely (``spec=None`` into the
        body) — even a value-neutral traced seam (``x*1.0``,
        ``pow(x, traced-default)``) perturbs XLA's fusion choices
        enough to flip a reduce tiling and drift a ULP (measured), and
        the bit-parity contract tolerates zero ULPs."""

        fn = self._superstep_fn(k)
        pc = self.cfg.population
        p = int(pc.size)
        neutral = (p == 1 and not pc.lr and not pc.eps_scale
                   and not pc.per_alpha and not pc.scenario_salt
                   and not pc.pbt.enabled)

        def _superstep_pop(ts: TrainState, keys: jax.Array,
                           t_env0: jnp.ndarray, spec):
            if p == 1:
                out_ts, stats, infos = fn(
                    _squeeze0(ts), jnp.squeeze(keys, 0), t_env0,
                    None if neutral else _squeeze0(spec))
                return _expand0(out_ts), _expand0(stats), _expand0(infos)
            return jax.vmap(
                lambda t, kk, s: fn(t, kk, t_env0, s))(ts, keys, spec)

        return jax.jit(_superstep_pop,
                       donate_argnums=(0,) if donate else ())

    def population_rollout_program(self):
        """→ jitted ``pop_test(params, rs) -> (rs', stats)``: the
        greedy test rollout vmapped over the population axis — serves
        the test cadence of the population driver loop (the episode
        batch is dropped inside the jit, so XLA never materializes
        it). P=1 squeezes instead of vmapping, for the same
        bit-parity reason as ``population_superstep_program``."""
        runner = self.runner
        p = int(self.cfg.population.size)

        def one(params, r):
            r2, _tm, stats = runner.run_raw(params, r, test_mode=True)
            return _strong(r2), stats

        def _pop_test(params, rs):
            if p == 1:
                r2, stats = one(_squeeze0(params), _squeeze0(rs))
                return _expand0(r2), _expand0(stats)
            return jax.vmap(one)(params, rs)

        return jax.jit(_pop_test)


def register_audit_programs(ctx):
    """graftprog registry hook (``analysis/registry.py``): name the
    driver's hot programs once, so the compiled-program auditor and the
    budget baseline (``analysis/programs.json``) can build exactly what
    ``run_sequential`` dispatches. Everything is abstract — eval_shape
    state + ShapeDtypeStruct keys — and ``t_env`` is the driver's own
    weak-typed ``jnp.asarray(int)`` scalar, so the recorded fingerprint
    is the fingerprint of the program the loop actually runs (an aval
    drift between driver and audit surfaces as GP304)."""
    from .analysis.registry import AuditProgram
    exp, ts, k = ctx.exp, ctx.ts_shape, ctx.superstep_k
    rollout, insert, train_iter = exp.jitted_programs(donate=True)
    sup = exp.superstep_program(k, donate=True)
    params, rs = ts.learner.params["agent"], ts.runner
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    keys = jax.ShapeDtypeStruct((k,) + key.shape, key.dtype)
    t_env = jnp.asarray(0)           # weak-typed, like the driver's
    _, batch, _ = jax.eval_shape(
        lambda p, r: rollout(p, r, test_mode=False), params, rs)
    return {
        "rollout": AuditProgram(
            rollout, (params, rs), kwargs=dict(test_mode=False),
            description="parallel env rollout (classic path + test "
                        "cadence)"),
        "insert": AuditProgram(
            insert, (ts.buffer, batch), donate_argnums=(0,),
            description="episode-batch ring insert (classic path, "
                        "donated ring)"),
        "train_iter": AuditProgram(
            train_iter, (ts, key, t_env), donate_argnums=(0,),
            compile=True,
            description="sample -> train -> priority feedback "
                        "(donated TrainState)"),
        "superstep": AuditProgram(
            sup, (ts, keys, t_env), donate_argnums=(0,), compile=True,
            description=f"fused K={k} rollout->insert->train superstep "
                        f"(donated TrainState)"),
        **_kernel_pair_programs(key, t_env),
        **_sight_twin_programs(key, t_env),
        **_population_twin_programs(key, t_env),
    }


def _kernel_pair_programs(key, t_env):
    """The kernel-mode byte-comparison pair (PR 13): the SAME
    ``_train_iter`` lowered under each ``kernels.attention`` mode at the
    kernel audit scale (``registry.kernels_audit_config`` — token counts
    where the logits tensor the flash path eliminates is material).
    Lowered level only; the GP302 ratchet + tests/test_graftprog.py pin
    ``train_iter_pallas`` strictly BELOW ``train_iter_pallas_ref`` —
    the train-path bytes the flash backward exists to remove."""
    from .analysis.registry import AuditProgram, kernels_audit_context
    out = {}
    for mode, name in (("pallas", "train_iter_pallas"),
                       ("xla", "train_iter_pallas_ref")):
        kctx = kernels_audit_context(mode)
        _, _, k_train_iter = kctx.exp.jitted_programs(donate=True)
        out[name] = AuditProgram(
            k_train_iter, (kctx.ts_shape, key, t_env),
            donate_argnums=(0,),
            description=(f"sample -> train -> priority feedback under "
                         f"kernels.attention={mode} at the kernel audit "
                         f"scale — the flash-vs-einsum train-path byte "
                         f"comparison (pallas must stay strictly below "
                         f"the _ref twin)"))
    return out


def _sight_twin_programs(key, t_env):
    """The sight-on twin audit entries (the PR 13 kernel-pair pattern):
    the SAME ``_train_iter``/``_superstep`` lowered under
    ``obs.sight.enabled`` at the shared audit scale
    (``registry.sight_audit_config``). The twins carry their own
    GP301/302 budgets so the diagnostic overhead is itself RATCHETED —
    a sight change that doubles the train step's bytes fails the gate —
    while the sight-OFF fingerprints of
    ``train_iter``/``superstep``/``learner_train``/``dp_superstep``
    stay byte-identical (the static gate compiles out; zero
    re-baseline, pinned by tests/test_sight.py)."""
    from .analysis.registry import AuditProgram, sight_audit_context
    sctx = sight_audit_context()
    exp, ts, k = sctx.exp, sctx.ts_shape, sctx.superstep_k
    _, _, s_train_iter = exp.jitted_programs(donate=True)
    s_sup = exp.superstep_program(k, donate=True)
    keys = jax.ShapeDtypeStruct((k,) + key.shape, key.dtype)
    return {
        "train_iter_sight": AuditProgram(
            s_train_iter, (ts, key, t_env), donate_argnums=(0,),
            description="sample -> train -> priority feedback with the "
                        "graftsight in-graph diagnostics compiled in "
                        "(obs.sight.enabled) — the diagnostic overhead "
                        "ratchet next to the sight-off train_iter"),
        "superstep_sight": AuditProgram(
            s_sup, (ts, keys, t_env), donate_argnums=(0,),
            description=f"fused K={k} superstep with the graftsight "
                        f"diagnostics compiled in — pins the fused-path "
                        f"diagnostic overhead (both lax.cond branches "
                        f"carry the sight info pytree)"),
    }


def _population_twin_programs(key, t_env):
    """The graftpop audit entry (the PR 13/14 twin pattern):
    ``superstep_pop`` — the SAME fused superstep body vmapped over a
    FIXED P=2 population at the shared audit scale
    (``registry.population_audit_config``), ratcheted in programs.json
    so a population-path cost regression fails the gate statically,
    while the population-OFF fingerprints of every existing hot program
    stay byte-identical (the spec seams are ``None``-defaulted — zero
    re-baseline, pinned by the t1 prelude)."""
    import jax as _jax

    from .analysis.registry import (AuditProgram, population_audit_context,
                                    population_kernels_audit_context)
    pctx = population_audit_context()
    exp, k = pctx.exp, pctx.superstep_k
    p = pctx.cfg.population.size
    # the context's ts_shape IS the stacked (ts, spec) aval pair —
    # registry.population_audit_context docstring
    ts_shape, spec_shape = pctx.ts_shape
    prog = exp.population_superstep_program(k, donate=True)
    keys = _jax.ShapeDtypeStruct((p, k) + key.shape, key.dtype)
    # vmap-over-pallas twin (graftlattice): the same population program
    # under kernels.attention=pallas at the KERNEL audit scale — its own
    # context, so neither the xla-mode population baseline above nor the
    # population-OFF pallas baselines move a byte
    pkctx = population_kernels_audit_context()
    pk_ts, pk_spec = pkctx.ts_shape
    pk_prog = pkctx.exp.population_superstep_program(k, donate=True)
    pk_keys = _jax.ShapeDtypeStruct((pkctx.cfg.population.size, k)
                                    + key.shape, key.dtype)
    return {
        "superstep_pop": AuditProgram(
            prog, (ts_shape, keys, t_env, spec_shape),
            donate_argnums=(0,),
            description=f"fused K={k} superstep vmapped over a P={p} "
                        f"population (graftpop — one donated dispatch "
                        f"advances P members; per-member lr/eps/alpha "
                        f"spec leaves)"),
        "superstep_pop_pallas": AuditProgram(
            pk_prog, (pk_ts, pk_keys, t_env, pk_spec),
            donate_argnums=(0,),
            description=f"fused K={k} population superstep with the "
                        f"flash attention kernels vmapped over the "
                        f"P={pkctx.cfg.population.size} member axis "
                        f"(vmap-over-pallas, kernel audit scale — "
                        f"populations use the fused forward+backward "
                        f"kernels)"),
    }


def _host_int(x) -> int:
    """Host mirror of a control counter. Under a population the counter
    is (P,)-stacked but every member's copy evolves identically (same
    batch_size_run, capacity, gates), so member 0's value mirrors the
    whole stacked pytree."""
    return int(np.asarray(jax.device_get(x)).reshape(-1)[0])


class _DriverKit:
    """Shared driver-helper kit (graftlattice, ROADMAP item 2): the
    watchdog stamps, fault-handled dispatch, sync-point classification,
    stall response, flight persist and bounded save-lock discipline that
    ``run_sequential`` and ``run_sebulba`` previously carried as
    acknowledged forked copies (PR 10 known debt). One instance per
    driver; each loop binds locals (``_watched = kit.watched`` …) so
    graftlint's name-keyed call-site phase checks (GL110), the
    fault-injection hooks and the tests see the same wrapper names
    either way.

    Parameterization points — the only behavioral deltas the two loops
    ever had:

    * ``default_wd`` — the watchdog a bare ``watched``/``dispatch``
      call stamps with. The classic loop arms its single watchdog here
      (every device-facing region stamps by default); the sebulba loop
      leaves it ``None`` and passes ``awd=`` explicitly per thread (one
      armed stamp per instance — concurrent threads must not share
      one), so its span-only sites (queue waits bounded by the PEER's
      progress, not device health) stay unstamped.
    * ``t_env_fn`` — the classic loop's cursor closure for sites that
      don't pass ``t=`` explicitly; sebulba always passes ``t=`` from
      whichever thread's cursor applies.
    * ``wake`` — sebulba's queue-condition notifier, fired inside the
      stall response so threads blocked on the queue observe the guard
      trip; ``None`` classically.
    * ``P``/``spec_fn`` — the population stamp wrap: the watchdog's
      emergency save writes the stamped state verbatim, and a bare
      (P,)-stacked TrainState would hit the single-member→population
      migration shim on restore and double-stack, so any full
      TrainState stamp is wrapped into the checkpointable ``PopState``
      (runner-state-only and learner-half stamps pass through — they
      are never emergency-saved).
    """

    _UNSET = object()

    def __init__(self, *, cfg, res, log, rec, mw, sight_mon, guard,
                 model_dir, save_lock, P=0, spec_fn=None, wake=None):
        self.cfg, self.res, self.log, self.rec = cfg, res, log, rec
        self.mw, self.sight_mon, self.guard = mw, sight_mon, guard
        self.model_dir, self.save_lock = model_dir, save_lock
        self.P, self.spec_fn, self.wake = P, spec_fn, wake
        self.default_wd = None      # armed by the driver once built
        self.t_env_fn = lambda: 0   # the classic loop re-binds its cursor
        self.dispatch_faults = 0    # transient dispatch errors seen (stats)

    # ------------------------------------------------------------ telemetry

    def persist_flight(self, path: str) -> None:
        """Flight persist + the memwatch high-water + sight-verdict
        blocks (cached state only — safe on crash/stall paths over a
        wedged backend)."""
        extra = {}
        if self.mw.enabled:
            extra["memwatch"] = self.mw.report()
        if self.sight_mon is not None:
            extra["sight"] = self.sight_mon.report()
        self.rec.persist(path, extra=extra or None)

    def watched(self, phase, state=None, awd=_UNSET, t=None, **meta):
        """One watchdog stamp + graftscope span for a device-facing
        region (no-op context when both are disabled) — keeps the
        wd-None guard, the current-t_env threading, and the telemetry
        pairing in one place instead of at every site. ``meta`` lands
        in the span event (attempt counts, K); the watchdog stamp is
        the OUTER context so a hang inside the span bookkeeping is
        still bounded."""
        if awd is _DriverKit._UNSET:
            awd = self.default_wd
        if t is None:
            t = self.t_env_fn()
        if (self.P and state is not None and hasattr(state, "runner")
                and not hasattr(state, "spec")):
            # population runs stamp the CHECKPOINTABLE PopState, never
            # the bare stacked TrainState (class docstring)
            from . import population as graftpop
            state = graftpop.PopState(ts=state, spec=self.spec_fn())
        w = (awd.watch(phase, t_env=t, state=state)
             if awd is not None else None)
        if self.rec.enabled:
            s = self.rec.span(phase, t_env=t, **meta)
            return obs_spans.stacked(w, s) if w is not None else s
        return w if w is not None else nullcontext()

    # ------------------------------------------------------------ dispatch

    def dispatch(self, phase, fn, state, awd=_UNSET, t=None,
                 retryable=True, **context):
        """One device-facing dispatch: fault-injection hook + watchdog
        heartbeat + bounded in-place retry with backoff (ladder rung 0).
        Transient-classified failures retry ``fn`` with the SAME inputs —
        the callers commit their host mirrors only after success, so a
        retry replays an identical dispatch. Pass ``retryable=False``
        when ``fn`` carries non-idempotent HOST side effects the
        commit-after-success discipline cannot cover (the host-buffer
        path: ``buffer.sample()`` advances the host RNG and the ring
        insert mutates host RAM before a transient h2d/sync failure
        surfaces, and ``state_intact`` can't see host mutations — a
        retry would train on a different batch or double-insert); the
        first transient failure then goes straight to the ladder.
        Deterministic errors propagate immediately (retrying a shape bug
        only delays the real diagnosis); exhausted retries — or a
        failure that already consumed the donated state — raise
        DispatchFailed for the ladder. Deliberately NOT composed from
        watchdog.retry_call: the per-attempt stamp+fire, the donation
        check, and the exhaustion→DispatchFailed conversion don't fit
        its propagate-last-error contract."""
        res = self.res
        if t is None:
            t = self.t_env_fn()
        attempts = (1 + res.dispatch_retries) if retryable else 1
        for attempt in range(1, attempts + 1):
            try:
                with self.watched(phase, state, awd=awd, t=t,
                                  attempt=attempt, **context):
                    # the hook fires INSIDE the watched region: an
                    # injected sleep here is indistinguishable from a
                    # hung dispatch to the watchdog (tests rely on this)
                    resilience.fire(phase, t_env=t, attempt=attempt,
                                    **context)
                    return fn()
            except Exception as e:  # noqa: BLE001 — classified below
                if not watchdog.is_transient(e):
                    raise
                self.dispatch_faults += 1
                if attempt >= attempts or not watchdog.state_intact(state):
                    raise watchdog.DispatchFailed(phase, attempt, e) from e
                delay = watchdog.backoff_delay(attempt, res.retry_backoff_s)
                self.log.warning(f"{phase}: transient dispatch failure "
                                 f"(attempt {attempt}/{attempts}), "
                                 f"retrying in {delay:.2f}s: "
                                 f"{type(e).__name__}: {e}")
                time.sleep(delay)

    def sync_point(self, phase, fn, state):
        """One blocking sync/fetch boundary (run-ahead wait, cadence stat
        fetch): watchdog stamp + fault-injection hook + transient
        classification in one place. On the production path these host
        round-trips are where a device-side wedge or async fault
        actually surfaces, so each must carry a stamp — an unstamped
        blocking fetch is exactly the silent hang this layer exists to
        bound. No in-place retry is possible here (the already-
        dispatched computation's donated inputs are gone and its
        outputs are suspect), so a transient failure raises
        ``DispatchFailed`` for the caller to route to the ladder with
        ``can_degrade=False`` — restore is the only rung that can
        stand; deterministic errors propagate unwrapped."""
        try:
            with self.watched(phase, state):
                resilience.fire(phase, t_env=self.t_env_fn())
                return fn()
        except Exception as e:  # noqa: BLE001 — classified below
            if not watchdog.is_transient(e):
                raise
            self.dispatch_faults += 1
            raise watchdog.DispatchFailed(phase, 1, e) from e

    # ------------------------------------------------------------ stalls

    def acquire_save_lock(self, where: str) -> bool:
        """BOUNDED acquire shared by every save site: an emergency save
        wedged inside the stalled backend can hold the lock forever, and
        each waiter (watchdog callback, save cadence, exit path) must
        skip with a warning instead of inheriting the hang — resume then
        falls back to the newest published checkpoint."""
        if self.save_lock.acquire(timeout=max(self.res.stall_grace_s,
                                              60.0)):
            return True
        self.log.warning(f"{where}: checkpoint skipped — an emergency "
                         f"save still holds the save lock (wedged "
                         f"backend?); resume falls back to the newest "
                         f"published checkpoint")
        return False

    def stall_response(self, diag, tag: str = "watchdog",
                       save: bool = True) -> None:
        """The watchdog stall callback: flight tail + memwatch + sight
        extras folded into the diagnosis write, guard trip (BEFORE the
        save attempt — the emergency save reads device state over the
        possibly-wedged backend and can block without raising; with
        stall_grace_s=0 a guard tripped only afterwards would never
        trip at all), queue-wait wakeup, then a gated emergency
        checkpoint from the stamped pre-dispatch state. ``save=False``
        is the actor-thread shape: diagnosis + guard trip only — the
        learner (main) thread owns the checkpointable state and writes
        the emergency save on its own exit path. Telemetry extras are
        guarded: a telemetry failure must not abort the callback before
        the diagnosis write and the guard trip — the stall response
        outranks its own decoration. The memwatch/sight blocks are
        host-cached only (``report()``, never ``snapshot()``): the
        stall path must not read the wedged backend it diagnoses."""
        cfg, res, log = self.cfg, self.res, self.log
        extra = {}
        if self.rec.enabled:
            try:
                extra["recent_spans"] = self.rec.tail()
            except Exception:  # noqa: BLE001 — diagnostics only
                log.exception("graftscope: flight tail unavailable")
        if self.mw.enabled:
            extra["memwatch"] = self.mw.report()
        if self.sight_mon is not None:
            extra["sight"] = self.sight_mon.report()
        watchdog.write_diagnosis(diag, self.model_dir, extra=extra or None)
        self.guard.request(tag)
        if self.wake is not None:
            self.wake()              # unblock any queue-condition wait
        # single-process only: save_checkpoint is a lockstep collective
        # sequence in multi-host, and a one-sided save from THIS
        # process's stalled watchdog would hang in sync_global_devices
        # barriers its (healthy, not-saving) peers never enter — wedging
        # the watchdog thread while it holds save_lock. Multi-host
        # stalls still get the diagnosis + guard trip; resume falls back
        # to the last cadence save. A stall during the checkpoint write
        # itself also skips the save (the staging directory is in use by
        # the stalled writer), as does donated-and-consumed state (its
        # buffers are gone).
        if (save and cfg.save_model and res.emergency_checkpoint
                and jax.process_count() == 1
                and not diag.phase.startswith("checkpoint")
                and diag.state is not None
                and watchdog.state_intact(diag.state)):
            # stall callbacks run on their own threads (the monitor
            # keeps watching), so a previous callback wedged inside the
            # stalled backend may still hold the lock — blocking
            # unbounded here would just stack dead threads
            if not self.acquire_save_lock("watchdog emergency save"):
                return
            try:
                save_to = save_checkpoint(
                    self.model_dir, diag.t_env, diag.state,
                    gather_retries=res.dispatch_retries,
                    gather_backoff_s=res.retry_backoff_s)
                log.warning(f"watchdog: emergency checkpoint saved to "
                            f"{save_to}")
            except Exception as e:  # noqa: BLE001 — device may be wedged
                log.warning(f"watchdog: emergency checkpoint failed "
                            f"({e!r}); resume falls back to the last "
                            f"cadence save")
            finally:
                self.save_lock.release()


def run(cfg: TrainConfig, logger: Optional[Logger] = None) -> TrainState:
    """Top-level entry (reference ``run``, ``per_run.py:20-66``): set up the
    unique token and sinks, then train (or evaluate and exit)."""
    logger = logger or Logger()
    cfg = sanity_check(cfg)
    token = unique_token(cfg)
    results_dir = os.path.join(cfg.local_results_path, token)
    if cfg.use_tensorboard:
        logger.setup_tb(os.path.join(
            cfg.local_results_path, "tb_logs", token))
    logger.setup_json(results_dir)
    logger.console_logger.info(f"Experiment token: {token}")

    # graftscope telemetry (docs/OBSERVABILITY.md): NULL_RECORDER when
    # obs.enabled is off — every span below is then a shared no-op
    # context and the driver is behaviorally identical to a build
    # without the obs layer. The Logger history cap applies regardless
    # (the unbounded self.stats growth was a bug, not a behavior).
    logger.max_history = cfg.obs.stats_history
    rec = obs_spans.make_recorder(cfg.obs, results_dir)
    # the first jax computation in the build triggers backend init —
    # the phase BENCH_r03–r05 died in with no telemetry trail
    with rec.span("backend.init"):
        exp = Experiment.build(cfg)
    # reference dispatch (per_run.py:192): save_animation alone does NOT
    # divert to evaluation — it enables the in-training animation cadence
    if cfg.evaluate or cfg.save_replay:
        rec.close()             # eval path records no further spans
        return evaluate_sequential(exp, logger, results_dir)
    return run_sequential(exp, logger, results_dir, rec=rec)


def run_sequential(exp: Experiment, logger: Logger,
                   results_dir: str,
                   rec=None) -> TrainState:
    """The train loop (reference ``run_sequential``, ``per_run.py:106-289``)."""
    cfg = exp.cfg
    log = logger.console_logger
    # graftscope span recorder (``run`` passes its own; direct callers —
    # tests, evaluate harnesses — get one from the config here)
    if rec is None:
        rec = obs_spans.make_recorder(cfg.obs, results_dir)
    if sebulba_eligible(cfg):
        # Sebulba decoupled actor/learner loop (docs/PERF.md): disjoint
        # device meshes + device-resident trajectory queue; its own loop
        # shape below — everything past this point is the fused/classic
        # single-set driver
        return run_sebulba(exp, logger, results_dir, rec=rec)
    env_info = exp.env.get_env_info()
    log.info(f"env_info: {env_info}")

    # ---- graftpop population axis (docs/POPULATION.md) -----------------
    # P > 0 vmaps the WHOLE train state over a leading (P,) axis and
    # drives the loop through ONE donated population superstep per
    # iteration — P seed/hyperparameter variants per dispatch. P = 0
    # (default) leaves every program and this loop byte-identical.
    from . import population as graftpop
    P = graftpop.population_size(cfg)
    spec = graftpop.build_spec(cfg) if P else None
    if P:
        log.info(f"graftpop: population of {P} members per dispatch "
                 f"(seeds {graftpop.member_seeds(cfg)}, "
                 f"pbt={'on' if cfg.population.pbt.enabled else 'off'})")

    # ---- graftpulse live telemetry plane (docs/OBSERVABILITY.md §pulse)
    # obs.pulse_port unset (default) leaves all three as no-op/None —
    # the loop below is byte-identical to a build without the plane
    pulse = obs_pulse.make_pulse(cfg.obs, rec=rec, log=log)
    mw = obs_memwatch.make_memwatch(cfg.obs, rec=rec)
    mw.snapshot("startup", t_env=0)
    trc = (obs_pulse.TraceController(
               results_dir, rec=rec,
               hub=pulse.hub if pulse is not None else None,
               n_iterations=cfg.profile_iterations)
           if (rec.enabled or pulse is not None) else None)
    # graftsight learning-health monitor (docs/OBSERVABILITY.md §6):
    # None when obs.sight is off — the loop below is byte-identical.
    # The in-graph half already rode the train programs; this is the
    # host detector pass over the log-cadence fetch. Under a population
    # the detectors run PER MEMBER over the (P,)-leading fetched leaves
    # and the /healthz verdicts name pop<i> (sight.PopulationSightMonitor).
    sight_mon = obs_sight.make_monitor(cfg.obs, logger=logger, rec=rec,
                                       population=P)

    # ---- data parallelism (SURVEY.md §7.2(6)) --------------------------
    # dp_devices > 0 swaps in the mesh-sharded program triple; the loop
    # below is identical either way (same pure functions, GSPMD shardings
    # come from input placement — parallel/mesh.py)
    dp = None
    pop_mesh = None
    if cfg.dp_devices and P:
        # population-over-dp (graftlattice): the mesh shards the LEADING
        # (P,) member axis — whole members per device, no cross-member
        # collectives — so the episode-axis DataParallel wrapper (and
        # its divisibility invariant) does not apply. GSPMD shardings
        # come from input placement exactly like classic dp: the stacked
        # state is device_put with population_shardings below and the
        # unchanged vmapped programs propagate the member axis.
        from .parallel import make_mesh, population_shardings
        pop_mesh = make_mesh(cfg.dp_devices)
        log.info(f"population-over-dp: {P} members sharded over "
                 f"{cfg.dp_devices} devices (mesh axis 'data', "
                 f"{P // cfg.dp_devices} members per device)")
    elif cfg.dp_devices:
        from .parallel import DataParallel, make_mesh
        dp = DataParallel(exp, make_mesh(cfg.dp_devices))
        log.info(f"data-parallel over {cfg.dp_devices} devices "
                 f"(mesh axis 'data')")
    # resolve the resume target FIRST: a checkpoint_path pointing at an
    # empty directory (the enable-resume-from-day-one pattern) is still a
    # fresh start and must take the born-sharded init below
    found = None
    if cfg.checkpoint_path:
        found = find_checkpoint(cfg.checkpoint_path, cfg.load_step)
        if found is None:
            log.info(f"no checkpoint found in {cfg.checkpoint_path}")
    if dp is not None and found is None:
        # fresh DP start: build the state BORN sharded (out_shardings) —
        # the single-device-then-reshard path holds a full extra copy of
        # the replay ring at startup, an OOM at config-5 ring sizes
        ts = dp.init_sharded(cfg.seed)
    elif dp is not None:
        # DP resume: restore each leaf straight onto the mesh — the
        # classic init → load → shard sequence re-creates the same
        # single-device ring transient the born-sharded init exists to
        # avoid (ADVICE r5). elastic.resume_state keeps the rigid
        # load_checkpoint_sharded path when the topology stamp matches
        # and routes population/topology changes through restore_elastic
        # (docs/RESILIENCE.md §6).
        shapes = jax.eval_shape(lambda: exp.init_train_state(cfg.seed))
        ts, _ = elastic.resume_state(found[0], shapes,
                                     dp.state_shardings(shapes),
                                     verify=False,
                                     topology={"loop": "classic"})
    elif P and found is None:
        # population init: P explicit solo inits stacked — member i's
        # leaves are bit-identical to a solo init at seed_i
        ts, spec = graftpop.init_population(exp, cfg)
    elif P:
        # population RESUME: an abstract template only — P concrete
        # inits here would materialize P replay rings just to be
        # discarded by the load below (the ADVICE-r5 init-then-load
        # transient, ×P). The spec stays concrete: a single-member
        # (v4) checkpoint lifting into this template takes its spec
        # from HERE (the config's grids), not from zero-filled avals.
        ts = jax.eval_shape(lambda: graftpop.init_population(exp, cfg))[0]
        spec = graftpop.build_spec(cfg)
    else:
        ts = exp.init_train_state(cfg.seed)
    # the driver loop replaces its state right after every call, so the
    # replay ring / train state can be donated (in-place on device)
    rollout, insert, train_iter = (dp or exp).jitted_programs(donate=True)

    # fused superstep (config.superstep, docs/SPEC.md §8): K > 1 swaps the
    # three-program iteration for ONE donated program scanning K rollout→
    # insert→train iterations per dispatch; the rollout program above
    # still serves the test/animation cadences. A population ALWAYS
    # drives through the (vmapped) fused program, even at K=1 — one
    # donated dispatch advances all P members. The builder is shared
    # with the degradation ladder's K→1 rung.
    def _build_superstep(k):
        if P:
            return exp.population_superstep_program(k, donate=True)
        return (dp or exp).superstep_program(k, donate=True)

    K = cfg.superstep if superstep_eligible(cfg) else 1
    pop_test = None
    if P:
        K = max(cfg.superstep, 1)
        superstep = _build_superstep(K)
        pop_test = exp.population_rollout_program()
        log.info(f"population superstep: {P} members x {K} iterations "
                 f"per dispatch")
    else:
        superstep = _build_superstep(K) if K > 1 else None
    if cfg.superstep > 1 and K == 1:
        log.info("superstep requested but ineligible (buffer_cpu_only "
                 "keeps the three-program path)")
    elif K > 1 and not P:
        log.info(f"fused superstep: {K} iterations per dispatch")
    # per-member driver key streams under a population (each member's
    # stream splits exactly like the classic loop's single one)
    key = graftpop.member_keys(cfg) if P else jax.random.PRNGKey(
        cfg.seed + 1)

    def _ckpt_state():
        """What checkpoints hold: the bare TrainState classically, the
        (state, spec) PopState under a population (the spec is
        PBT-mutable and must resume with the members it shaped)."""
        return graftpop.PopState(ts=ts, spec=spec) if P else ts

    t_env = 0
    # ---- resume (reference :159-189, Q13: t_env cursor restored) ----
    if found is not None:
        dirname, step = found
        if P:
            # population resume: the checkpoint is a PopState (or a
            # v4 single-member state the migration shim lifts to
            # P=stacked — utils/checkpoint._migrate_raw). A stamped
            # P-mismatch (grow/shrink since the save) routes through
            # restore_elastic via elastic.resume_state.
            ps, _ = elastic.resume_state(dirname, _ckpt_state(),
                                         verify=False,
                                         topology={"loop": "classic"})
            ts, spec = ps.ts, ps.spec
        elif dp is None:
            # find_checkpoint already hashed this candidate — skip
            # re-verify (the DP path restored sharded above)
            ts, _ = elastic.resume_state(dirname, ts, verify=False,
                                         topology={"loop": "classic"})
        t_env = step
        new_t = (jnp.full((P,), step, jnp.int32) if P
                 else jnp.asarray(step, jnp.int32))
        if dp is not None:
            # keep the canonical replicated placement — a fresh
            # single-device scalar here would hand the first dispatch a
            # different input aval than every later iteration
            new_t = jax.device_put(new_t, ts.runner.t_env.sharding)
        ts = ts.replace(runner=ts.runner.replace(t_env=new_t))
        log.info(f"resumed from {dirname} at t_env={step}")

    if pop_mesh is not None:
        # population-over-dp placement: shard every leaf (state AND
        # spec) on the leading member axis. Fresh and resumed states
        # both route through here — the single device_put is the whole
        # parallelization, because the vmapped programs are rank-
        # polymorphic over placement (GSPMD propagates the member
        # sharding through the batched graph). Members never
        # communicate: control state matches replication bit-exactly,
        # floats at ULP scale (partitioning retiles batched reduces —
        # see parallel/mesh.py population_shardings).
        ts = jax.device_put(ts, population_shardings(pop_mesh, ts))
        spec = jax.device_put(spec, population_shardings(pop_mesh, spec))

    model_dir = os.path.join(cfg.local_results_path, "models",
                             os.path.basename(results_dir))

    # ---- resilience (docs/RESILIENCE.md) -------------------------------
    res = cfg.resilience
    # SIGTERM/SIGINT → flag; the loop polls it once per iteration and
    # performs the orderly exit below (emergency checkpoint + exit 0)
    guard = (resilience.ShutdownGuard.install() if res.handle_signals
             else resilience.ShutdownGuard())
    nonfinite_streak = 0            # consecutive tripped train steps
    nonfinite_total = 0
    restores = 0                    # guard-triggered checkpoint restores
    # coordinated preemption (docs/RESILIENCE.md §6): once the guard
    # trips, every host negotiates ONE cut step (stop_at); stop_ok=False
    # means a peer died mid-negotiation and the exit path must degrade
    # to the per-host shard save (no collectives over a corpse)
    stop_at = None
    stop_ok = True

    def _save_topology():
        """The topology stamp every save carries (meta.json) — what a
        later resume compares its own shape against. The member ranking
        (best first, from the host-side EMA returns when every member
        has one) is what an elastic population SHRINK keeps."""
        topo = {"loop": "classic"}
        if dp is not None or pop_mesh is not None:
            topo["mesh_shape"] = [int(cfg.dp_devices)]
        if P:
            ema = getattr(train_acc, "member_return_ema", None)
            if ema and all(v is not None for v in ema):
                topo["member_ranking"] = sorted(
                    range(P), key=lambda m: ema[m], reverse=True)
        return topo

    # ---- hang detection + degradation ladder (RESILIENCE.md §5) --------
    # The watchdog's stall callback runs in the WATCHDOG thread — the main
    # thread is blocked inside the stalled call — so the emergency
    # checkpoint comes from the pre-dispatch state stamped with the
    # heartbeat: complete and consistent, because the dispatch that would
    # have superseded it never finished. A stall during the checkpoint
    # write itself skips the save (the staging directory is in use by the
    # stalled writer); donated-and-consumed state is skipped too (its
    # buffers are gone — resume falls back to the last cadence save).
    # serializes the watchdog thread's emergency save against the main
    # thread's cadence/exit saves: both stage into the same tmp.<t_env>
    # directory, and a bounded wd.stop() join can hand control back to
    # the main thread while the watchdog's save is still mid-write
    save_lock = threading.Lock()

    # graftlattice shared driver kit: the flight-persist, save-lock,
    # stall-response, watchdog-stamp and fault-handled-dispatch bodies
    # shared with run_sebulba (_DriverKit above) — bound to the local
    # names every call site (and graftlint GL110's name-keyed phase
    # check) keys on. The classic loop's shape: one armed watchdog
    # stamps every device-facing region by default, and the loop's own
    # t_env cursor threads into every stamp/span.
    kit = _DriverKit(cfg=cfg, res=res, log=log, rec=rec, mw=mw,
                     sight_mon=sight_mon, guard=guard,
                     model_dir=model_dir, save_lock=save_lock,
                     P=P, spec_fn=lambda: spec)
    kit.t_env_fn = lambda: t_env
    _persist_flight = kit.persist_flight
    _acquire_save_lock = kit.acquire_save_lock
    _on_stall = kit.stall_response

    wd = None
    if res.dispatch_timeout > 0:
        wd = watchdog.Watchdog(
            res.dispatch_timeout, on_stall=_on_stall,
            grace_s=res.stall_grace_s, exit_code=res.stall_exit_code,
            first_timeout_s=res.first_dispatch_timeout).start()
        log.info(f"dispatch watchdog armed: timeout="
                 f"{res.dispatch_timeout}s (first occurrence of each "
                 f"phase: {res.first_dispatch_timeout or 'unbounded'}, "
                 f"compile exemption), hard-exit grace="
                 f"{res.stall_grace_s}s (exit {res.stall_exit_code})")
    # arm the kit: bare _watched/_dispatch calls stamp this watchdog
    kit.default_wd = wd
    ladder = watchdog.DegradationLadder(res.max_restores)
    if pulse is not None:
        # live health/heartbeat surface: the watchdog rows are read per
        # scrape (visible while the main thread is wedged), and
        # /healthz flips to degraded the moment a stall fires or the
        # shutdown guard trips
        if wd is not None:
            pulse.wire_watchdog(wd)
        pulse.wire_guard(guard)
        pulse.set("superstep_k", K)
        pulse.set("backend_info", 1, backend=jax.default_backend())
        if sight_mon is not None:
            # one /healthz check per RL-health detector: the endpoint
            # flips 503 naming the verdict (sight-<detector>) the
            # moment the host pass trips it
            sight_mon.wire_pulse(pulse.hub)

    # one watchdog stamp + graftscope span per device-facing region
    # (_DriverKit.watched: wd-None guard, t_env threading, PopState
    # wrap, telemetry pairing — shared with run_sebulba)
    _watched = kit.watched

    last_test_t = t_env - cfg.test_interval - 1
    last_log_t = t_env
    last_save_t = t_env if t_env else -cfg.save_model_interval - 1
    start_time = last_time = time.time()
    last_log_time = None     # set at the first flush: the first window is
    # dominated by the rollout/train compiles (~30s+ on chip) and would
    # log a wildly-low throughput outlier
    start_t = last_T = t_env
    n_test_runs = max(1, cfg.test_nepisode // cfg.batch_size_run)
    # Q10 rounded quota; a population tests all P members per dispatch,
    # so the accumulator's total-episode quota scales by P
    test_quota = n_test_runs * cfg.batch_size_run * max(P, 1)
    train_infos = []
    # terminal-info stat accumulation (reference parallel_runner.py:202-231;
    # population=P adds the per-member pop<i>_* aggregation on the same
    # fold fetch — utils/stats.py)
    train_acc = StatsAccumulator(population=P)
    test_acc = StatsAccumulator(population=P)
    last_runner_log_t = t_env
    # in-training animation cadence (reference per_run.py:258-263)
    last_anim_t = -cfg.animation_interval - 1
    er_rs = None
    # tracing/profiling (SURVEY.md §5(1)): per-stage wall-clock into the
    # metric stream + optional jax.profiler trace window over the hot loop
    timer = StageTimer()
    if cfg.obs.program_trace:
        # graftscope device-time attribution: same trace window, plus a
        # post-stop parse mapping the captured events back to the
        # registry's named programs (device_ms_<prog> stats +
        # device_times.json for the report CLI)
        from .obs.device_time import ProgramTraceWindow
        tracer = ProgramTraceWindow(cfg.profile_dir, cfg.profile_start,
                                    cfg.profile_iterations,
                                    out_dir=results_dir)
    else:
        tracer = TraceWindow(cfg.profile_dir, cfg.profile_start,
                             cfg.profile_iterations)
    # run header for the report CLI: the shapes that scale graftprog's
    # audit-config budgets to this run (obs/report.py)
    if rec.enabled:
        from .envs.registry import scenario_config
        rec.mark("run", t_env=t_env, backend=jax.default_backend(),
                 batch_size_run=cfg.batch_size_run,
                 episode_limit=cfg.env_args.episode_limit,
                 batch_size=cfg.batch_size, superstep=K,
                 host_buffer=exp.host_buffer, population=P,
                 scenario=scenario_config(cfg.env_args).kind)
    # per-stage barriers for honest attribution; tracing implies them
    # (an un-synced trace window would capture dispatch, not execution)
    sync_stages = cfg.profile_stages or bool(cfg.profile_dir)

    # ---- async dispatch ------------------------------------------------
    # Every control scalar of this loop evolves deterministically: the
    # rollout scan always runs episode_limit slots (termination is
    # time-limit-only, envs/mec_offload.py step), so t_env advances by
    # exactly B·T per train rollout; the episode counter by B; the replay
    # ring fill by min(+B, capacity). Tracking them host-side removes
    # every blocking device→host fetch from the loop body — under the
    # axon remote tunnel one fetch is a ~0.66 s round-trip (BASELINE.md),
    # which would otherwise serialize the driver on the slowest link.
    # The loop then only blocks at its natural cadences (stat flush, log,
    # test, checkpoint), letting the host enqueue ahead of the device.
    steps_per_rollout = cfg.batch_size_run * cfg.env_args.episode_limit

    episode = _host_int(ts.episode)                    # restored on resume
    buffer_filled = (0 if exp.host_buffer else
                     _host_int(ts.buffer.episodes_in_buffer))
    buffer_capacity = 0 if exp.host_buffer else exp.buffer.capacity
    inflight = deque()              # rollout outputs not yet waited on

    # ---- fault-handled dispatch + ladder plumbing (RESILIENCE.md §5) ---
    # one device-facing dispatch: fault-injection hook + watchdog
    # heartbeat + bounded in-place retry (ladder rung 0) — shared body
    # in _DriverKit.dispatch; transient-failure counts accumulate in
    # kit.dispatch_faults for the log cadence below
    _dispatch = kit.dispatch

    def _restore_checkpoint(dirname, step):
        """Reload a published checkpoint and re-sync every host-side
        mirror of device state — shared by the non-finite escalation and
        the degradation ladder's restore rung."""
        nonlocal ts, t_env, episode, buffer_filled, train_infos
        nonlocal last_test_t, last_log_t, last_runner_log_t, last_save_t
        nonlocal nonfinite_streak, train_acc, spec
        if P:
            # population restore: the checkpoint holds a PopState; the
            # live ts only contributes structure/shape metadata
            ps = load_checkpoint(dirname, _ckpt_state(), verify=False)
            ts, spec = ps.ts, ps.spec
            new_t = jnp.full((P,), step, jnp.int32)
            if pop_mesh is not None:
                # re-shard onto the member axis (same placement as the
                # startup path — a single-device restore mid-run would
                # hand the next dispatch differently-placed inputs)
                ts = jax.device_put(ts, population_shardings(pop_mesh,
                                                             ts))
                spec = jax.device_put(
                    spec, population_shardings(pop_mesh, spec))
                new_t = jax.device_put(new_t,
                                       ts.runner.t_env.sharding)
        elif dp is not None:
            # same born-sharded restore as the resume path: the live ts
            # only contributes shape metadata (its donated leaves may
            # already be deleted), and the single-device load → shard
            # sequence would re-create the ring OOM mid-run (ADVICE r5)
            shapes = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), ts)
            ts = load_checkpoint_sharded(dirname, shapes,
                                         dp.state_shardings(shapes),
                                         verify=False)
            new_t = jax.device_put(jnp.asarray(step, jnp.int32),
                                   ts.runner.t_env.sharding)
        else:
            ts = load_checkpoint(dirname, ts, verify=False)
            new_t = jnp.asarray(step, jnp.int32)
        ts = ts.replace(runner=ts.runner.replace(t_env=new_t))
        # re-sync every host-side mirror of device state
        t_env = step
        episode = _host_int(ts.episode)
        if not exp.host_buffer:
            buffer_filled = _host_int(ts.buffer.episodes_in_buffer)
        inflight.clear()
        train_infos = []
        # the restored state predates whatever streak was counted — a
        # stale streak would double-count the replayed steps (the ladder
        # restore shares this path, not just the non-finite escalation)
        nonfinite_streak = 0
        # drop pending stats too: their device refs belong to the
        # rolled-back (possibly poisoned) computation, and the replayed
        # iterations will re-push them — flushing the stale ones would
        # both double-count episodes and re-raise the fault at the next
        # cadence fetch, outside any routing. The fetch tally survives
        # the reset: stat_fetches is logged as a cumulative round-trip
        # counter and must not go backwards across a restore
        fetches = train_acc.fetches
        train_acc = StatsAccumulator(population=P)
        train_acc.fetches = fetches
        if exp.host_buffer:
            # same hazard for the host-replay deferred priority refs:
            # they came from the rolled-back train step
            exp.buffer.drop_pending_update()
        last_test_t = last_log_t = t_env
        last_runner_log_t = last_save_t = t_env

    def _dispatch_ladder(df: watchdog.DispatchFailed,
                         can_degrade: Optional[bool] = None) -> None:
        """Rungs above in-place retry: superstep K→1 (smaller blast
        radius), restore the last good checkpoint, abort with the
        captured diagnosis. Mutates the loop shape; callers ``continue``
        after it returns (their host mirrors were never committed, so the
        abandoned dispatch leaves no trace). Pass ``can_degrade=False``
        from boundaries where degrading cannot help — a failure surfacing
        at a sync/fetch point means the already-dispatched computation
        (or its output state) is suspect, so only restore can stand."""
        nonlocal K, superstep
        # a dispatch whose donated inputs were consumed mid-failure left
        # ts unusable — degrading and continuing would dereference
        # deleted arrays; only the restore rung can stand on it (the
        # deleted leaves still carry shape metadata, which is all the
        # load_checkpoint template needs)
        if can_degrade is None:
            can_degrade = (K > 1 and res.degrade_superstep
                           and watchdog.state_intact(ts))
        action = ladder.next_action(can_degrade=can_degrade)
        logger.log_stat("dispatch_failures", ladder.failures, t_env)
        # ladder actions are span-stream events too: the flight tail
        # then shows retry exhaustion -> rung taken in causal order
        rec.mark("ladder", action=action, phase=df.phase, t_env=t_env,
                 failures=ladder.failures)
        if action == "degrade":
            log.warning(f"degradation ladder: {df} — falling back "
                        f"superstep K={K} -> 1 ({ladder.describe()})")
            K = 1
            # a population still drives through the (vmapped) fused
            # program — rebuild it at K=1 instead of dropping to the
            # three-program path, which has no population rank
            superstep = _build_superstep(1) if P else None
            logger.log_stat("superstep_k", 1, t_env)
            return
        if action == "restore":
            good = find_checkpoint(model_dir) if cfg.save_model else None
            if good is not None:
                log.warning(f"degradation ladder: {df} — restoring last "
                            f"good checkpoint {good[0]} "
                            f"({ladder.describe()})")
                _restore_checkpoint(*good)
                return
            # no checkpoint to stand on: fall through to abort
        # abort rung: persist the flight tail next to the checkpoints
        # (the stall-diagnosis merge covers hangs; this covers failures)
        _persist_flight(os.path.join(model_dir, "flight_recorder.json"))
        # consume the stall diagnosis only on abort: a degrade/restore
        # rung leaves it for the guard-triggered exit log (the causal
        # "stalled call eventually returned" chain) or a later abort
        diag = wd.take_diagnosis() if wd is not None else None
        raise RuntimeError(
            f"dispatch failure exhausted the degradation ladder at "
            f"t_env={t_env} ({ladder.describe()})"
            + (f"; stall diagnosis: {diag.message()}" if diag else "")
            + ("" if cfg.save_model else
               "; no checkpoints exist to restore (save_model off)")
            + f" — last failure: {df}") from df

    def _sync_point(phase, fn):
        """One blocking sync/fetch boundary (run-ahead wait, cadence
        stat fetch) — shared body in ``_DriverKit.sync_point``. Stays a
        local def (not a bare bound method) so the stamp always carries
        the loop's CURRENT ``ts``: the state local is rebound across
        restores and donated dispatches, and an early capture would
        stamp deleted buffers."""
        return kit.sync_point(phase, fn, ts)

    # signal handlers are process-global state: restore them on
    # EVERY exit (normal, preemption, divergence abort)
    try:
        while t_env <= cfg.t_max:
            # fault-injection hook + preemption poll (docs/RESILIENCE.md):
            # the signal handler only sets a flag; the orderly exit —
            # emergency checkpoint, resume hint, exit 0 — happens here, at an
            # iteration boundary where ts is a complete consistent state.
            # Under superstep K>1 this is a DISPATCH boundary: the poll,
            # every cadence, and every checkpoint land between fused
            # dispatches, so a preemption loses at most K iterations and a
            # restored checkpoint always resumes at a K-aligned t_env
            resilience.fire("driver.iteration", t_env=t_env, guard=guard)
            # coordinated preemption (docs/RESILIENCE.md §6): propagate a
            # PEER's announced shutdown into the local guard, then
            # negotiate the one cut step all hosts share. Hosts behind
            # the consensus keep stepping (lockstep dp trajectories make
            # every host's t_env reach stop_at) so the collective
            # emergency save below runs at one t_env on every host.
            if not guard.triggered and dist.peer_shutdown_requested():
                guard.request("peer")
            if guard.triggered:
                if stop_at is None:
                    dist.announce_shutdown(t_env)
                    with rec.span("preempt.barrier", t_env=t_env):
                        stop_at, stop_ok = dist.negotiate_stop_step(
                            t_env, res.preempt_barrier_timeout_s)
                if not stop_ok or t_env >= stop_at:
                    break
            if pulse is not None:
                pulse.tick_iteration(t_env, episode)
            if trc is not None:
                # on-demand trace trigger (PULSE_TRACE file / /trace
                # endpoint): one os.path.exists when idle
                trc.poll(t_env)
            tracer.maybe_start(t_env)
            if superstep is not None:
                # ------------ fused superstep (one dispatch = K iters) ------
                # mirror the control scalars host-side for each of the K
                # sub-iterations: they evolve deterministically (see the
                # async-dispatch note above), so the host knows exactly
                # which sub-iterations train — it splits the driver key
                # stream ONLY for those (bit-identical threading to the
                # K=1 loop's conditional split) and keeps their stacked
                # info rows, dropping the zero rows of skipped ones.
                # Computed from snapshots and COMMITTED only after the
                # dispatch succeeds: an in-place retry (or a ladder rung
                # abandoning this dispatch) replays the identical key
                # stream, preserving bit-parity with the K=1 loop.
                # Under a population (P > 0) `key` is a LIST of P member
                # streams: the gate mirror is computed ONCE (the
                # counters evolve identically across members) and each
                # member's stream splits exactly like the classic
                # loop's single one — member 0's consumed stream IS the
                # solo run's, the bit-parity contract.
                ep2, fill2 = episode, buffer_filled
                key2 = list(key) if P else key
                key_rows, gated = [], []
                for _ in range(K):
                    ep2 += cfg.batch_size_run
                    fill2 = min(fill2 + cfg.batch_size_run,
                                buffer_capacity)
                    g = (fill2 >= cfg.batch_size
                         and ep2 >= cfg.accumulated_episodes)
                    gated.append(g)
                    if P:
                        if g:
                            row = []
                            for m in range(P):
                                key2[m], k_s = jax.random.split(key2[m])
                                row.append(k_s)
                            key_rows.append(jnp.stack(row))
                        else:
                            key_rows.append(jnp.zeros(
                                (P,) + key2[0].shape, key2[0].dtype))
                    elif g:
                        key2, k_sample = jax.random.split(key2)
                        key_rows.append(k_sample)
                    else:
                        key_rows.append(jnp.zeros_like(key2))
                def _fused(ts=ts, key_rows=key_rows):
                    if P:
                        # (P, K, 2) — the vmapped program maps axis 0,
                        # each member scanning its own (K,) key rows
                        keys = jnp.stack(key_rows, axis=1)
                        if pop_mesh is not None:
                            # member-axis placement for the key stack
                            # too: the dispatched program must see the
                            # same input shardings as the audited
                            # pop_dp_superstep twin (a replicated key
                            # input would lower a different SPMD
                            # program than the one ratcheted)
                            keys = jax.device_put(
                                keys, population_shardings(pop_mesh,
                                                           keys))
                        ts2, stats, infos = superstep(
                            ts, keys, jnp.asarray(t_env), spec)
                    else:
                        ts2, stats, infos = superstep(
                            ts, jnp.stack(key_rows), jnp.asarray(t_env))
                    if sync_stages:
                        # inside the dispatched fn so the barrier (where
                        # a device-side wedge actually surfaces) is
                        # covered by the watchdog stamp + retry, like
                        # _roll/_train_once below
                        jax.block_until_ready(stats.epsilon)
                    return ts2, stats, infos
                try:
                    with timer.stage("superstep"):
                        ts, stats, infos = _dispatch("dispatch.superstep",
                                                     _fused, ts, k=K)
                except watchdog.DispatchFailed as df:
                    _dispatch_ladder(df)
                    continue
                key, episode, buffer_filled = key2, ep2, fill2
                t_env += K * steps_per_rollout
                for i, g in enumerate(gated):
                    if g:
                        # population infos carry the leading (P,) member
                        # axis; the scan's (K,) axis is the next one
                        train_infos.append(jax.tree.map(
                            (lambda x, i=i: x[:, i]) if P
                            else (lambda x, i=i: x[i]), infos))
            else:
                # ------------ rollout (no grad by construction) -------------
                def _roll(ts=ts):
                    rs, batch, stats = rollout(ts.learner.params["agent"],
                                               ts.runner, test_mode=False)
                    ts = ts.replace(runner=rs,
                                    buffer=insert(ts.buffer, batch),
                                    episode=ts.episode + cfg.batch_size_run)
                    if sync_stages:
                        jax.block_until_ready(rs.t_env)
                    return ts, stats
                try:
                    with timer.stage("rollout"):
                        # host-buffer rollouts insert into host RAM
                        # inside fn — not replayable in place
                        ts, stats = _dispatch("dispatch.rollout", _roll,
                                              ts,
                                              retryable=not exp.host_buffer)
                except watchdog.DispatchFailed as df:
                    _dispatch_ladder(df)
                    continue
                t_env += steps_per_rollout
                episode += cfg.batch_size_run
                buffer_filled = min(buffer_filled + cfg.batch_size_run,
                                    buffer_capacity)

                # ------------ train gate (reference :220-238) ---------------
                if exp.host_buffer:
                    can = exp.buffer.can_sample(cfg.batch_size)
                else:
                    can = buffer_filled >= cfg.batch_size
                if can and episode >= cfg.accumulated_episodes:
                    key2, k_sample = jax.random.split(key)

                    # NB: not named `_train` — graftlint's traced-region
                    # discovery is name-keyed per module, and `_train` is
                    # the lax.cond branch inside superstep_program
                    def _train_once(ts=ts, k_sample=k_sample):
                        ts, info = train_iter(ts, k_sample,
                                              jnp.asarray(t_env))
                        if sync_stages:
                            jax.block_until_ready(info["loss"])
                        return ts, info
                    try:
                        with timer.stage("train"):
                            # host-buffer sampling advances the host RNG
                            # inside fn — not replayable in place
                            ts, info = _dispatch(
                                "dispatch.train", _train_once, ts,
                                retryable=not exp.host_buffer)
                    except watchdog.DispatchFailed as df:
                        _dispatch_ladder(df)
                        continue
                    key = key2
                    train_infos.append(info)
            # shared accounting for both loop shapes: ONE stats push per
            # dispatch (per-rollout (B,) or stacked (K, B) — the
            # accumulator flattens), then the dispatch run-ahead bound:
            # block on the dispatch from two back (TPU executes in
            # dispatch order, so this caps live episode batches while
            # still double-buffering host↔device)
            # the accumulator push folds with a blocking device fetch
            # every FOLD_EVERY rollouts — a sync point like any other
            try:
                _sync_point("fetch.train_stats",
                            lambda: train_acc.push(stats))
            except watchdog.DispatchFailed as df:
                _dispatch_ladder(df, can_degrade=False)
                continue
            inflight.append(stats.epsilon)
            if len(inflight) > 2:
                # the steady-state blocking point of the async loop: a
                # device-side wedge surfaces HERE, not at the dispatch
                # call
                try:
                    _sync_point("dispatch.wait",
                                lambda: jax.block_until_ready(
                                    inflight.popleft()))
                except watchdog.DispatchFailed as df:
                    _dispatch_ladder(df, can_degrade=False)
                    continue
            tracer.tick(logger, t_env)
            if trc is not None:
                trc.tick(logger, t_env)

            # train-stat cadence: runner_log_interval, epsilon alongside
            # (reference parallel_runner.py:215-219). Deliberately after the
            # train dispatch: at configs where B·T ≥ the interval this flush
            # fires every iteration, and its blocking stat fetch then overlaps
            # the already-enqueued train step instead of serializing it.
            if t_env - last_runner_log_t >= cfg.runner_log_interval:
                def _flush_train_stats():
                    train_acc.flush(logger, t_env)
                    # cached by the flush's own fold — no second fetch
                    logger.log_stat("epsilon", train_acc.epsilon, t_env)
                try:
                    _sync_point("fetch.train_stats", _flush_train_stats)
                except watchdog.DispatchFailed as df:
                    _dispatch_ladder(df, can_degrade=False)
                    continue
                last_runner_log_t = t_env

            # ---------------- test cadence (reference :240-256) ----------------
            if (t_env - last_test_t) / cfg.test_interval >= 1.0:
                log.info(f"t_env: {t_env} / {cfg.t_max}")
                log.info(
                    f"Estimated time left: "
                    f"{time_left(last_time, last_T, t_env, cfg.t_max)}. "
                    f"Time passed: {time_str(time.time() - start_time)}")
                last_time, last_T = time.time(), t_env

                try:
                    with timer.stage("test"):
                        for _ in range(n_test_runs):
                            # one _dispatch per rollout (stamp + hook +
                            # retry) — a single stamp spanning all
                            # n_test_runs (plus the flush's sink I/O)
                            # would overrun a per-dispatch-sized timeout
                            # on a perfectly healthy test cadence
                            def _test_roll(ts=ts):
                                if P:
                                    # vmapped greedy rollout: every
                                    # member evaluates in the SAME
                                    # dispatch (the population cost
                                    # profile — never P fetches)
                                    return pop_test(
                                        ts.learner.params["agent"],
                                        ts.runner)
                                rs, _, s = rollout(
                                    ts.learner.params["agent"], ts.runner,
                                    test_mode=True)
                                return rs, s
                            rs, s = _dispatch("dispatch.test", _test_roll,
                                              ts)
                            if pop_mesh is not None:
                                # pin the runner back to the member-axis
                                # placement (no-op when GSPMD already
                                # propagated it): the next superstep's
                                # input shardings must not drift with
                                # XLA's output-sharding choices
                                rs = jax.device_put(
                                    rs, population_shardings(pop_mesh,
                                                             rs))
                            ts = ts.replace(runner=rs)
                            # the push's periodic device fold is a
                            # blocking fetch like the train-side one —
                            # stamped + routed the same way (its
                            # DispatchFailed lands in the except below)
                            _sync_point("fetch.test_stats",
                                        lambda s=s: test_acc.push(s))
                            # Q10: flush only on the exact rounded quota
                            # (the flush fetch is a sync point; its
                            # DispatchFailed lands in the except below)
                            if test_acc.n_episodes == test_quota:
                                _sync_point(
                                    "fetch.test_stats",
                                    lambda: test_acc.flush(logger, t_env,
                                                           prefix="test_"))
                except watchdog.DispatchFailed as df:
                    # drop the partial cadence: a leftover sub-quota
                    # accumulation would miss the exact-quota flush on
                    # every later cadence; degrading can't help a test
                    # rollout, only restore can
                    test_acc = StatsAccumulator(population=P)
                    _dispatch_ladder(df, can_degrade=False)
                    continue
                last_test_t = t_env

            # ---------------- animation cadence (reference :258-263) -----------
            if (cfg.save_animation
                    and (t_env - last_anim_t) / cfg.animation_interval >= 1.0):
                er = exp.episode_runner
                if er_rs is None:
                    er_rs = er.init_state(jax.random.PRNGKey(cfg.seed + 3))
                er_rs, _, _, traj = er.run(ts.learner.params["agent"], er_rs,
                                           test_mode=True,
                                           capture_trajectory=True)
                p = er.save_animation(
                    traj, os.path.join(results_dir, f"animation_{t_env}.gif"))
                if p:
                    log.info(f"animation saved to {p}")
                last_anim_t = t_env

            # ---------------- save cadence (reference :265-279) ----------------
            if cfg.save_model and (t_env - last_save_t) >= cfg.save_model_interval:
                # watchdog covers the (possibly multi-host-collective)
                # write; transient gather/filesystem faults retry with
                # backoff — deterministic errors still propagate. The
                # stamp wraps EACH attempt, not the whole retry loop: a
                # dispatch_timeout sized for one save must not be eaten
                # by attempt 1's failure + backoff sleep and then
                # misdiagnose a succeeding attempt 2 as a stall
                def _save_once():
                    with _watched("checkpoint.save", ts):
                        # this cadence runs in the tail of the very
                        # iteration whose stall fired it (the guard poll
                        # at the loop top comes later) — the watchdog's
                        # emergency save may well hold the lock
                        if not _acquire_save_lock("save cadence"):
                            return None
                        try:
                            # population checkpoints hold the PopState
                            # (stacked state + the PBT-mutable spec)
                            return save_checkpoint(
                                model_dir, t_env, _ckpt_state(),
                                gather_retries=res.dispatch_retries,
                                gather_backoff_s=res.retry_backoff_s,
                                topology=_save_topology())
                        finally:
                            save_lock.release()
                # retry only single-process: in multi-host the save is a
                # lockstep collective sequence, and a ONE-SIDED transient
                # failure (say process 0's file write) retried on that
                # process alone would re-enter barriers its peers already
                # left — deadlock or a cross-step checkpoint. Symmetric
                # transport faults are retried one level down (the
                # per-leaf allgather in utils/checkpoint.py, in lockstep).
                save_to = watchdog.retry_call(
                    _save_once,
                    attempts=(1 + res.dispatch_retries
                              if jax.process_count() == 1 else 1),
                    backoff_s=res.retry_backoff_s,
                    label="checkpoint.save")
                if save_to is not None:
                    log.info(f"Saving models to {save_to}")
                    if res.keep_last:
                        prune_checkpoints(model_dir, res.keep_last,
                                          res.keep_every)
                    # checkpoint gather is a transient-HBM event worth
                    # a memwatch boundary of its own (no-op when off)
                    mw.snapshot("checkpoint.save", t_env=t_env)
                    # advance the cadence only on a real save: a
                    # lock-skipped attempt (None) retries next iteration
                    # instead of silently widening the data-loss window
                    # by a full save interval right after a stall event
                    last_save_t = t_env
                    if P and cfg.population.pbt.enabled:
                        # PBT exploit/explore (docs/POPULATION.md): at
                        # save boundaries ONLY, after the save — the
                        # published checkpoint holds the pre-PBT
                        # population, so a restored run is self-
                        # consistent (it re-warms the host-side
                        # ranking EMA from fresh flushes and may no-op
                        # this boundary rather than replay it — the
                        # EMA is deliberately not checkpointed). The
                        # ranking signal is the accumulator's
                        # per-member return EMA (riding the existing
                        # fold fetch — the only device work here is
                        # pbt_step's one gather when members copy).
                        ts, spec, pbt_info = graftpop.pbt_step(
                            cfg, ts, spec,
                            train_acc.member_return_ema, t_env)
                        if pbt_info is not None:
                            logger.log_stat("pbt_copies",
                                            len(pbt_info["copied"]),
                                            t_env)
                            rec.mark("pbt", t_env=t_env, **pbt_info)
                            log.info(f"graftpop PBT: exploited "
                                     f"{pbt_info['copied']} at "
                                     f"t_env={t_env}")

            # ---------------- log cadence (reference :283-286) ------------------
            if (t_env - last_log_t) >= cfg.log_interval:
                if train_infos:
                    # non-finite guard escalation: ONE blocking fetch for all
                    # flags since the last cadence — the async dispatch
                    # pipeline never syncs per train step. Deliberately after
                    # the save cadence: the checkpoint written just above
                    # (params finite by construction — tripped steps are
                    # no-ops) is the state the restore wants.
                    # ONE stamped region for the whole cadence fetch
                    # (flags + the last info row): a wedge surfacing at
                    # either device_get must fire the watchdog, and a
                    # transient error routes through the ladder (the
                    # fetched-from state is suspect — restore only)
                    def _fetch_infos():
                        flags = np.asarray(jax.device_get(
                            [i["all_finite"] for i in train_infos]))
                        return flags, jax.device_get(train_infos[-1])
                    try:
                        flags, last = _sync_point("fetch.train_infos",
                                                  _fetch_infos)
                    except watchdog.DispatchFailed as df:
                        _dispatch_ladder(df, can_degrade=False)
                        continue
                    if P:
                        # (n, P) member flags: a train step counts as
                        # finite only when EVERY member's update was —
                        # one poisoned member is a restore-worthy event
                        # exactly like a solo NaN (the stacked state is
                        # one checkpoint)
                        flags = flags.reshape(len(train_infos), -1)\
                                     .all(axis=1)
                    for ok in flags:
                        if ok:
                            nonfinite_streak = 0
                        else:
                            nonfinite_streak += 1
                            nonfinite_total += 1
                    if not flags.all():
                        logger.log_stat("nonfinite_steps", nonfinite_total,
                                        t_env)
                        # non-finite trip: event + flight persist, so a
                        # later divergence abort has the phase history
                        # leading up to the first trip on disk already
                        rec.mark("nonfinite", t_env=t_env,
                                 streak=nonfinite_streak,
                                 total=nonfinite_total)
                        _persist_flight(os.path.join(
                            results_dir, "flight_recorder.json"))
                        log.warning(
                            f"non-finite loss/grads in "
                            f"{int((~flags).sum())}/{len(flags)} train steps "
                            f"since last log (streak={nonfinite_streak}, "
                            f"total={nonfinite_total}); parameter updates "
                            f"were skipped")
                    for k in ("loss", "grad_norm", "td_error_abs",
                              "q_taken_mean", "target_mean"):
                        if P:
                            # aggregate row = population mean; per-
                            # member rows (pop<i>_*) only at P > 1 so a
                            # P=1 run keeps the solo metric stream
                            v = np.asarray(last[k], np.float64)
                            logger.log_stat(k, float(v.mean()), t_env)
                            if P > 1:
                                for m in range(P):
                                    logger.log_stat(f"pop{m}_{k}",
                                                    float(v[m]), t_env)
                        else:
                            logger.log_stat(k, float(last[k]), t_env)
                    if sight_mon is not None:
                        # graftsight detector pass over the SAME fetched
                        # info (no extra device traffic; the monitor
                        # logs the sight_* stats at full fidelity). A
                        # fresh trip persists the flight ring like a
                        # non-finite trip does — the post-mortem then
                        # carries the verdict even if the run dies later
                        with rec.span("sight.detect", t_env=t_env):
                            trips = sight_mon.observe(last, t_env)
                        if trips:
                            log.warning(
                                f"graftsight: detector(s) tripped at "
                                f"t_env={t_env}: {', '.join(trips)} — "
                                f"/healthz degraded; run `python -m "
                                f"t2omca_tpu.obs learning "
                                f"{results_dir}` for the read")
                            _persist_flight(os.path.join(
                                results_dir, "flight_recorder.json"))
                    train_infos = []
                    if (res.nonfinite_tolerance
                            and nonfinite_streak >= res.nonfinite_tolerance):
                        found = (find_checkpoint(model_dir)
                                 if cfg.save_model else None)
                        if found is None or restores >= res.max_restores:
                            raise RuntimeError(
                                f"training diverged: {nonfinite_streak} "
                                f"consecutive non-finite train steps at "
                                f"t_env={t_env} (last loss="
                                f"{float(np.mean(last['loss']))}, grad_norm="
                                f"{float(np.mean(last['grad_norm']))}), and "
                                + (f"restore limit reached (resilience."
                                   f"max_restores={res.max_restores})"
                                   if found is not None else
                                   "no valid checkpoint exists to restore "
                                   "(save_model off or none written yet)")
                                + " — the NaN source is persistent; inspect "
                                "lr/grad_norm_clip/td_loss before rerunning")
                        dirname, step = found
                        log.warning(
                            f"non-finite streak hit resilience."
                            f"nonfinite_tolerance={res.nonfinite_tolerance}; "
                            f"restoring last good checkpoint {dirname} "
                            f"(restore {restores + 1}/{res.max_restores})")
                        _restore_checkpoint(dirname, step)
                        restores += 1
                        nonfinite_streak = 0
                        continue
                if kit.dispatch_faults:
                    # ladder visibility: cumulative transient dispatch
                    # errors (in-place retries included); per-escalation
                    # counters land in _dispatch_ladder as they happen
                    logger.log_stat("dispatch_faults",
                                    kit.dispatch_faults, t_env)
                if rec.enabled:
                    # device-fetch accounting (utils/stats.py): how many
                    # blocking stat round-trips the cadences have cost
                    logger.log_stat("stat_fetches",
                                    train_acc.fetches + test_acc.fetches,
                                    t_env)
                logger.log_stat("episode", episode, t_env)
                # wall-clock throughput including everything (train, logging,
                # cadences) — the honest live rate; the async loop makes the
                # per-stage timings dispatch-enqueue times unless
                # profile_stages is on
                now = time.time()
                if last_log_time is not None:
                    rate = ((t_env - last_log_t)
                            / max(now - last_log_time, 1e-9))
                    logger.log_stat("env_steps_per_sec", rate, t_env)
                    if pulse is not None:
                        pulse.set("env_steps_per_sec", rate)
                last_log_time = now
                # memwatch phase boundary + the live-plane cadence
                # gauges (both no-ops when the plane is off)
                pulse_snap = mw.snapshot("log", t_env=t_env)
                if pulse is not None:
                    pulse.set("nonfinite_streak", nonfinite_streak)
                    pulse.set("nonfinite_total", nonfinite_total)
                    pulse.set("dispatch_faults", kit.dispatch_faults)
                    pulse.set("ladder_failures", ladder.failures)
                    pulse.set("restores", restores)
                    pulse.set("superstep_k", K)
                    pulse.set_memwatch(pulse_snap)
                timer.log_and_reset(logger, t_env)
                logger.print_recent_stats()
                last_log_t = t_env

    except BaseException as e:
        # crash path: leave the same causal trail a stall does — the
        # flight tail with the failing span's phase/outcome last
        # (best-effort no-ops when telemetry is off; never masks ``e``)
        rec.mark("crash", t_env=t_env,
                 error=f"{type(e).__name__}: {e}"[:300])
        _persist_flight(os.path.join(results_dir, "flight_recorder.json"))
        rec.close()                     # flush the spans.jsonl tail too
        raise
    finally:
        # stop the watchdog FIRST: the hard-exit grace timer must not be
        # able to kill the process while the orderly emergency checkpoint
        # below is being written
        if wd is not None:
            wd.stop()
        guard.uninstall()
        if pulse is not None:
            pulse.close()               # bounded; never hangs the exit

    if guard.triggered:
        # ---- preemption path: lose at most one iteration ---------------
        # SIGTERM (or watchdog guard trip) is a flight-persist trigger:
        # the preempted run's last phases survive even if the emergency
        # checkpoint below cannot be written
        rec.mark("shutdown", t_env=t_env, signame=guard.signame or "")
        _persist_flight(os.path.join(results_dir, "flight_recorder.json"))
        stall = wd.take_diagnosis() if wd is not None else None
        if stall is not None:
            log.warning(f"watchdog: {stall.message()} — the stalled call "
                        f"eventually returned; exiting with the diagnosis "
                        f"persisted to {model_dir}/stall_diagnosis.json")
        log.warning(f"shutdown requested ({guard.signame}) at "
                    f"t_env={t_env} — stopping gracefully")
        if cfg.save_model and res.emergency_checkpoint:
            # a watchdog-thread emergency save may still be mid-write if
            # wd.stop()'s bounded join gave up on it — both stage into
            # the same tmp.<t_env> directory, and an unbounded wait
            # would hang the exit forever (the watchdog and its grace
            # timer are already stopped)
            if _acquire_save_lock("preemption exit"):
                save_to = None
                # the watchdog and its grace timer are stopped, so this
                # save is the one device-facing call left with no bound:
                # wedged device→host fetches block without raising and
                # retry_call only bounds failures — arm a hard deadline
                # (watchdog-armed runs only: dispatch_timeout unset
                # keeps today's behavior) so a wedged backend costs the
                # stall exit code, not a silent forever-hang in the
                # exit path
                deadline = (watchdog.ExitDeadline(
                                max(res.stall_grace_s, 60.0),
                                res.stall_exit_code,
                                label="preemption-exit emergency "
                                      "checkpoint")
                            if wd is not None else nullcontext())
                try:
                    with deadline:
                        if stop_ok:
                            # same single-process-only retry policy as
                            # the cadence save (a one-sided retry of the
                            # lockstep multi-host collective would
                            # deadlock its peers) — and an orderly
                            # preemption exit must STAY orderly: a save
                            # that still fails degrades to the per-host
                            # shard save below instead of turning the
                            # exit-0 resume hint into a crash
                            try:
                                save_to = watchdog.retry_call(
                                    lambda: save_checkpoint(
                                        model_dir, t_env, _ckpt_state(),
                                        gather_retries=res.dispatch_retries,
                                        gather_backoff_s=res.retry_backoff_s,
                                        topology=_save_topology()),
                                    attempts=(1 + res.dispatch_retries
                                              if jax.process_count() == 1
                                              else 1),
                                    backoff_s=res.retry_backoff_s,
                                    label="checkpoint.emergency")
                            except Exception:  # noqa: BLE001
                                log.exception(
                                    "collective emergency checkpoint "
                                    "failed (a peer died mid-gather?) — "
                                    "degrading to the per-host shard "
                                    "save")
                        if save_to is None:
                            # degraded exit (docs/RESILIENCE.md §6): the
                            # peer barrier failed or the collective save
                            # died — write THIS host's addressable shard
                            # only (no collectives, cannot hang on a
                            # dead peer); restore_elastic reassembles
                            # the set, find_checkpoint skips it unless
                            # every shard landed
                            with rec.span("checkpoint.shard_save",
                                          t_env=t_env):
                                save_to = save_checkpoint_shards(
                                    model_dir, t_env, _ckpt_state(),
                                    topology=_save_topology())
                except Exception:  # noqa: BLE001 — exit path stays orderly
                    log.exception(
                        "emergency checkpoint failed on the preemption "
                        "exit — resume falls back to the newest "
                        "published checkpoint")
                finally:
                    save_lock.release()
                if save_to is not None:
                    if res.keep_last:
                        prune_checkpoints(model_dir, res.keep_last,
                                          res.keep_every)
                    log.info(f"emergency checkpoint saved to {save_to}")
        log.info(f"resume with checkpoint_path={model_dir} (newest valid "
                 f"step selected automatically)")
    else:
        log.info("Finished Training")
    rec.close()
    return ts


def run_sebulba(exp: Experiment, logger: Logger, results_dir: str,
                rec=None) -> TrainState:
    """The Sebulba decoupled train loop (ROADMAP item 2, docs/PERF.md §
    decoupled pipeline): rollout and training on DISJOINT device sets
    with a bounded device-resident trajectory queue between them, so
    neither phase idles the other's devices.

    Two host threads orchestrate dispatches (no value ever comes to
    host except at the same cadences the classic loop syncs at):

    * the **actor thread** runs ``actor_step`` (the shared ``run_raw``
      rollout definition) on the actor mesh, pushes each time-major
      emission into the queue (``queue.put`` — an async device-to-device
      copy + one scatter per leaf into the slot ring), adopts freshly
      published params under the ``sebulba.staleness`` bound
      (``params.sync``), and owns the test cadence (it owns the rollout
      program and the runner state, exactly like the classic loop's
      shared-runner test rollouts);
    * the **learner (main) thread** consumes batches (``queue.get`` —
      slot gather scattered straight into the replay ring via
      ``insert_time_major``), mirrors the train gate host-side and
      splits the key stream EXACTLY like the classic loop, trains
      (``learner.dispatch``), publishes params back to the actor mesh,
      and owns the log/save cadences, the non-finite escalation, the
      degradation ladder and every exit path.

    Failure routing: both threads route dispatches through the
    watchdog-stamped retry helper (each thread has its OWN watchdog —
    one armed stamp per instance); exhausted retries and actor-thread
    failures land in the shared ladder, whose rungs here are restore
    (tear down the actor thread, reload the newest checkpoint, restart
    a fresh epoch) and abort — there is no superstep to degrade.
    A stall on either side writes the diagnosis and trips the
    ShutdownGuard, so a wedged learner dispatch still ends with the
    actor thread exiting and a resumable checkpoint on disk
    (tests/test_sebulba.py chaos scenario).

    Lockstep mode (``queue_slots=1, staleness=0``) serializes
    rollout→insert→train exactly like the classic K=1 loop and is
    bit-identical to it (pinned by test on a forced multi-device CPU
    host)."""
    cfg = exp.cfg
    sb = cfg.sebulba
    log = logger.console_logger
    if rec is None:
        rec = obs_spans.make_recorder(cfg.obs, results_dir)

    # ---- graftpop population axis over the decoupled loop ---------------
    # (graftlattice, docs/POPULATION.md §composition): P > 0 stacks a
    # leading (P,) member axis onto BOTH halves of the split state and
    # vmaps every sebulba program over it (parallel/sebulba.py). Only
    # lockstep queues are legal (sanity_check): the queue serializes
    # rollout→insert→train exactly like the classic population loop, so
    # the host loop below needs no per-member control flow — counters,
    # gates and cadences mirror member 0 (every member's control
    # counters evolve identically; _host_int).
    from . import population as graftpop
    P = graftpop.population_size(cfg)
    # graftpulse plane (same off-state contract as the classic loop);
    # the decoupled layout is the one Podracer says lives or dies on
    # utilization you can see live — queue depth, staleness, idle time
    pulse = obs_pulse.make_pulse(cfg.obs, rec=rec, log=log)
    mw = obs_memwatch.make_memwatch(cfg.obs, rec=rec)
    mw.snapshot("startup", t_env=0)
    # on-demand trace trigger, driven from the learner (main) thread —
    # the profiler window captures whole-process device activity, so
    # one driver is enough and the /trace route works on decoupled
    # runs exactly like classic ones
    trc = (obs_pulse.TraceController(
               results_dir, rec=rec,
               hub=pulse.hub if pulse is not None else None,
               n_iterations=cfg.profile_iterations)
           if (rec.enabled or pulse is not None) else None)

    # graftsight monitor (learner-thread cadence pass; same off-state
    # contract as the classic loop)
    sight_mon = obs_sight.make_monitor(cfg.obs, logger=logger, rec=rec,
                                       population=P)

    from .parallel.sebulba import make_sebulba
    seb = make_sebulba(exp)
    spec = seb.spec
    lockstep = sb.queue_slots == 1 and sb.staleness == 0
    log.info(f"sebulba decoupled loop: {sb.actor_devices} actor + "
             f"{sb.learner_devices} learner devices, queue_slots="
             f"{sb.queue_slots}, staleness={sb.staleness}"
             + (" (lockstep)" if lockstep else ""))
    if P:
        log.info(f"graftpop × sebulba: population of {P} members vmapped "
                 f"over the decoupled programs (seeds "
                 f"{graftpop.member_seeds(cfg)}, member axis sharded "
                 f"over each device set)")

    res = cfg.resilience
    guard = (resilience.ShutdownGuard.install() if res.handle_signals
             else resilience.ShutdownGuard())
    model_dir = os.path.join(cfg.local_results_path, "models",
                             os.path.basename(results_dir))
    save_lock = threading.Lock()
    spr = cfg.batch_size_run * cfg.env_args.episode_limit
    n_test_runs = max(1, cfg.test_nepisode // cfg.batch_size_run)
    test_quota = n_test_runs * cfg.batch_size_run * max(P, 1)
    buffer_capacity = exp.buffer.capacity

    actor_step, queue_put, queue_get, learner_step = seb.programs()

    # ---- cross-thread cells (all access under `cond` unless noted) ----
    cond = threading.Condition()
    cell = {"rs": None,          # latest post-rollout runner state handle
            "rs_t_env": 0,       # the actor's env-step cursor at it
            "params": None,      # latest published acting params (actor mesh)
            "version": 0,        # publish counter
            "q": None}           # the queue handle (threaded linearly)
    counters = {"put": 0, "got": 0, "consumed": 0, "started": 0}
    idle = {"actor_s": 0.0, "learner_s": 0.0}   # cumulative blocked time
    stop_event = threading.Event()   # epoch teardown (restore/exit)
    actor_failure = []               # DispatchFailed escaped from the actor
    nonfinite_streak = 0
    nonfinite_total = 0
    restores = 0
    # coordinated preemption (docs/RESILIENCE.md §6): stop_ok=False
    # after a failed peer negotiation degrades the exit to the per-host
    # shard save. Sebulba cuts at its own t_env (sanity_check rejects
    # sebulba×dp, so there is no multi-host sebulba to step in lockstep
    # toward a consensus cut).
    stop_at = None
    stop_ok = True

    # ---- shared driver-helper kit (graftlattice) ----------------------
    # default_wd stays None: each thread passes awd= explicitly (one
    # armed stamp per watchdog instance), and the queue waits bounded by
    # the PEER's progress stay span-only; wake= lets the stall response
    # unblock either thread's queue-condition wait.
    def _wake():
        with cond:
            cond.notify_all()
    kit = _DriverKit(cfg=cfg, res=res, log=log, rec=rec, mw=mw,
                     sight_mon=sight_mon, guard=guard,
                     model_dir=model_dir, save_lock=save_lock,
                     P=P, spec_fn=lambda: spec, wake=_wake)
    _persist_flight = kit.persist_flight
    _acquire_save_lock = kit.acquire_save_lock
    _watched = kit.watched
    _dispatch = kit.dispatch

    # ---- resume target ------------------------------------------------
    found = None
    if cfg.checkpoint_path:
        found = find_checkpoint(cfg.checkpoint_path, cfg.load_step)
        if found is None:
            log.info(f"no checkpoint found in {cfg.checkpoint_path}")

    def _snapshot_state():
        """The latest complete joined TrainState (for stamps and
        saves): learner half from the main thread's handles, runner
        half from the actor's published post-rollout handle."""
        with cond:
            rs = cell["rs"]
        return seb.join(rs, state_cell["ls"]) if rs is not None else None

    state_cell = {"ls": None}        # learner-side handle (main thread owns)

    # learner-side stall: full kit response (diagnosis + guard trip +
    # bounded emergency save from the stamped pre-dispatch state);
    # actor-side: diagnosis + guard trip only — the learner (main)
    # thread owns the checkpointable state and writes the emergency
    # save on its own exit path
    _on_stall = kit.stall_response

    def _on_actor_stall(diag: watchdog.StallDiagnosis) -> None:
        kit.stall_response(diag, tag="watchdog-actor", save=False)

    wd = wd_actor = None
    if res.dispatch_timeout > 0:
        wd = watchdog.Watchdog(
            res.dispatch_timeout, on_stall=_on_stall,
            grace_s=res.stall_grace_s, exit_code=res.stall_exit_code,
            first_timeout_s=res.first_dispatch_timeout).start()
        wd_actor = watchdog.Watchdog(
            res.dispatch_timeout, on_stall=_on_actor_stall,
            grace_s=res.stall_grace_s, exit_code=res.stall_exit_code,
            first_timeout_s=res.first_dispatch_timeout).start()
        log.info(f"dispatch watchdogs armed (actor + learner): timeout="
                 f"{res.dispatch_timeout}s, grace={res.stall_grace_s}s")
    ladder = watchdog.DegradationLadder(res.max_restores)
    if pulse is not None:
        if wd is not None:
            pulse.wire_watchdog(wd, side="learner")
        if wd_actor is not None:
            pulse.wire_watchdog(wd_actor, side="actor")
        pulse.wire_guard(guard)
        pulse.set("backend_info", 1, backend=jax.default_backend())
        pulse.set("queue_slots", sb.queue_slots)
        pulse.set("staleness_bound", sb.staleness)
        if sight_mon is not None:
            sight_mon.wire_pulse(pulse.hub)

    # ---- stat accumulators (actor pushes, both flush at cadences) -----
    train_acc = StatsAccumulator(population=P)
    test_acc = StatsAccumulator(population=P)

    def _stopping() -> bool:
        return stop_event.is_set() or guard.triggered

    # ---- the actor thread body ----------------------------------------
    def _actor_loop(rs, t_env0):
        """Rollout producer: staleness-bounded params adoption → rollout
        → queue put, plus the test cadence. Exits on quota, stop_event,
        guard trip, or an escaped DispatchFailed (recorded for the main
        thread's ladder)."""
        a_t = t_env0
        last_test_t = a_t - cfg.test_interval - 1
        last_runner_log_t = t_env0
        try:
            while a_t <= cfg.t_max and not _stopping():
                # params.sync: adopt the newest published params, but
                # never act more than `staleness` batches ahead of the
                # learner's last processed batch (0 = lockstep). Span
                # only, no watchdog stamp: this wait is bounded by the
                # LEARNER's progress, not device health — a slow train
                # step must read as actor idle time, never as a stall
                with _watched("params.sync", t=a_t):
                    resilience.fire("params.sync", t_env=a_t)
                    with cond:
                        while (counters["started"] - counters["consumed"]
                               > sb.staleness and not _stopping()):
                            t0 = time.monotonic()
                            cond.wait(0.05)
                            idle["actor_s"] += time.monotonic() - t0
                        params = cell["params"]
                if _stopping():
                    break

                def _roll(rs=rs, params=params):
                    rs2, tm, stats = actor_step(params, rs,
                                                test_mode=False)
                    # the actor thread's natural barrier: it has nothing
                    # else to do, and blocking here makes actor.dispatch
                    # spans the honest device rollout time
                    jax.block_until_ready(stats.epsilon)  # graftlint: disable=GL105
                    return rs2, tm, stats
                rs, tm, stats = _dispatch("actor.dispatch", _roll, rs,
                                          awd=wd_actor, t=a_t)
                a_t += spr
                with cond:
                    counters["started"] += 1
                    cell["rs"], cell["rs_t_env"] = rs, a_t
                _dispatch("fetch.train_stats",
                          lambda: train_acc.push(stats), None,
                          awd=wd_actor, t=a_t, retryable=False)

                # queue.put: wait for a free slot (backpressure), then
                # d2d-copy the emission and scatter it into the slot
                # ring. Span only (no stamp): a full queue is the
                # learner being slower, i.e. actor idle — not a stall
                with _watched("queue.put", t=a_t):
                    resilience.fire("queue.put", t_env=a_t)
                    tm_l = seb.to_learner(tm)
                    with cond:
                        while (counters["put"] - counters["got"]
                               >= sb.queue_slots and not _stopping()):
                            t0 = time.monotonic()
                            cond.wait(0.05)
                            idle["actor_s"] += time.monotonic() - t0
                        if _stopping():
                            break
                        slot = counters["put"] % sb.queue_slots
                        cell["q"] = queue_put(
                            cell["q"], jnp.asarray(slot, jnp.int32), tm_l)
                        counters["put"] += 1
                        cond.notify_all()

                # train-stat cadence (classic: runner_log_interval)
                if a_t - last_runner_log_t >= cfg.runner_log_interval:
                    def _flush_train_stats():
                        train_acc.flush(logger, a_t)
                        logger.log_stat("epsilon", train_acc.epsilon, a_t)
                    _dispatch("fetch.train_stats", _flush_train_stats,
                              None, awd=wd_actor, t=a_t, retryable=False)
                    last_runner_log_t = a_t

                # test cadence (the actor owns the rollout program and
                # the runner state, like the classic loop's test rolls)
                if (a_t - last_test_t) / cfg.test_interval >= 1.0:
                    # drain the pipeline first and adopt the freshest
                    # params: the classic loop evaluates AFTER the
                    # current iteration's train step, so the test
                    # rollouts here must see every produced batch
                    # trained (lockstep bit-parity depends on it; for
                    # overlapped configs it briefly drains the queue —
                    # the same serialization the classic cadence pays)
                    with _watched("params.sync", t=a_t):
                        resilience.fire("params.sync", t_env=a_t)
                        with cond:
                            while (counters["consumed"]
                                   < counters["started"]
                                   and not _stopping()):
                                t0 = time.monotonic()
                                cond.wait(0.05)
                                idle["actor_s"] += time.monotonic() - t0
                            params = cell["params"]
                    for _ in range(n_test_runs):
                        if _stopping():
                            break

                        def _test_roll(rs=rs, params=params):
                            rs2, _, s = actor_step(params, rs,
                                                   test_mode=True)
                            return rs2, s
                        rs, s = _dispatch("dispatch.test", _test_roll,
                                          rs, awd=wd_actor, t=a_t)
                        _dispatch("fetch.test_stats",
                                  lambda s=s: test_acc.push(s), None,
                                  awd=wd_actor, t=a_t, retryable=False)
                        if test_acc.n_episodes == test_quota:
                            _dispatch(
                                "fetch.test_stats",
                                lambda: test_acc.flush(logger, a_t,
                                                       prefix="test_"),
                                None, awd=wd_actor, t=a_t,
                                retryable=False)
                    with cond:
                        cell["rs"], cell["rs_t_env"] = rs, a_t
                    last_test_t = a_t
        except watchdog.DispatchFailed as df:
            log.warning(f"actor thread: {df} — handing to the ladder")
            with cond:
                actor_failure.append(df)
                cond.notify_all()
        except Exception as e:  # noqa: BLE001 — surfaced to the ladder
            log.exception("actor thread failed")
            with cond:
                actor_failure.append(
                    watchdog.DispatchFailed("actor.dispatch", 1, e))
                cond.notify_all()
        finally:
            with cond:
                cond.notify_all()    # wake a learner waiting on the queue

    # ---- state init / resume ------------------------------------------
    # per-member driver key streams under a population (each member's
    # stream splits exactly like the classic loop's single one)
    key = graftpop.member_keys(cfg) if P else jax.random.PRNGKey(
        cfg.seed + 1)
    t_env = 0

    def _ckpt_state(ts_):
        """What checkpoints hold: the bare TrainState classically, the
        (state, spec) PopState under a population — the classic loop's
        checkpoint contract, so either driver resumes the other's
        saves."""
        return graftpop.PopState(ts=ts_, spec=spec) if P else ts_

    def _save_topology():
        """The topology stamp every sebulba save carries (meta.json) —
        symmetric with the classic loop's, so a classic resume of a
        sebulba save (or vice versa) sees the loop-shape change and
        logs/routes it (docs/RESILIENCE.md §6)."""
        topo = {"loop": "sebulba",
                "sebulba": {"actor_devices": sb.actor_devices,
                            "learner_devices": sb.learner_devices}}
        if P:
            ema = getattr(train_acc, "member_return_ema", None)
            if ema and all(v is not None for v in ema):
                topo["member_ranking"] = sorted(
                    range(P), key=lambda m: ema[m], reverse=True)
        return topo

    def _place(found_):
        """(rs, ls, t_env) freshly initialized or restored. The restore
        streams each leaf STRAIGHT onto its mesh
        (``load_checkpoint_sharded`` with an abstract eval_shape
        template — per-leaf ``device_put``, so the two halves land on
        their disjoint meshes with no full-state single-device
        transient; the classic DP resume's ADVICE-r5 reasoning, which
        matters doubly here because this is also the mid-run ladder
        restore path, where the live sharded state still holds HBM)."""
        if found_ is None:
            return (*seb.init_states(cfg.seed), 0)
        dirname, step = found_
        if P:
            # population resume: the checkpoint is a PopState (or a v4
            # single-member state the migration shim lifts to the
            # stacked template — utils/checkpoint._migrate_raw).
            # Abstract ts template only (P concrete inits would
            # materialize P replay rings just to be discarded). The
            # restored spec is ignored in favor of the program-baked
            # one: pbt × sebulba is rejected (sanity_check), so the
            # spec is config-determined and the two are identical.
            shapes = jax.eval_shape(
                lambda: graftpop.init_population(exp, cfg))[0]
            ps, _ = elastic.resume_state(dirname, _ckpt_state(shapes),
                                         verify=False,
                                         topology={"loop": "sebulba"})
            rs, ls = seb.place(ps.ts)
            rs = rs.replace(t_env=jax.device_put(
                jnp.full((P,), step, jnp.int32), rs.t_env.sharding))
            log.info(f"resumed population from {dirname} at "
                     f"t_env={step}")
            return rs, ls, step
        shapes = jax.eval_shape(lambda: exp.init_train_state(cfg.seed))
        rs_shape, ls_shape = seb.split_shapes(shapes)
        ts = elastic.resume_state(
            dirname, shapes,
            seb.join(seb.runner_shardings(rs_shape),
                     seb.learner_shardings(ls_shape)),
            verify=False, topology={"loop": "sebulba"})[0]
        rs, ls = seb.split_shapes(ts)
        # keep the canonical placement for the restored cursor
        rs = rs.replace(t_env=jax.device_put(
            jnp.asarray(step, jnp.int32), rs.t_env.sharding))
        log.info(f"resumed from {dirname} at t_env={step}")
        return rs, ls, step

    rs0, ls, t_env = _place(found)

    if rec.enabled:
        rec.mark("run", t_env=t_env, backend=jax.default_backend(),
                 batch_size_run=cfg.batch_size_run,
                 episode_limit=cfg.env_args.episode_limit,
                 batch_size=cfg.batch_size, superstep=1,
                 host_buffer=False, sebulba=True, population=P,
                 actor_devices=sb.actor_devices,
                 learner_devices=sb.learner_devices,
                 queue_slots=sb.queue_slots, staleness=sb.staleness)

    last_log_t = t_env
    last_save_t = t_env if t_env else -cfg.save_model_interval - 1
    start_time = time.time()
    last_log_time = None
    train_infos = []
    episode = _host_int(ls.episode)
    buffer_filled = _host_int(ls.buffer.episodes_in_buffer)
    state_cell["ls"] = ls

    def _epoch(rs, t_env0):
        """One actor-thread lifetime: spawn the producer, consume until
        the quota is drained (or a guard trip / ladder rung ends it).
        Returns ``'done' | 'failed'`` — 'failed' hands the recorded
        DispatchFailed to the caller's ladder."""
        nonlocal ls, t_env, episode, buffer_filled, key, train_infos
        nonlocal nonfinite_streak, nonfinite_total
        nonlocal last_log_t, last_save_t, last_log_time
        nonlocal stop_at, stop_ok
        stop_event.clear()
        with cond:
            actor_failure.clear()   # same discipline as its append sites
            counters.update(put=0, got=0, consumed=0, started=0)
            cell["q"] = seb.init_queue()
            cell["rs"], cell["rs_t_env"] = rs, t_env0
            cell["params"] = seb.publish_params(ls.learner.params["agent"])
            cell["version"] = 0
        actor = threading.Thread(target=_actor_loop, args=(rs, t_env0),
                                 daemon=True, name="t2omca-sebulba-actor")
        actor.start()
        failed = None
        try:
            while not guard.triggered:
                resilience.fire("driver.iteration", t_env=t_env,
                                guard=guard)
                # coordinated preemption (docs/RESILIENCE.md §6):
                # propagate a peer's announced shutdown, then negotiate
                # once and cut HERE — the actor thread exits on the
                # trigger, so the learner cannot step toward a later
                # consensus target (and sanity_check rejects sebulba×dp,
                # so there is no multi-host sebulba peer to align with;
                # the negotiation only decides collective-vs-shard save)
                if not guard.triggered and dist.peer_shutdown_requested():
                    guard.request("peer")
                if guard.triggered:
                    if stop_at is None:
                        dist.announce_shutdown(t_env)
                        with rec.span("preempt.barrier", t_env=t_env):
                            stop_at, stop_ok = dist.negotiate_stop_step(
                                t_env, res.preempt_barrier_timeout_s)
                    break
                if pulse is not None:
                    pulse.tick_iteration(t_env, episode)
                if trc is not None:
                    trc.poll(t_env)
                # queue.get: wait for an item (or producer exit), then
                # gather the slot straight into the replay ring. Span
                # only (no stamp): an empty queue is the actor being
                # slower, i.e. learner idle — not a stall; the consume
                # dispatch itself is an async enqueue whose faults
                # surface at the stamped learner.dispatch/fetch
                # boundaries
                got_item = False
                with _watched("queue.get", t=t_env):
                    resilience.fire("queue.get", t_env=t_env)
                    with cond:
                        while (counters["put"] == counters["got"]
                               and actor.is_alive() and not actor_failure
                               and not _stopping()):
                            t0 = time.monotonic()
                            cond.wait(0.05)
                            idle["learner_s"] += time.monotonic() - t0
                        if actor_failure:
                            failed = actor_failure[0]
                            break
                        if counters["put"] > counters["got"]:
                            slot = counters["got"] % sb.queue_slots
                            ls2, q2 = queue_get(
                                ls, cell["q"],
                                jnp.asarray(slot, jnp.int32))
                            ls, cell["q"] = ls2, q2
                            counters["got"] += 1
                            got_item = True
                            cond.notify_all()
                if failed is not None or (not got_item):
                    break               # producer finished (or failed)
                state_cell["ls"] = ls
                t_env += spr
                episode += cfg.batch_size_run
                buffer_filled = min(buffer_filled + cfg.batch_size_run,
                                    buffer_capacity)

                # train gate: the classic loop's host mirror + key split
                if (buffer_filled >= cfg.batch_size
                        and episode >= cfg.accumulated_episodes):
                    if P:
                        # per-member key streams: each member's stream
                        # splits exactly like the classic loop's single
                        # one (lockstep bit-parity with the classic
                        # population loop depends on it)
                        key2, rows = list(key), []
                        for m in range(P):
                            key2[m], k_s = jax.random.split(key2[m])
                            rows.append(k_s)
                        k_sample = jnp.stack(rows)
                    else:
                        key2, k_sample = jax.random.split(key)

                    def _train_once(ls=ls, k_sample=k_sample):
                        ls2, info = learner_step(ls, k_sample,
                                                 jnp.asarray(t_env))
                        return ls2, info
                    ls, info = _dispatch("learner.dispatch", _train_once,
                                         _snapshot_state(), awd=wd,
                                         t=t_env)
                    key = key2
                    train_infos.append(info)
                    state_cell["ls"] = ls

                # params.sync: publish the (possibly) fresh params back
                # to the actor mesh and advance the staleness window
                # (an async device-to-device copy — the stamp bounds
                # only the enqueue)
                with _watched("params.sync", awd=wd, t=t_env):
                    resilience.fire("params.sync", t_env=t_env)
                    new_params = seb.publish_params(
                        ls.learner.params["agent"])
                with cond:
                    cell["params"] = new_params
                    cell["version"] += 1
                    counters["consumed"] += 1
                    cond.notify_all()

                _cadences()
                if trc is not None:
                    trc.tick(logger, t_env)
            return ("failed", failed) if failed is not None else \
                ("done", None)
        except watchdog.DispatchFailed as df:
            return "failed", df
        finally:
            stop_event.set()
            with cond:
                cond.notify_all()
            actor.join(timeout=30.0)
            if actor.is_alive():
                log.warning("actor thread did not exit within 30s "
                            "(wedged dispatch?) — continuing teardown; "
                            "the daemon thread dies with the process")

    def _cadences():
        """Save + log cadences (learner thread; the actor owns the
        test/runner-log cadences)."""
        nonlocal last_save_t, last_log_t, last_log_time, train_infos
        nonlocal nonfinite_streak, nonfinite_total
        if cfg.save_model and (t_env - last_save_t) >= cfg.save_model_interval:
            def _save_once():
                with _watched("checkpoint.save", state_cell["ls"], awd=wd,
                              t=t_env):
                    if not _acquire_save_lock("save cadence"):
                        return None
                    try:
                        return save_checkpoint(
                            model_dir, t_env,
                            _ckpt_state(_snapshot_state()),
                            gather_retries=res.dispatch_retries,
                            gather_backoff_s=res.retry_backoff_s,
                            topology=_save_topology())
                    finally:
                        save_lock.release()
            save_to = watchdog.retry_call(
                _save_once, attempts=1 + res.dispatch_retries,
                backoff_s=res.retry_backoff_s, label="checkpoint.save")
            if save_to is not None:
                log.info(f"Saving models to {save_to}")
                if res.keep_last:
                    prune_checkpoints(model_dir, res.keep_last,
                                      res.keep_every)
                last_save_t = t_env

        if (t_env - last_log_t) >= cfg.log_interval:
            if train_infos:
                def _fetch_infos():
                    flags = np.asarray(jax.device_get(  # graftlint: disable=GL105
                        [i["all_finite"] for i in train_infos]))
                    return flags, jax.device_get(train_infos[-1])  # graftlint: disable=GL105
                flags, last = _dispatch("fetch.train_infos",
                                        _fetch_infos, None, awd=wd,
                                        t=t_env, retryable=False)
                if P:
                    # (n, P) member flags: a train step counts as
                    # finite only when EVERY member's update was —
                    # one poisoned member is a restore-worthy event
                    # exactly like a solo NaN (the stacked state is
                    # one checkpoint)
                    flags = flags.reshape(len(train_infos), -1)\
                                 .all(axis=1)
                for ok in flags:
                    if ok:
                        nonfinite_streak = 0
                    else:
                        nonfinite_streak += 1
                        nonfinite_total += 1
                if not flags.all():
                    logger.log_stat("nonfinite_steps", nonfinite_total,
                                    t_env)
                    rec.mark("nonfinite", t_env=t_env,
                             streak=nonfinite_streak,
                             total=nonfinite_total)
                    log.warning(
                        f"non-finite loss/grads in "
                        f"{int((~flags).sum())}/{len(flags)} train steps "
                        f"since last log (streak={nonfinite_streak})")
                for k in ("loss", "grad_norm", "td_error_abs",
                          "q_taken_mean", "target_mean"):
                    if P:
                        # aggregate row = population mean; per-member
                        # rows (pop<i>_*) only at P > 1 so a P=1 run
                        # keeps the solo metric stream (the classic
                        # population cadence's shape)
                        v = np.asarray(last[k], np.float64)
                        logger.log_stat(k, float(v.mean()), t_env)
                        if P > 1:
                            for m in range(P):
                                logger.log_stat(f"pop{m}_{k}",
                                                float(v[m]), t_env)
                    else:
                        logger.log_stat(k, float(last[k]), t_env)
                if sight_mon is not None:
                    # classic-loop contract: detector pass on the same
                    # fetch, flight persist on a fresh trip
                    with rec.span("sight.detect", t_env=t_env):
                        trips = sight_mon.observe(last, t_env)
                    if trips:
                        log.warning(
                            f"graftsight: detector(s) tripped at "
                            f"t_env={t_env}: {', '.join(trips)} — "
                            f"/healthz degraded")
                        _persist_flight(os.path.join(
                            results_dir, "flight_recorder.json"))
                train_infos = []
                if (res.nonfinite_tolerance
                        and nonfinite_streak >= res.nonfinite_tolerance):
                    raise _NonFiniteEscalation(nonfinite_streak)
            with cond:
                depth = counters["put"] - counters["got"]
                ahead = counters["started"] - counters["consumed"]
            logger.log_stat("queue_depth", depth, t_env)
            logger.log_stat("actor_idle_s", round(idle["actor_s"], 3),
                            t_env)
            logger.log_stat("learner_idle_s",
                            round(idle["learner_s"], 3), t_env)
            if rec.enabled:
                rec.mark("sebulba", t_env=t_env, queue_depth=depth,
                         actor_idle_s=round(idle["actor_s"], 3),
                         learner_idle_s=round(idle["learner_s"], 3))
            if kit.dispatch_faults:
                logger.log_stat("dispatch_faults", kit.dispatch_faults,
                                t_env)
            logger.log_stat("episode", episode, t_env)
            now = time.time()
            rate = None
            if last_log_time is not None:
                rate = ((t_env - last_log_t)
                        / max(now - last_log_time, 1e-9))
                logger.log_stat("env_steps_per_sec", rate, t_env)
            last_log_time = now
            pulse_snap = mw.snapshot("log", t_env=t_env)
            if pulse is not None:
                # the decoupled loop's live utilization surface: queue
                # depth, params staleness in flight, both sides' idle
                if rate is not None:
                    pulse.set("env_steps_per_sec", rate)
                pulse.set("queue_depth", depth)
                pulse.set("staleness_in_flight", ahead)
                pulse.set("actor_idle_seconds", round(idle["actor_s"], 3))
                pulse.set("learner_idle_seconds",
                          round(idle["learner_s"], 3))
                pulse.set("nonfinite_streak", nonfinite_streak)
                pulse.set("dispatch_faults", kit.dispatch_faults)
                pulse.set("ladder_failures", ladder.failures)
                pulse.set("restores", restores)
                pulse.set_memwatch(pulse_snap)
            logger.print_recent_stats()
            last_log_t = t_env

    # ---- epochs: run; a ladder restore reloads and re-enters ----------
    try:
        while True:
            try:
                status, failed = _epoch(rs0, t_env)
            except _NonFiniteEscalation as nf:
                status, failed = "failed", watchdog.DispatchFailed(
                    "learner.dispatch", 1, nf)
            if status == "done" or guard.triggered:
                break
            # ladder: no superstep to degrade — restore or abort
            action = ladder.next_action(can_degrade=False)
            logger.log_stat("dispatch_failures", ladder.failures, t_env)
            rec.mark("ladder", action=action, phase=failed.phase,
                     t_env=t_env, failures=ladder.failures)
            good = (find_checkpoint(model_dir) if cfg.save_model
                    else None)
            if action == "restore" and good is not None:
                log.warning(f"degradation ladder: {failed} — restoring "
                            f"last good checkpoint {good[0]} "
                            f"({ladder.describe()})")
                rs0, ls, t_env = _place(good)
                state_cell["ls"] = ls
                episode = _host_int(ls.episode)
                buffer_filled = _host_int(ls.buffer.episodes_in_buffer)
                train_infos = []
                nonfinite_streak = 0
                fetches = train_acc.fetches
                train_acc = StatsAccumulator(population=P)
                train_acc.fetches = fetches
                # the torn-down actor thread may have died mid-test-
                # cadence: a partial accumulation would miss the
                # exact-quota flush on every later cadence (the classic
                # loop's test-failure reset, same reasoning)
                tfetches = test_acc.fetches
                test_acc = StatsAccumulator(population=P)
                test_acc.fetches = tfetches
                restores += 1
                last_log_t = last_save_t = t_env
                continue
            _persist_flight(os.path.join(model_dir,
                                         "flight_recorder.json"))
            diag = wd.take_diagnosis() if wd is not None else None
            raise RuntimeError(
                f"sebulba dispatch failure exhausted the degradation "
                f"ladder at t_env={t_env} ({ladder.describe()})"
                + (f"; stall diagnosis: {diag.message()}" if diag else "")
                + f" — last failure: {failed}") from failed
    except BaseException as e:
        rec.mark("crash", t_env=t_env,
                 error=f"{type(e).__name__}: {e}"[:300])
        _persist_flight(os.path.join(results_dir, "flight_recorder.json"))
        rec.close()
        raise
    finally:
        stop_event.set()
        with cond:
            cond.notify_all()
        if wd is not None:
            wd.stop()
        if wd_actor is not None:
            wd_actor.stop()
        guard.uninstall()
        if pulse is not None:
            pulse.close()

    ts = _snapshot_state() or seb.join(rs0, ls)
    if guard.triggered:
        rec.mark("shutdown", t_env=t_env, signame=guard.signame or "")
        _persist_flight(os.path.join(results_dir, "flight_recorder.json"))
        stall = (wd.take_diagnosis() if wd is not None else None) or \
                (wd_actor.take_diagnosis() if wd_actor is not None
                 else None)
        if stall is not None:
            log.warning(f"watchdog: {stall.message()} — diagnosis "
                        f"persisted to {model_dir}/stall_diagnosis.json")
        log.warning(f"shutdown requested ({guard.signame}) at "
                    f"t_env={t_env} — stopping gracefully")
        if cfg.save_model and res.emergency_checkpoint \
                and watchdog.state_intact(ts):
            if _acquire_save_lock("preemption exit"):
                save_to = None
                deadline = (watchdog.ExitDeadline(
                                max(res.stall_grace_s, 60.0),
                                res.stall_exit_code,
                                label="sebulba exit emergency checkpoint")
                            if wd is not None else nullcontext())
                try:
                    with deadline:
                        if stop_ok:
                            try:
                                save_to = watchdog.retry_call(
                                    lambda: save_checkpoint(
                                        model_dir, t_env, _ckpt_state(ts),
                                        gather_retries=res.dispatch_retries,
                                        gather_backoff_s=res.retry_backoff_s,
                                        topology=_save_topology()),
                                    attempts=1 + res.dispatch_retries,
                                    backoff_s=res.retry_backoff_s,
                                    label="checkpoint.emergency")
                            except Exception:  # noqa: BLE001
                                log.exception(
                                    "collective emergency checkpoint "
                                    "failed on the sebulba exit — "
                                    "degrading to the per-host shard "
                                    "save")
                        if save_to is None:
                            # degraded exit (docs/RESILIENCE.md §6):
                            # write this host's addressable shard only —
                            # no collectives, cannot hang on a dead peer
                            with rec.span("checkpoint.shard_save",
                                          t_env=t_env):
                                save_to = save_checkpoint_shards(
                                    model_dir, t_env, _ckpt_state(ts),
                                    topology=_save_topology())
                except Exception:  # noqa: BLE001 — exit stays orderly
                    log.exception("emergency checkpoint failed on the "
                                  "sebulba exit path")
                finally:
                    save_lock.release()
                if save_to is not None:
                    log.info(f"emergency checkpoint saved to {save_to}")
        log.info(f"resume with checkpoint_path={model_dir} (newest valid "
                 f"step selected automatically)")
    else:
        log.info("Finished Training")
        log.info(f"sebulba totals: actor idle {idle['actor_s']:.2f}s, "
                 f"learner idle {idle['learner_s']:.2f}s, "
                 f"wall {time.time() - start_time:.2f}s")
    rec.close()
    return ts


class _NonFiniteEscalation(RuntimeError):
    """Internal control flow: the non-finite streak hit
    ``resilience.nonfinite_tolerance`` inside the sebulba log cadence —
    routed through the epoch ladder (restore rung) exactly like a
    persistent dispatch failure."""

    def __init__(self, streak: int):
        super().__init__(f"training diverged: {streak} consecutive "
                         f"non-finite train steps")


def evaluate_sequential(exp: Experiment, logger: Logger,
                        results_dir: str) -> TrainState:
    """Eval/replay/benchmark entry (reference ``evaluate_sequential``,
    ``per_run.py:74-101``): greedy episodes on the single-env runner, with
    optional replay (npz), animation (gif) and benchmark CSV export."""
    cfg = exp.cfg
    log = logger.console_logger
    ts = exp.init_train_state(cfg.seed)
    if cfg.checkpoint_path:
        found = find_checkpoint(cfg.checkpoint_path, cfg.load_step)
        if found is not None:
            dirname, step = found
            from .utils.checkpoint import (CheckpointFormatError,
                                           load_learner_state)
            try:
                ts = load_checkpoint(dirname, ts, verify=False)
                log.info(f"loaded full state from {dirname}")
            except CheckpointFormatError:
                raise        # unreadable format: no fallback applies
            except ValueError as e:
                # eval config differs from the training config (other
                # env-lane count, dense-vs-compact replay, DP shapes):
                # fall back to the learner subtree — the reference's
                # model-only checkpoint semantics (per_run.py:185-187)
                log.info(f"full-state restore rejected ({e}); trying "
                         f"model-only restore")
                ts = load_learner_state(dirname, ts)
                log.info(f"loaded learner (model-only) from {dirname}; "
                         f"runner state starts fresh")

    er = exp.episode_runner
    rs = er.init_state(jax.random.PRNGKey(cfg.seed + 2))
    params = ts.learner.params["agent"]

    trajs = []
    returns = []
    for ep in range(cfg.test_nepisode):
        rs, batch, stats, traj = er.run(params, rs, test_mode=True,
                                        capture_trajectory=True)
        trajs.append(traj)
        returns.append(float(np.sum(jax.device_get(stats.episode_return))))
    log.info(f"eval over {len(returns)} episodes: "
             f"return_mean={np.mean(returns):.3f} ± {np.std(returns):.3f}")
    logger.log_stat("test_return_mean", float(np.mean(returns)), 0)

    # reference per_run.py:85,92: in full-evaluate mode only every
    # ``animation_interval_evaluation``-th episode is rendered/animated
    anim_every = max(cfg.animation_interval_evaluation, 1)
    anim_eps = [i for i in range(len(trajs))
                if not cfg.evaluate or i % anim_every == 0]
    if cfg.save_replay:
        for i in anim_eps:
            p = er.save_replay(trajs[i],
                               os.path.join(results_dir,
                                            f"replay_episode_{i}.npz"))
        log.info(f"replays saved to {results_dir} ({len(anim_eps)} episodes)")
    if cfg.save_animation:
        for i in anim_eps:
            p = er.save_animation(
                trajs[i], os.path.join(results_dir,
                                       f"animation_episode_{i}.gif"))
        if p:
            log.info(f"animations saved to {results_dir} "
                     f"({len(anim_eps)} episodes)")
    if cfg.benchmark_mode:
        # reference exports CSVs only in benchmark mode (per_run.py:96-101)
        p = er.benchmark_csv(trajs, os.path.join(results_dir,
                                                 "benchmark.csv"))
        log.info(f"benchmark CSV saved to {p}")
    return ts


if __name__ == "__main__":          # `python -m t2omca_tpu.run train ...`
    import sys

    from .__main__ import main
    sys.exit(main())
