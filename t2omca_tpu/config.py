"""Configuration system.

Replaces the reference's sacred config dict → ``SimpleNamespace`` flow
(``/root/reference/per_run.py:20-66,292-309``). The full flag inventory is the
set of ``args.*`` / ``config[...]`` accesses in the released reference slice
(SURVEY.md §5.6); every one of those flags exists here with the same name so a
reference user can carry their config across.

Config objects are frozen dataclasses (hashable → usable as jit static
arguments). ``load_config`` merges: defaults → optional YAML/JSON file →
``key=value`` CLI overrides, then runs the same sanity pass the reference
applies in ``args_sanity_check`` (``/root/reference/per_run.py:292-309``).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Optional, Tuple


@dataclass(frozen=True)
class ScenarioConfig:
    """graftworld scenario-distribution surface (``env_args.scenario.*``,
    envs/graftworld.py, docs/ENVS.md). Every collection field is a tuple
    — the config tree stays hashable, so jitted programs can close over
    the resolved distribution as static structure. ``kind`` empty (the
    default) means "this env key's registry default scenario"
    (envs/registry.py ``scenario_config``), which for the classic
    ``multi_agv_offloading`` key is the fixed baseline — byte-identical
    behavior for every pre-graftworld config. An EXPLICIT kind always
    wins over the registry default, even when it names the baseline
    point (the empty sentinel exists exactly so that explicit-baseline-
    over-a-family-key stays expressible)."""

    # "" = the env key's registry default; fixed = one parameter point;
    # uniform = uniform ranges over knobs; mixture = weighted mixture
    # over family distributions
    kind: str = ""
    # the scenario family (fixed/uniform kinds): baseline | hetfleet |
    # interference | surge (envs/graftworld.FAMILY_NAMES)
    family: str = "baseline"
    # uniform kind: ((knob, lo, hi), ...); empty = the family's
    # canonical envelope (graftworld.FAMILY_RANGES)
    ranges: Tuple[Tuple[str, float, float], ...] = ()
    # fixed/uniform kinds: ((knob, value), ...) applied over the
    # family preset before any range draws
    overrides: Tuple[Tuple[str, float], ...] = ()
    # mixture kind: component family names; empty = all families
    families: Tuple[str, ...] = ()
    # mixture kind: component weights; empty = uniform
    weights: Tuple[float, ...] = ()
    # fleet-size randomization (the padding axis): each lane draws
    # n_active ~ U{min_agents..agv_num} at reset; 0 = always the full
    # static fleet
    min_agents: int = 0


@dataclass(frozen=True)
class EnvConfig:
    """Environment flags (reference ``env_args``, SURVEY.md §5.6)."""

    key: str = "multi_agv_offloading"     # env registry name (ref: env / map_name)
    map_name: str = "multi_agv"
    seed: int = 0
    mec_num: int = 2
    agv_num: int = 4
    num_channels: int = 2
    episode_limit: int = 150
    obs_entity_mode: bool = True
    state_entity_mode: bool = True
    state_last_action: bool = False
    edge_only: bool = False
    # one order-free batched Welford update per step instead of the
    # reference's sequential per-agent loop (O(A/n) transient deviation;
    # see envs/normalization.py:welford_update_batch). Default ON: it gates
    # the whole fast-path stack (entity-table acting + compact entity
    # storage, ops/query_slice.py eligibility predicates) and is validated
    # end-to-end by the config-1 faststack sweep
    # (runs/config1_faststack/SUMMARY.md). Reference-exact parity configs
    # (sequential normalizer ordering) opt out with fast_norm=False.
    fast_norm: bool = True
    # train-time reward scaling (the reference env imports RewardScaling
    # but the released slice never instantiates it — provided wired): each
    # env lane divides its recorded rewards by the running std of its
    # discounted return (envs/normalization.py scale_reward; the
    # discounted-return accumulator resets at episode start, the running
    # std persists across episodes). Logged returns/metrics stay RAW;
    # only the replay-recorded reward the learner trains on is scaled.
    # Off by default — changes the loss scale, so parity configs must not
    # enable it.
    reward_scaling: bool = False

    # ----- physics / M1 spec values (frozen in docs/SPEC.md §1; the reference
    # does not release data_struct_multiagv, so these are our pinned choices)
    mec_radius_m: float = 50.0            # placement radius & spacing/2 (ref env :23-24)
    communication_range_m: float = 50.0   # MEC.communication_range (M1)
    mec_compute_cap: float = 20e9         # cycles/s (M1)
    user_compute_cap: float = 5e9         # cycles/s (M1)
    transmit_power_w: float = 0.5         # W (M1)
    latency_max_ms: float = 100.0         # job deadline budget (M1)
    job_prob: float = 0.5                 # P(generate_job emits a job) per slot (M1)
    data_size_min: float = 4000.0         # bits (M1)
    data_size_max: float = 12000.0        # bits (M1)

    # graftworld scenario distribution (envs/graftworld.py, docs/ENVS.md):
    # which EnvParams each env lane samples at reset. Default = the env
    # key's registry default (fixed baseline for the classic key).
    scenario: "ScenarioConfig" = field(default_factory=lambda: ScenarioConfig())


@dataclass(frozen=True)
class ModelConfig:
    """Agent/mixer model flags (SURVEY.md §5.6 'model')."""

    emb: int = 32
    heads: int = 3
    depth: int = 2
    ff_hidden_mult: int = 4
    dropout: float = 0.0
    mixer_emb: int = 32                   # must equal emb when mixer consumes agent hiddens
    mixer_heads: int = 3
    mixer_depth: int = 2
    qmix_pos_func: str = "abs"            # abs | softplus | quadratic | none
    qmix_pos_func_beta: float = 1.0
    use_orthogonal: bool = False
    standard_heads: bool = False          # perf mode: per-head dim = emb//heads (quirk Q1 off)
    dtype: str = "float32"                # compute dtype: float32 | bfloat16 (perf mode)
    # exact token-0-only agent forward (ops/query_slice.py): on by default,
    # auto-disabled where inapplicable (non-transformer agent, dropout>0);
    # noisy selectors STAY eligible — the noise is q-head-only, sampled
    # post-slice from an explicit key (round 5)
    use_qslice: bool = True
    # entity-table acting (ops/query_slice.agent_forward_qslice_entity):
    # contract attention against per-env (A, E) tables instead of
    # materializing per-agent token embeddings; exact for entity-mode obs
    # under fast_norm, auto-disabled otherwise
    use_entity_tables: bool = True
    # ReZero-style zero-init gate on the mixer output (q_tot = gate * y,
    # gate a scalar param init 0). The transformer mixer's readout
    # contracts emb-many O(1) post-LN token entries against abs-positive
    # weights, so its INIT output scale grows ~linearly with emb
    # (measured O(+-600) at emb=128/16 agents) — garbage early bootstrap
    # targets that dwarf unit-normalized rewards. Off by default
    # (reference-parity init); the config-2 learning recipe turns it on
    # together with reward_unit/td_loss (scripts/campaign_config2_r5.sh).
    mixer_zero_init: bool = False
    # rematerialize the learner's per-timestep forwards in the backward
    # pass (jax.checkpoint around the scan bodies): trades ~1 extra
    # forward for O(T) less residual HBM — the standard TPU lever for
    # long-horizon episode unrolls (config 3/4: T=150)
    remat: bool = False
    # entity counts: filled from env info when 0
    n_entities_obs: int = 0
    n_entities_state: int = 0
    # acting-path compute dtype (docs/PERF.md dtype policy): the dtype
    # select_actions/rollout forwards run in, threaded the same way
    # replay.store_dtype is. "" (default) inherits model.dtype — every
    # existing config is byte-identical. "bfloat16" over a float32
    # model.dtype is the bf16-acting mode: the per-rollout acting fold
    # (BasicMAC.prepare_acting_params) casts params once per rollout
    # and the scan-step forwards compute in bf16, while softmax
    # statistics, LayerNorm statistics, the carried hidden token, the
    # q-head output and the env normalizer all stay f32, and the TRAIN
    # path keeps model.dtype untouched (f32 parity configs stay
    # bit-identical between acting and learner unroll).
    act_dtype: str = ""


@dataclass(frozen=True)
class ReplayConfig:
    buffer_size: int = 500                # episodes
    buffer_cpu_only: bool = False         # kept for parity; device-resident by default
    prioritized: bool = True
    per_alpha: float = 0.6
    per_beta: float = 0.4
    # storage dtype for the big obs/state arrays in episode batches and the
    # replay ring (HBM is the budget; bf16 halves it — the TPU analog of the
    # reference's buffer_cpu_only escape hatch)
    store_dtype: str = "float32"          # float32 | bfloat16
    # store the factored entity obs (rows + MEC index + normalizer stats,
    # ~20x smaller, exact reconstruction) instead of the flattened entity
    # obs; auto-disabled where inapplicable (ops/query_slice.py
    # entity_store_eligible)
    compact_entity_store: bool = True


@dataclass(frozen=True)
class ResilienceConfig:
    """Fault-tolerance knobs (docs/RESILIENCE.md). All of these govern the
    driver/checkpoint layer only — the train math is untouched, so every
    default is safe for parity configs."""

    # SIGTERM/SIGINT → flag → orderly loop exit with one final emergency
    # checkpoint and a resume hint (utils/resilience.ShutdownGuard). TPU
    # preemption then loses at most one iteration instead of up to
    # save_model_interval env steps.
    handle_signals: bool = True
    emergency_checkpoint: bool = True
    # non-finite guard (learners/qmix_learner.py): the jitted train step
    # skips the parameter+priority update when loss/grads go non-finite
    # (params pass through unchanged); the driver counts CONSECUTIVE
    # tripped steps at the log cadence (async pipeline stays unblocked)
    # and, at this threshold, restores the newest valid checkpoint and
    # continues. 0 disables the restore escalation (guard still skips).
    nonfinite_tolerance: int = 3
    # guard-triggered restores allowed before the run aborts with a
    # diagnosis (a deterministic NaN source would otherwise loop forever)
    max_restores: int = 2
    # checkpoint retention (utils/checkpoint.prune_checkpoints): keep the
    # newest keep_last steps, plus every step divisible by keep_every
    # (0 = no modular survivors). keep_last=0 disables pruning entirely.
    keep_last: int = 0
    keep_every: int = 0
    # fault injection (tests/test_resilience.py ONLY): multiply the loss
    # by NaN at exactly this learner train step (-1 = off). Static config,
    # so the disabled case costs nothing inside jit.
    inject_nan_at_step: int = -1
    # ---- hang detection & degradation ladder (docs/RESILIENCE.md §5) ----
    # watchdog stall threshold in seconds for any device-facing call
    # (dispatch, collective, checkpoint gather). 0 = watchdog fully
    # disabled — the driver behaves bit-identically to a build without it.
    # Size it to a few× the slowest expected dispatch (superstep K ×
    # iteration time, or the checkpoint gather at cadence).
    dispatch_timeout: float = 0.0
    # the FIRST occurrence of each watched phase includes the XLA compile
    # (tens of seconds on CPU, minutes at production shapes) and is
    # therefore exempt from dispatch_timeout; this key bounds it instead.
    # 0 = unbounded first occurrence (the default — compile times are
    # config-dependent); set it explicitly to catch startup hangs (the
    # wedged-tunnel-at-backend-init shape, BASELINE.md's ~25 min block).
    # Only meaningful alongside dispatch_timeout > 0 (the watchdog is
    # not constructed otherwise — sanity_check rejects the dead combo).
    first_dispatch_timeout: float = 0.0
    # after the watchdog fired (diagnosis persisted + emergency checkpoint
    # attempted), how long to wait for the stalled call to return before a
    # hard process exit with stall_exit_code. 0 = never hard-exit (rely on
    # the orderly ShutdownGuard path once the call returns).
    stall_grace_s: float = 300.0
    # process exit code of the hard watchdog exit — distinct from 0
    # (orderly) and 1 (crash) so supervisors can count stall restarts
    stall_exit_code: int = 17
    # degradation ladder (utils/watchdog.py): in-place retries of a failed
    # dispatch before escalating a rung (transient-classified errors only;
    # deterministic errors propagate immediately). Exponential backoff
    # from retry_backoff_s with jitter between attempts.
    dispatch_retries: int = 2
    retry_backoff_s: float = 0.5
    # ladder rung 1: on exhausted retries of the fused superstep, fall
    # back to K=1 (smaller blast radius — each dispatch then risks one
    # iteration, not K) before restoring a checkpoint
    degrade_superstep: bool = True
    # coordinated multi-host preemption (docs/RESILIENCE.md §6): how long
    # the signaled hosts wait at the stop-step barrier for their peers
    # before degrading to the per-host shard save. Bounds the exit path
    # against a peer that died mid-preemption; single-host runs never
    # wait.
    preempt_barrier_timeout_s: float = 10.0


@dataclass(frozen=True)
class SightConfig:
    """graftsight learning-dynamics telemetry (``obs/sight.py``,
    docs/OBSERVABILITY.md §6). ``enabled`` is a STATIC gate compiled
    into the train step: off (the default) leaves every jitted program
    byte-identical (graftprog fingerprints pinned); on, the train step
    additionally reduces per-module gradient/update norms, fixed-bin
    masked histograms, PER importance/priority health, per-layer
    attention entropy and target drift ON DEVICE into ``train_info`` —
    the diagnostics then ride the existing log-cadence fetch (zero
    extra dispatches, zero extra device→host syncs). The host-side
    ``SightMonitor`` runs the windowed detectors below over that
    stream; each registers a pulse ``/healthz`` check when the live
    plane is up (``pulse_port``) and a flight-recorder mark when span
    telemetry is on (``enabled`` here does NOT require ``obs.enabled``
    — the metrics.jsonl stream and the jax-free ``obs learning`` CLI
    work standalone; the pulse/flight integrations simply no-op
    without their planes)."""

    enabled: bool = False
    # fixed-bin masked histograms (TD error symmetric over ±td_range;
    # q_taken/targets over ±q_range; outliers clip into the edge bins —
    # an edge-bin pileup IS the signal the ranges exist to surface)
    bins: int = 16
    td_range: float = 10.0
    q_range: float = 50.0
    # detector window, in log cadences (plateau/starvation detectors
    # need history; collapse/divergence detectors trip on one sample)
    window: int = 5
    # loss plateau: relative spread of the windowed loss below this
    # fraction of its mean over a FULL window
    plateau_rel: float = 0.02
    # Q divergence: |q_taken_mean| or |target_mean| beyond this (raw
    # value units — NaN-free blow-ups, the guard rail catches NaNs)
    q_div: float = 1e4
    # PER health: importance-weight effective sample size below this
    # fraction of the batch, or priority-distribution entropy below
    # this fraction of log(episodes_in_buffer) — the classic silent
    # PER collapse (a handful of episodes soak all sampling mass)
    ess_min: float = 0.05
    priority_entropy_min: float = 0.1
    # attention collapse: any layer's mean attention entropy below this
    # fraction of log(n_keys) (0 = every head a delta function)
    attn_entropy_min: float = 0.05
    # per-module gradient starvation: a module's share of the total
    # gradient norm below this for a FULL window
    grad_starvation: float = 1e-6


@dataclass(frozen=True)
class ObsConfig:
    """graftscope runtime-telemetry knobs (docs/OBSERVABILITY.md). All
    host-side: nothing here touches the jitted programs, so the
    graftprog fingerprints are identical at any setting — and with
    ``enabled=False`` (the default) the driver/bench paths are
    behaviorally identical to a build without the obs layer."""

    # master switch: span recording around every watchdog-stamped
    # boundary, the spans.jsonl sink, and flight-recorder persistence
    # on stall/crash/non-finite/SIGTERM. Off by default — telemetry is
    # opt-in, parity/test configs pay nothing.
    enabled: bool = False
    # flight-recorder capacity: the last ring_size completed events
    # (plus every still-open span) survive into stall_diagnosis.json /
    # flight_recorder.json
    ring_size: int = 256
    # spans.jsonl flush cadence in events (amortizes the write syscall;
    # the flight ring covers the unflushed tail on a crash)
    flush_every: int = 32
    # attribute the jax.profiler window (profile_dir) back to the
    # registry's named programs: logs device_ms_<program> stats and
    # writes device_times.json for the report CLI. Needs profile_dir.
    program_trace: bool = False
    # Logger per-key in-memory history cap (0 = unbounded, the pre-PR-6
    # behavior): self.stats held every (t, value) pair for the life of
    # the run — unbounded host-RAM growth on long runs now that the
    # JSONL sink is the durable record. print_recent_stats only reads
    # the last 5 entries, so any cap >= 5 is observationally identical.
    stats_history: int = 1024
    # ---- graftpulse live telemetry plane (obs/pulse.py) ----------------
    # TCP port for the stdlib-only HTTP metrics endpoint (Prometheus-text
    # /metrics + JSON /healthz + /trace trigger). 0 (default) = no
    # server, no socket, driver byte-identical to a build without the
    # plane. Independent of `enabled`: the gauges need no span recorder
    # (span decoration of the scrape path simply degrades to no-ops when
    # telemetry is off).
    pulse_port: int = 0
    # bind address for the endpoint. Loopback by default: /trace is an
    # unauthenticated state-changing route (arms live profiler
    # captures), so reaching it from off-host is an explicit "0.0.0.0"
    # opt-in, never a default.
    pulse_host: str = "127.0.0.1"
    # sliding-sample window for the pulse quantile gauges (serve p50/p99
    # etc.) — bounds the hub's memory, not a statistics knob
    pulse_window: int = 512
    # HBM memwatch (obs/memwatch.py): per-device memory snapshots at
    # phase boundaries with phase-attributed high-water tracking, merged
    # into flight_recorder.json / stall_diagnosis.json. Requires
    # `enabled` (the snapshots ride the span/flight machinery — same
    # dead-knob policy as program_trace).
    memwatch: bool = False
    # graftsight learning-dynamics telemetry (obs/sight.py): in-graph
    # train-step diagnostics + host-side RL-health detectors. See
    # SightConfig — deliberately NOT gated on `enabled` (its primary
    # sink is the metrics.jsonl scalar stream, not the span plane).
    sight: "SightConfig" = field(default_factory=lambda: SightConfig())


@dataclass(frozen=True)
class SebulbaConfig:
    """Sebulba-style decoupled actor/learner (Podracer, PAPERS.md arXiv
    2104.06272; ``parallel/sebulba.py``, docs/PERF.md). The visible
    devices are partitioned into a disjoint actor set (runs the rollout)
    and learner set (owns the replay ring and the train step), with a
    bounded device-resident trajectory queue between them so both stay
    saturated instead of idling through each other's phase. Off by
    default (``actor_devices=0``): the driver is byte-identical to the
    fused/classic loop and no compiled-program fingerprint changes."""

    # disjoint device counts: devices[0:actor] act, the next `learner`
    # devices train. Both 0 = disabled; both must be set together.
    actor_devices: int = 0
    learner_devices: int = 0
    # trajectory-queue capacity in rollout batches (ring of slots on the
    # learner devices). The actor blocks putting into a full queue; the
    # learner blocks getting from an empty one. 1 + staleness=0 is the
    # lockstep mode — bit-identical to the classic K=1 loop (pinned by
    # tests/test_sebulba.py).
    queue_slots: int = 2
    # parameter-staleness bound: how many rollout batches the actor may
    # run ahead of the learner's last processed batch. 0 = lockstep
    # (every rollout waits for the params from the previous train step);
    # S > 0 lets the actor act with params up to S learner updates old —
    # the overlap that keeps both device sets busy.
    staleness: int = 1


@dataclass(frozen=True)
class PBTConfig:
    """Population-based-training exploit/explore (``population.pbt.*``,
    t2omca_tpu/population.py). Host-side select-and-perturb on the
    population axis at checkpoint-save boundaries ONLY — zero extra
    steady-state dispatches. Off by default; enabling it deliberately
    breaks the member-0/solo bit-parity contract (that is its job)."""

    enabled: bool = False
    # exploit fraction: the bottom `frac` members copy the full train
    # state of the top `frac` at each save boundary (clamped so the two
    # sets never overlap)
    frac: float = 0.25
    # explore: copied members multiply each spec leaf (lr_scale,
    # eps_scale, per_alpha) by `perturb` or `1/perturb` (coin flip,
    # deterministic in (seed, t_env))
    perturb: float = 1.2


@dataclass(frozen=True)
class PopulationConfig:
    """graftpop population axis (``population.*``, docs/POPULATION.md,
    t2omca_tpu/population.py): ``size=P`` vmaps the WHOLE training
    state — params, opt_state, replay ring + PER priorities, runner
    state, RNG keys, per-member EnvParams scenario draws — over a
    leading ``(P,)`` axis, so ONE donated superstep dispatch advances P
    seed/hyperparameter variants. ``size=0`` (default) leaves every
    compiled program byte-identical (graftprog fingerprints pinned —
    zero re-baseline). Per-member grids are tuples of length P (empty =
    replicate the base config's value, the bit-parity-neutral default);
    P=1 with empty grids is bit-identical to the classic loop and
    member 0 of any un-gridded population is bit-exactly the solo run
    at ``cfg.seed`` (tests/test_population.py)."""

    size: int = 0
    # per-member ABSOLUTE learning rates (len P; empty = cfg.lr for
    # every member). Applied as an update-tree scale of lr_i/cfg.lr —
    # exact for adam/rmsprop, where lr enters linearly after the
    # moment statistics.
    lr: Tuple[float, ...] = ()
    # per-member multipliers on the epsilon-greedy schedule (len P;
    # empty = 1.0 — bitwise-neutral)
    eps_scale: Tuple[float, ...] = ()
    # per-member ABSOLUTE PER priority exponents (len P; empty =
    # replay.per_alpha). Traced into the store-side pow — value-
    # identical to the static exponent at the default.
    per_alpha: Tuple[float, ...] = ()
    # member i seeds from cfg.seed + i*seed_stride: 1 (default) = seed
    # replication (member 0 == the solo run), 0 = identical seeds
    # (controlled grid comparisons; combine with scenario_salt below)
    seed_stride: int = 1
    # fold the member index into the graftworld scenario sampler key
    # (envs/graftworld.member_scenario_key) so members draw DIFFERENT
    # scenario instances even at seed_stride=0. Off by default: the
    # fold is not bitwise-neutral, so member 0 would no longer match
    # the solo run's env streams.
    scenario_salt: bool = False
    pbt: "PBTConfig" = field(default_factory=lambda: PBTConfig())


@dataclass(frozen=True)
class KernelsConfig:
    """Rollout hot-path kernel selection (``t2omca_tpu/kernels/``,
    docs/PERF.md). Every entry keeps the XLA lowering as the default
    with CPU-gate parity tests pinning the hand-written kernel against
    it, so flipping a switch is a performance decision, never a
    semantics one."""

    # attention kernel for MultiHeadAttention (per-agent transformer AND
    # the mixer): "xla" = the einsum→softmax→einsum path (materializes
    # the (B·A, H, Q, K) logits tensor every env step); "pallas" = the
    # fused flash-style kernel (kernels/attention.py — tiled QK^T →
    # masked online softmax → PV, f32 accumulators, logits live only in
    # VMEM). Off-TPU the pallas kernel runs in interpreter mode, which
    # is what keeps it inside the CPU tier-1 gate.
    attention: str = "xla"


@dataclass(frozen=True)
class TrainConfig:
    """Top-level run flags (reference run-control set, SURVEY.md §5.6)."""

    name: str = "qmix_transf"
    seed: int = 0
    t_max: int = 205_000
    test_interval: int = 10_000
    test_nepisode: int = 32
    log_interval: int = 10_000
    runner_log_interval: int = 10_000
    batch_size_run: int = 8               # parallel envs (vmapped, not subprocesses)
    batch_size: int = 32                  # train batch (episodes)
    accumulated_episodes: int = 0         # min episodes collected before training
    # Anakin-style fused training superstep (Podracer, PAPERS.md): K > 1
    # fuses rollout → ring insert → gated sample+train into ONE donated
    # XLA program and lax.scan-s it K iterations per dispatch — amortizing
    # the per-dispatch overhead (~0.66 s under the axon tunnel,
    # BASELINE.md) over K full train iterations and never materializing
    # the episode batch between rollout and insert (the rollout's scan
    # outputs scatter straight into the replay ring). 1 = the classic
    # three-program loop (bit-identical training either way — pinned by
    # tests/test_superstep.py). Requires the device-resident ring:
    # buffer_cpu_only configs stay on the three-program path
    # (run.superstep_eligible, the ops/query_slice.py predicate pattern).
    # Cadences (test/log/save) and preemption/checkpoint boundaries land
    # between dispatches, so they coarsen to every K iterations and a
    # preemption loses at most K iterations (docs/SPEC.md §8).
    superstep: int = 1
    use_cuda: bool = False                # parity flag; device selection is JAX's
    # data parallelism (SURVEY.md §7.2(6)): shard env lanes + replay
    # episodes over a `dp_devices`-wide mesh data axis (parallel/mesh.py);
    # 0 = single-device programs. Replaces the reference's single-device
    # select (/root/reference/per_run.py:26).
    dp_devices: int = 0
    # PRNG implementation for every key in the run: "threefry" (JAX
    # default — counter-based, reproducible across backends; all parity
    # and learning-evidence configs use it) or "rbg" (XLA
    # RngBitGenerator — the TPU hardware generator, far cheaper for the
    # rollout's many small draws: teleports, job generation, exploration
    # noise; streams differ from threefry, so trajectories are not
    # bit-comparable across the two)
    prng_impl: str = "threefry"
    evaluate: bool = False
    benchmark_mode: bool = False          # export per-episode CSV during eval
    checkpoint_path: str = ""
    load_step: int = 0
    save_model: bool = True
    save_model_interval: int = 50_000
    local_results_path: str = "results"
    use_tensorboard: bool = False
    save_replay: bool = False
    save_animation: bool = False
    animation_interval: int = 200_000
    animation_interval_evaluation: int = 0

    # tracing/profiling (capability upgrade over the reference, SURVEY.md §5(1))
    profile_dir: str = ""                 # jax.profiler trace output ("" = off)
    profile_start: int = 0                # t_env at which to start the trace
    profile_iterations: int = 3           # driver iterations to capture
    # block after each driver stage so StageTimer attributes real device
    # time instead of dispatch-enqueue time; costs one host round-trip per
    # stage (~0.66 s each under the axon tunnel), so off in production —
    # the async loop then only syncs at log/test/save cadences
    profile_stages: bool = False

    # component selection (registries, reference §5.6; agent/mixer families
    # follow the parent PyMARL lineage's registry pattern — the released
    # slice hardcodes the transformer pair)
    runner: str = "parallel"
    mac: str = "basic_mac"
    learner: str = "qmix_learner"
    env: str = "multi_agv_offloading"
    agent: str = "transformer"            # transformer | rnn
    mixer: str = "transformer"            # transformer | qmix_ff | vdn

    # learning hyperparameters (M8 spec — the learner itself is unreleased;
    # values start from the PyMARL/TransfQMIX lineage and are then pinned
    # by our 4-config x 5-seed config-1 stability sweep,
    # runs/config1_stable/SUMMARY.md: lr 5e-4 + epsilon floor 0.1 is the
    # only combination where all 5 seeds clear the +2-sigma learning bar —
    # at lr 1e-3 / floor 0.05 the greedy policy intermittently collapses
    # into the all-agents-conflict channel mode)
    gamma: float = 0.99
    lr: float = 0.0005
    optimizer: str = "adam"               # adam | rmsprop
    optim_alpha: float = 0.99             # rmsprop smoothing
    optim_eps: float = 1e-5
    grad_norm_clip: float = 10.0
    target_update_interval: int = 200     # episodes between hard target syncs
    double_q: bool = True
    # ----- loss-scale levers. Per-step rewards are O(10^2) (latency units,
    # docs/SPEC.md §1), so unweighted MSE on TD errors of that scale drives
    # grad_norm to 1e4-1e5 against grad_norm_clip=10 — every update is a
    # direction-only step of size clip*lr (measured:
    # runs/config1_stable/metrics_rbg_seed0.jsonl grad_norm=193k). Two
    # spec-level remedies, both OFF by default so reference-parity configs
    # and all committed learning evidence are byte-identical:
    # td_loss="huber": elementwise 2x-scaled Huber — td^2 inside
    # |td|<=huber_delta, 2*delta*|td|-delta^2 outside — so the quadratic
    # region matches the default MSE exactly and delta->inf recovers it.
    # The DQN-lineage gradient bound: each TD element contributes at most
    # 2*delta to dLoss/dq_tot.
    td_loss: str = "mse"                  # mse | huber
    huber_delta: float = 10.0             # Huber transition point (TD units)
    # reward_unit: divide the TRAIN-TIME reward by this constant (e.g.
    # latency_max_ms=100 makes per-step rewards O(1)); the value function
    # and the learner's logged metrics (loss/td_error_abs/target_mean)
    # are in reward/reward_unit units, while the runner's episode
    # returns/rewards stay raw. Unlike env_args.reward_scaling
    # (running-std, state-dependent — provably harmful at config 2,
    # runs/config2_scaling/SUMMARY.md) this is a static unit choice: no
    # state, no checkpoint migration, exact. Mutually exclusive with
    # reward_scaling (sanity_check) — combining would double-scale.
    reward_unit: float = 1.0

    # action selection
    action_selector: str = "epsilon_greedy"   # epsilon_greedy | noisy-new
    epsilon_start: float = 1.0
    # 0.1 floor: see the lr comment above — the residual exploration breaks
    # the symmetric conflict-mode lock-in (reference lineage uses 0.05)
    epsilon_finish: float = 0.1
    epsilon_anneal_time: int = 50_000

    env_args: EnvConfig = field(default_factory=EnvConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    replay: ReplayConfig = field(default_factory=ReplayConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    kernels: KernelsConfig = field(default_factory=KernelsConfig)
    sebulba: SebulbaConfig = field(default_factory=SebulbaConfig)
    population: PopulationConfig = field(default_factory=PopulationConfig)

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)


def sanity_check(cfg: TrainConfig) -> TrainConfig:
    """Mirror of the reference ``args_sanity_check``
    (``/root/reference/per_run.py:292-309``): round ``test_nepisode`` down to a
    multiple of ``batch_size_run`` (quirk Q10)."""
    tn = cfg.test_nepisode
    if tn < cfg.batch_size_run:
        tn = cfg.batch_size_run
    else:
        tn = (tn // cfg.batch_size_run) * cfg.batch_size_run
    if cfg.prng_impl not in ("threefry", "rbg", "unsafe_rbg"):
        raise ValueError(f"prng_impl must be threefry/rbg/unsafe_rbg, "
                         f"got {cfg.prng_impl!r}")
    if cfg.td_loss not in ("mse", "huber"):
        raise ValueError(f"td_loss must be mse/huber, got {cfg.td_loss!r}")
    if cfg.td_loss == "huber" and cfg.huber_delta <= 0:
        raise ValueError(f"huber_delta must be > 0, got {cfg.huber_delta}")
    if cfg.reward_unit <= 0:
        raise ValueError(f"reward_unit must be > 0, got {cfg.reward_unit}")
    if cfg.superstep < 1:
        raise ValueError(f"superstep must be >= 1 (1 = the unfused "
                         f"three-program loop), got {cfg.superstep}")
    if cfg.reward_unit != 1.0 and cfg.env_args.reward_scaling:
        raise ValueError(
            "reward_unit and env_args.reward_scaling are alternative "
            "reward-scale remedies; enabling both would double-scale the "
            "train-time reward (running-std AND /reward_unit) — pick one")
    if cfg.model.standard_heads:
        if cfg.model.emb % cfg.model.heads or cfg.model.mixer_emb % cfg.model.mixer_heads:
            raise ValueError(
                f"standard_heads requires emb divisible by heads: got "
                f"emb={cfg.model.emb}/heads={cfg.model.heads}, "
                f"mixer_emb={cfg.model.mixer_emb}/mixer_heads={cfg.model.mixer_heads}."
            )
    # valid family names; mirrored from controllers.AGENT_REGISTRY /
    # learners.MIXER_REGISTRY (config cannot import them — circular) and
    # pinned by tests/test_model_families.py
    _agents, _mixers = {"transformer", "rnn"}, {"transformer", "qmix_ff",
                                                "vdn"}
    if cfg.agent not in _agents:
        raise ValueError(f"unknown agent '{cfg.agent}'; valid: "
                         f"{sorted(_agents)}")
    if cfg.mixer not in _mixers:
        raise ValueError(f"unknown mixer '{cfg.mixer}'; valid: "
                         f"{sorted(_mixers)}")
    if (cfg.model.dropout > 0.0 and cfg.agent != "transformer"
            and cfg.mixer != "transformer"):
        # transformer modules implement dropout; with neither family
        # selected a configured rate would be a silent no-op. (A transformer
        # mixer alone still applies it in the mixer blocks, so rnn agent +
        # transformer mixer stays valid.)
        raise ValueError(
            "dropout is only implemented by the transformer families; "
            f"agent='{cfg.agent}' + mixer='{cfg.mixer}' configures no "
            "module that would apply it")
    if cfg.dp_devices:
        if cfg.dp_devices < 0:
            raise ValueError(f"dp_devices must be >= 0, got {cfg.dp_devices}")
        if cfg.replay.buffer_cpu_only:
            raise ValueError(
                "dp_devices shards the device-resident replay ring; "
                "buffer_cpu_only keeps storage in host RAM — pick one")
        if not cfg.population.size:
            # Under population-over-dp the mesh shards the leading (P,)
            # member axis, not episode lanes — the episode-axis invariant
            # is replaced by P % dp_devices (checked in the population
            # block below).
            check_dp_divisibility(cfg, cfg.dp_devices)
    res = cfg.resilience
    if res.nonfinite_tolerance < 0:
        raise ValueError(f"resilience.nonfinite_tolerance must be >= 0 "
                         f"(0 disables the restore escalation), got "
                         f"{res.nonfinite_tolerance}")
    if res.max_restores < 0:
        raise ValueError(f"resilience.max_restores must be >= 0, got "
                         f"{res.max_restores}")
    if res.keep_last < 0 or res.keep_every < 0:
        raise ValueError(
            f"resilience.keep_last/keep_every must be >= 0, got "
            f"keep_last={res.keep_last}, keep_every={res.keep_every}")
    if res.dispatch_timeout < 0:
        raise ValueError(f"resilience.dispatch_timeout must be >= 0 "
                         f"(0 disables the watchdog), got "
                         f"{res.dispatch_timeout}")
    if res.first_dispatch_timeout < 0:
        raise ValueError(f"resilience.first_dispatch_timeout must be >= 0 "
                         f"(0 leaves first occurrences unbounded), got "
                         f"{res.first_dispatch_timeout}")
    if res.first_dispatch_timeout > 0 and res.dispatch_timeout == 0:
        raise ValueError(
            "resilience.first_dispatch_timeout only bounds the compile-"
            "exempt FIRST occurrence of each watched phase — with "
            "dispatch_timeout=0 the watchdog is never constructed and "
            "the key is silently dead; set dispatch_timeout > 0 too")
    if res.stall_grace_s < 0:
        raise ValueError(f"resilience.stall_grace_s must be >= 0 "
                         f"(0 disables the hard exit), got "
                         f"{res.stall_grace_s}")
    if not 1 <= res.stall_exit_code <= 255:
        raise ValueError(f"resilience.stall_exit_code must be in 1..255 "
                         f"(0 means orderly exit to supervisors), got "
                         f"{res.stall_exit_code}")
    if res.dispatch_retries < 0 or res.retry_backoff_s < 0:
        raise ValueError(
            f"resilience.dispatch_retries/retry_backoff_s must be >= 0, "
            f"got dispatch_retries={res.dispatch_retries}, "
            f"retry_backoff_s={res.retry_backoff_s}")
    if res.preempt_barrier_timeout_s <= 0:
        raise ValueError(
            f"resilience.preempt_barrier_timeout_s must be > 0 (it bounds "
            f"the coordinated-preemption peer barrier against dead peers; "
            f"an unbounded wait would hang the exit path forever), got "
            f"{res.preempt_barrier_timeout_s}")
    if res.inject_nan_at_step >= 0 and res.nonfinite_tolerance == 0:
        raise ValueError(
            "resilience.inject_nan_at_step is a fault-injection knob whose "
            "point is exercising the restore escalation — enabling it with "
            "nonfinite_tolerance=0 (escalation off) tests nothing")
    o = cfg.obs
    if o.ring_size < 1:
        raise ValueError(f"obs.ring_size must be >= 1, got {o.ring_size}")
    if o.flush_every < 1:
        raise ValueError(f"obs.flush_every must be >= 1, got "
                         f"{o.flush_every}")
    if o.stats_history < 0:
        raise ValueError(f"obs.stats_history must be >= 0 (0 = "
                         f"unbounded), got {o.stats_history}")
    if o.program_trace and not cfg.profile_dir:
        raise ValueError(
            "obs.program_trace attributes the jax.profiler trace window "
            "to the registry's programs — with profile_dir empty no "
            "trace is ever captured and the key is silently dead; set "
            "profile_dir too")
    if o.program_trace and not o.enabled:
        raise ValueError(
            "obs.program_trace is part of the graftscope telemetry "
            "layer — with obs.enabled=false the master switch promises "
            "no telemetry side effects, so the combination is "
            "contradictory (same dead-knob policy as "
            "first_dispatch_timeout without dispatch_timeout); set "
            "obs.enabled=true too")
    if not 0 <= o.pulse_port <= 65535:
        raise ValueError(f"obs.pulse_port must be in 0..65535 (0 = no "
                         f"metrics endpoint), got {o.pulse_port}")
    if o.pulse_window < 16:
        raise ValueError(f"obs.pulse_window must be >= 16 (quantiles "
                         f"over fewer samples are noise), got "
                         f"{o.pulse_window}")
    if o.memwatch and not o.enabled:
        raise ValueError(
            "obs.memwatch merges its snapshots into the span/flight "
            "artifacts — with obs.enabled=false none of those exist and "
            "the key is silently dead (same policy as program_trace); "
            "set obs.enabled=true too")
    sg = o.sight
    if sg.bins < 4:
        raise ValueError(f"obs.sight.bins must be >= 4 (a histogram "
                         f"needs bins to be one), got {sg.bins}")
    if sg.td_range <= 0 or sg.q_range <= 0:
        raise ValueError(
            f"obs.sight.td_range/q_range must be > 0, got "
            f"td_range={sg.td_range}, q_range={sg.q_range}")
    if sg.window < 2:
        raise ValueError(f"obs.sight.window must be >= 2 (plateau/"
                         f"starvation detectors need history), got "
                         f"{sg.window}")
    if not 0.0 <= sg.ess_min <= 1.0:
        raise ValueError(f"obs.sight.ess_min is a fraction of the batch "
                         f"— must be in [0, 1], got {sg.ess_min}")
    if not 0.0 <= sg.priority_entropy_min <= 1.0 \
            or not 0.0 <= sg.attn_entropy_min <= 1.0:
        raise ValueError(
            f"obs.sight.priority_entropy_min/attn_entropy_min are "
            f"fractions of the max entropy — must be in [0, 1], got "
            f"{sg.priority_entropy_min}/{sg.attn_entropy_min}")
    if sg.plateau_rel < 0 or sg.q_div <= 0 or sg.grad_starvation < 0:
        raise ValueError(
            f"obs.sight thresholds out of range: plateau_rel="
            f"{sg.plateau_rel} (>= 0), q_div={sg.q_div} (> 0), "
            f"grad_starvation={sg.grad_starvation} (>= 0)")
    sb = cfg.sebulba
    if (sb.actor_devices > 0) != (sb.learner_devices > 0):
        raise ValueError(
            f"sebulba.actor_devices and sebulba.learner_devices must be "
            f"set together (both 0 disables the decoupled loop), got "
            f"actor_devices={sb.actor_devices}, "
            f"learner_devices={sb.learner_devices}")
    if sb.actor_devices < 0 or sb.learner_devices < 0:
        raise ValueError(
            f"sebulba device counts must be >= 0, got "
            f"actor_devices={sb.actor_devices}, "
            f"learner_devices={sb.learner_devices}")
    if sb.queue_slots < 1:
        raise ValueError(f"sebulba.queue_slots must be >= 1, got "
                         f"{sb.queue_slots}")
    if sb.staleness < 0:
        raise ValueError(f"sebulba.staleness must be >= 0 (0 = lockstep), "
                         f"got {sb.staleness}")
    if sb.actor_devices:
        if cfg.replay.buffer_cpu_only:
            raise ValueError(
                "sebulba runs the replay ring + train step on the learner "
                "device set; buffer_cpu_only keeps storage in host RAM — "
                "drop buffer_cpu_only (the learner mesh holds the ring) "
                "or run the classic loop for host-RAM replay")
        if cfg.dp_devices:
            raise ValueError(
                "sebulba partitions the visible devices itself (actor + "
                "learner sets); it does not compose with dp_devices — "
                "scale the actor set instead")
        if cfg.superstep > 1:
            raise ValueError(
                "sebulba decouples rollout from training onto disjoint "
                "device sets; the fused superstep re-serializes them into "
                "one program — pick one (superstep=1 under sebulba)")
        # under a population the (P,) MEMBER axis shards over each set
        # (whole members per device — the graftlattice placement), not
        # the env-lane/episode axes, so these tilings only bind at P=0
        # (the population block below checks P % set size instead)
        if not cfg.population.size:
            if cfg.batch_size_run % sb.actor_devices:
                raise ValueError(
                    f"batch_size_run={cfg.batch_size_run} must be "
                    f"divisible by sebulba.actor_devices="
                    f"{sb.actor_devices} (env lanes shard over the actor "
                    f"mesh)")
            if cfg.batch_size % sb.learner_devices \
                    or cfg.replay.buffer_size % sb.learner_devices:
                raise ValueError(
                    f"batch_size={cfg.batch_size} and replay.buffer_size="
                    f"{cfg.replay.buffer_size} must be divisible by "
                    f"sebulba.learner_devices={sb.learner_devices} "
                    f"(replay episodes shard over the learner mesh)")
    pp = cfg.population
    if pp.size < 0:
        raise ValueError(f"population.size must be >= 0 (0 = no "
                         f"population axis), got {pp.size}")
    if pp.size:
        if cfg.replay.buffer_cpu_only:
            raise ValueError(
                "the population superstep vmaps the device-resident "
                "replay ring; buffer_cpu_only keeps storage in host RAM "
                "outside any jitted program — drop buffer_cpu_only (the "
                "vmapped ring already lives on device) or train members "
                "as separate solo runs")
        if cfg.dp_devices and pp.size % cfg.dp_devices:
            # population-over-dp (graftlattice): the leading (P,) member
            # axis shards over the 'data' mesh — whole members per
            # device, so P must tile the mesh
            raise ValueError(
                f"population-over-dp shards the (P,) member axis over "
                f"the 'data' mesh (whole members per device — members "
                f"never communicate); population.size={pp.size} is not "
                f"divisible by dp_devices={cfg.dp_devices} — pick a "
                f"divisible P or drop dp_devices")
        if cfg.sebulba.actor_devices:
            sb_ = cfg.sebulba
            if sb_.queue_slots != 1 or sb_.staleness != 0:
                raise ValueError(
                    f"population x sebulba composes only in LOCKSTEP "
                    f"(queue_slots=1, staleness=0): the vmapped learner "
                    f"trains all P members behind the device-resident "
                    f"queue in publish order, and an overlapped queue "
                    f"(queue_slots={sb_.queue_slots}, staleness="
                    f"{sb_.staleness}) would let members act on params "
                    f"of different staleness — set queue_slots=1 and "
                    f"staleness=0, or drop one of population/sebulba")
            if pp.pbt.enabled:
                raise ValueError(
                    "population.pbt exploits/explores at the classic "
                    "loop's checkpoint-save boundary; the decoupled "
                    "sebulba loop cannot re-salt the actor thread's "
                    "in-flight rollouts mid-epoch — run PBT under the "
                    "classic loop (drop sebulba) or disable "
                    "population.pbt")
            for what, n in (("actor_devices", sb_.actor_devices),
                            ("learner_devices", sb_.learner_devices)):
                if pp.size % n:
                    raise ValueError(
                        f"population x sebulba shards the (P,) member "
                        f"axis over each device set; population.size="
                        f"{pp.size} is not divisible by sebulba.{what}="
                        f"{n} — pick a divisible P or shrink the set")
        if cfg.evaluate or cfg.save_replay or cfg.save_animation:
            raise ValueError(
                "population trains P stacked members; the evaluate/"
                "replay/animation paths run a single-member policy — "
                "evaluate a member by exporting its slice (docs/"
                "POPULATION.md)")
        for name, grid in (("lr", pp.lr), ("eps_scale", pp.eps_scale),
                           ("per_alpha", pp.per_alpha)):
            if grid and len(grid) != pp.size:
                raise ValueError(
                    f"population.{name} has {len(grid)} entries for "
                    f"population.size={pp.size} — per-member grids must "
                    f"have exactly P entries (or be empty = replicate)")
            if any(v <= 0 for v in grid):
                raise ValueError(f"population.{name} entries must be > 0, "
                                 f"got {grid}")
        if any(v > 1.0 for v in pp.per_alpha):
            raise ValueError(f"population.per_alpha entries must be in "
                             f"(0, 1], got {pp.per_alpha}")
        if pp.per_alpha and not cfg.replay.prioritized:
            raise ValueError(
                "population.per_alpha grids the PER exponent — with "
                "replay.prioritized=false the knob is silently dead "
                "(same policy as first_dispatch_timeout without "
                "dispatch_timeout)")
        if pp.seed_stride < 0:
            raise ValueError(f"population.seed_stride must be >= 0, got "
                             f"{pp.seed_stride}")
        if not 0.0 < pp.pbt.frac <= 0.5:
            raise ValueError(f"population.pbt.frac must be in (0, 0.5] "
                             f"(exploit/explore sets must not overlap), "
                             f"got {pp.pbt.frac}")
        if pp.pbt.perturb <= 1.0:
            raise ValueError(f"population.pbt.perturb must be > 1.0 (the "
                             f"multiplicative explore factor), got "
                             f"{pp.pbt.perturb}")
        if pp.pbt.enabled and not cfg.save_model:
            raise ValueError(
                "population.pbt runs at checkpoint-save boundaries — "
                "with save_model=false it never fires (dead-knob "
                "policy); set save_model=true too")
    if cfg.kernels.attention not in ("xla", "pallas"):
        raise ValueError(f"kernels.attention must be xla/pallas, got "
                         f"{cfg.kernels.attention!r}")
    if cfg.model.act_dtype not in ("", "float32", "bfloat16"):
        raise ValueError(
            f"model.act_dtype must be ''/float32/bfloat16 ('' inherits "
            f"model.dtype), got {cfg.model.act_dtype!r}")
    # graftworld scenario surface (env_args.scenario.*). Name sets are
    # mirrored from envs/graftworld.py (config cannot import it —
    # circular) and pinned by tests/test_graftworld.py, the same pattern
    # as the agent/mixer registries above.
    _scn_kinds = {"", "fixed", "uniform", "mixture"}
    _scn_families = {"baseline", "hetfleet", "interference", "surge"}
    _scn_fields = {"n_active", "gain_scale", "interference_w", "mec_scale",
                   "teleport_prob", "job_prob", "surge_amp", "surge_period",
                   "deadline_ms", "mec_compute_scale", "compute_scale",
                   "tx_scale"}
    scn = cfg.env_args.scenario
    if scn.kind not in _scn_kinds:
        raise ValueError(f"env_args.scenario.kind must be one of "
                         f"{sorted(_scn_kinds)}, got {scn.kind!r}")
    if scn.family not in _scn_families:
        raise ValueError(f"env_args.scenario.family must be one of "
                         f"{sorted(_scn_families)}, got {scn.family!r}")
    for f in scn.families:
        if f not in _scn_families:
            raise ValueError(f"env_args.scenario.families entry {f!r} "
                             f"unknown; valid: {sorted(_scn_families)}")
    if scn.weights and len(scn.weights) != len(scn.families or
                                               _scn_families):
        raise ValueError(
            f"env_args.scenario.weights ({len(scn.weights)}) must match "
            f"the mixture component count "
            f"({len(scn.families or _scn_families)})")
    if any(w < 0 for w in scn.weights) or (scn.weights
                                           and sum(scn.weights) <= 0):
        raise ValueError("env_args.scenario.weights must be non-negative "
                         "with a positive sum")
    for name, *bounds in tuple(scn.ranges) + tuple(scn.overrides):
        if name not in _scn_fields:
            raise ValueError(
                f"env_args.scenario knob {name!r} is not a randomizable "
                f"EnvParams field; valid: {sorted(_scn_fields)}")
        if name == "deadline_ms":
            hi = max(float(b) for b in bounds)
            lo = min(float(b) for b in bounds)
            if hi > cfg.env_args.latency_max_ms or lo <= 0:
                raise ValueError(
                    f"env_args.scenario deadline_ms values must lie in "
                    f"(0, latency_max_ms={cfg.env_args.latency_max_ms}] "
                    f"— latency_max fixes the static job-queue shape "
                    f"(got {bounds})")
        if name == "n_active":
            if (min(float(b) for b in bounds) < 1
                    or max(float(b) for b in bounds)
                    > cfg.env_args.agv_num):
                raise ValueError(
                    f"env_args.scenario n_active values must lie in "
                    f"[1, agv_num={cfg.env_args.agv_num}], got {bounds}")
    for name, lo, hi in scn.ranges:
        if not float(lo) <= float(hi):
            raise ValueError(f"env_args.scenario.ranges[{name!r}]: "
                             f"lo={lo} > hi={hi}")
    if not 0 <= scn.min_agents <= cfg.env_args.agv_num:
        raise ValueError(
            f"env_args.scenario.min_agents must be in "
            f"[0, agv_num={cfg.env_args.agv_num}], got {scn.min_agents}")
    if cfg.mixer == "transformer" and cfg.model.mixer_emb != cfg.model.emb:
        raise ValueError(
            "mixer_emb must equal emb: the transformer mixer concatenates "
            "agent hidden tokens (dim emb) with its own embeddings (dim "
            "mixer_emb) (reference n_transf_mixer.py:69)."
        )
    return cfg.replace(test_nepisode=tn)


def check_dp_divisibility(cfg: TrainConfig, n: int,
                          axis_label: str = "dp_devices") -> None:
    """The data-parallel shape invariant, shared by ``sanity_check`` (early,
    at config load) and ``parallel.DataParallel`` (late, at mesh build):
    every episode-axis quantity must split evenly over the mesh."""
    if (cfg.batch_size_run % n or cfg.batch_size % n
            or cfg.replay.buffer_size % n):
        raise ValueError(
            f"batch_size_run={cfg.batch_size_run}, "
            f"batch_size={cfg.batch_size} and "
            f"replay.buffer_size={cfg.replay.buffer_size} must all be "
            f"divisible by {axis_label}={n}")


def _coerce_scenario(base: ScenarioConfig, kw: dict) -> ScenarioConfig:
    """Normalize a scenario dict (YAML lists, JSON round trips) onto the
    tuple-typed frozen ScenarioConfig."""
    kw = dict(kw)
    if "ranges" in kw:
        kw["ranges"] = tuple(
            (str(n), float(lo), float(hi)) for n, lo, hi in kw["ranges"])
    if "overrides" in kw:
        kw["overrides"] = tuple(
            (str(n), float(v)) for n, v in kw["overrides"])
    if "families" in kw:
        kw["families"] = tuple(str(f) for f in kw["families"])
    if "weights" in kw:
        kw["weights"] = tuple(float(w) for w in kw["weights"])
    return dataclasses.replace(base, **kw)


def _merge_nested(cfg: TrainConfig, updates: dict) -> TrainConfig:
    """Merge a (possibly nested) dict of overrides into the config tree."""
    env_kw = dict(updates.pop("env_args", {}) or {})
    model_kw = dict(updates.pop("model", {}) or {})
    replay_kw = dict(updates.pop("replay", {}) or {})
    resilience_kw = dict(updates.pop("resilience", {}) or {})
    obs_kw = dict(updates.pop("obs", {}) or {})
    kernels_kw = dict(updates.pop("kernels", {}) or {})
    sebulba_kw = dict(updates.pop("sebulba", {}) or {})
    # `population: 4` (bare int, YAML/CLI shorthand) means {size: 4} —
    # the ISSUE-15 config surface; a dict/PopulationConfig is the full
    # block form
    pop_raw = updates.pop("population", None)
    if isinstance(pop_raw, PopulationConfig):
        pop_raw = dataclasses.asdict(pop_raw)
    if isinstance(pop_raw, (int, float)) and not isinstance(pop_raw, bool):
        pop_raw = {"size": int(pop_raw)}
    population_kw = dict(pop_raw or {})

    # route flat keys to their sub-config for reference-style flat configs
    env_fields = {f.name for f in dataclasses.fields(EnvConfig)}
    model_fields = {f.name for f in dataclasses.fields(ModelConfig)}
    replay_fields = {f.name for f in dataclasses.fields(ReplayConfig)}
    resilience_fields = {f.name for f in dataclasses.fields(ResilienceConfig)}
    obs_fields = {f.name for f in dataclasses.fields(ObsConfig)}
    kernels_fields = {f.name for f in dataclasses.fields(KernelsConfig)}
    sebulba_fields = {f.name for f in dataclasses.fields(SebulbaConfig)}
    top_fields = {f.name for f in dataclasses.fields(TrainConfig)}
    flat = dict(updates)
    for k, v in flat.items():
        if k in top_fields:
            continue
        if k in model_fields:
            model_kw.setdefault(k, v)
            updates.pop(k)
        elif k in replay_fields:
            replay_kw.setdefault(k, v)
            updates.pop(k)
        elif k in env_fields:
            env_kw.setdefault(k, v)
            updates.pop(k)
        elif k in resilience_fields:
            resilience_kw.setdefault(k, v)
            updates.pop(k)
        elif k in obs_fields:
            obs_kw.setdefault(k, v)
            updates.pop(k)
        elif k in kernels_fields:
            kernels_kw.setdefault(k, v)
            updates.pop(k)
        elif k in sebulba_fields:
            sebulba_kw.setdefault(k, v)
            updates.pop(k)
        else:
            raise KeyError(f"unknown config key: {k}")

    if env_kw:
        # scenario sub-tree: a nested dict (YAML), dotted keys (CLI
        # `env_args.scenario.kind=...` arrives here as "scenario.kind"),
        # or an already-built ScenarioConfig (from_dict re-entry)
        scn_kw = env_kw.pop("scenario", None)
        scn_kw = ({} if scn_kw is None
                  else dataclasses.asdict(scn_kw)
                  if isinstance(scn_kw, ScenarioConfig) else dict(scn_kw))
        for k in [k for k in env_kw if k.startswith("scenario.")]:
            scn_kw[k.split(".", 1)[1]] = env_kw.pop(k)
        if scn_kw:
            env_kw["scenario"] = _coerce_scenario(cfg.env_args.scenario,
                                                  scn_kw)
        updates["env_args"] = dataclasses.replace(cfg.env_args, **env_kw)
    if model_kw:
        updates["model"] = dataclasses.replace(cfg.model, **model_kw)
    if replay_kw:
        updates["replay"] = dataclasses.replace(cfg.replay, **replay_kw)
    if resilience_kw:
        updates["resilience"] = dataclasses.replace(cfg.resilience,
                                                    **resilience_kw)
    if obs_kw:
        # sight sub-tree: a nested dict (YAML), dotted keys (CLI
        # `obs.sight.enabled=...` arrives here as "sight.enabled"), or
        # an already-built SightConfig (from_dict re-entry) — the
        # env_args.scenario pattern
        sight_kw = obs_kw.pop("sight", None)
        sight_kw = ({} if sight_kw is None
                    else dataclasses.asdict(sight_kw)
                    if isinstance(sight_kw, SightConfig) else dict(sight_kw))
        for k in [k for k in obs_kw if k.startswith("sight.")]:
            sight_kw[k.split(".", 1)[1]] = obs_kw.pop(k)
        if sight_kw:
            obs_kw["sight"] = dataclasses.replace(cfg.obs.sight, **sight_kw)
        updates["obs"] = dataclasses.replace(cfg.obs, **obs_kw)
    if kernels_kw:
        updates["kernels"] = dataclasses.replace(cfg.kernels, **kernels_kw)
    if sebulba_kw:
        updates["sebulba"] = dataclasses.replace(cfg.sebulba, **sebulba_kw)
    if population_kw:
        # pbt sub-tree: a nested dict (YAML), dotted keys (CLI
        # `population.pbt.enabled=...` arrives here as "pbt.enabled"),
        # or an already-built PBTConfig (from_dict re-entry)
        pbt_kw = population_kw.pop("pbt", None)
        pbt_kw = ({} if pbt_kw is None
                  else dataclasses.asdict(pbt_kw)
                  if isinstance(pbt_kw, PBTConfig) else dict(pbt_kw))
        for k in [k for k in population_kw if k.startswith("pbt.")]:
            pbt_kw[k.split(".", 1)[1]] = population_kw.pop(k)
        if pbt_kw:
            population_kw["pbt"] = dataclasses.replace(cfg.population.pbt,
                                                       **pbt_kw)
        # YAML lists → the frozen tuples the hashable config needs
        for k in ("lr", "eps_scale", "per_alpha"):
            if k in population_kw:
                population_kw[k] = tuple(float(v)
                                         for v in population_kw[k])
        updates["population"] = dataclasses.replace(cfg.population,
                                                    **population_kw)
    return cfg.replace(**updates)


def from_dict(data: dict) -> TrainConfig:
    """Rebuild a TrainConfig from its ``dataclasses.asdict`` form (the
    serving artifact's ``meta.json`` round trip, serve/export.py) —
    defaults → nested dict → the same sanity pass as every other
    construction path, so a config that trained is a config that
    loads."""
    return sanity_check(_merge_nested(TrainConfig(), dict(data)))


def _coerce(s: str) -> Any:
    if s.lower() in ("true", "false"):
        return s.lower() == "true"
    for cast in (int, float):
        try:
            return cast(s)
        except ValueError:
            pass
    return s


def load_config(path: Optional[str] = None,
                overrides: Tuple[str, ...] = ()) -> TrainConfig:
    """defaults → file → ``key=value`` / ``section.key=value`` overrides."""
    cfg = TrainConfig()
    if path:
        with open(path) as f:
            if path.endswith((".yaml", ".yml")):
                import yaml  # baked into the image via other deps; gated import
                data = yaml.safe_load(f)
            else:
                data = json.load(f)
        cfg = _merge_nested(cfg, data or {})
    updates: dict = {}
    for ov in overrides:
        k, _, v = ov.partition("=")
        val = _coerce(v)
        if "." in k:
            sec, sub = k.split(".", 1)
            if sec == "population" and isinstance(updates.get(sec),
                                                 (int, float)):
                # the bare-int shorthand already stored —
                # `population=4 population.seed_stride=1` — lift it to
                # its dict form so the dotted key composes instead of
                # crashing on int.__setitem__
                updates[sec] = {"size": int(updates[sec])}
            updates.setdefault(sec, {})[sub] = val
        elif (k == "population" and isinstance(updates.get(k), dict)
                and not isinstance(val, dict)):
            # the reversed order: dotted keys first, then the bare-int
            # shorthand — merge instead of silently replacing the dict
            # (dropping `population.seed_stride=0` would turn a
            # controlled grid comparison into divergent seeds with no
            # error)
            updates[k]["size"] = int(val)
        else:
            updates[k] = val
    cfg = _merge_nested(cfg, updates)
    return sanity_check(cfg)


def unique_token(cfg: TrainConfig) -> str:
    """Run-naming scheme of the reference (``/root/reference/per_run.py:42``):
    ``{name}_seed{seed}_{map}_{datetime}``."""
    import datetime

    ts = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
    return f"{cfg.name}_seed{cfg.seed}_{cfg.env_args.map_name}_{ts}"
