"""Multi-AGV task-offloading environment as a pure function of pytrees.

TPU-native re-creation of ``MultiAgvOffloadingEnv``
(``/root/reference/environment_multi_mec.py:9-471``, C1): every 5 ms slot each
AGV either computes its head-of-queue job locally (action 0) or transmits it
over one of ``num_channels`` uplink channels to its serving MEC (actions
1..C); two AGVs picking the same channel under the same MEC collide (quirk
Q14: channels are reusable across MECs). Reward trades offload-latency
savings against deadline misses.

Where the reference is a Python object farmed out to subprocesses over Pipes
(``parallel_runner.py:21-32``), this is a ``reset``/``step`` pair of pure
functions over an ``EnvState`` pytree: ``jax.vmap`` gives thousands of envs
per chip, ``lax.scan`` gives the episode time axis, and the whole rollout
fuses into one XLA program — there is no IPC tier to replace.

Semantics preserved exactly (SURVEY.md §2.1/§7.5):

* step pipeline order (``:309-366``): one-hot last_action → per-MEC bincount
  collision resolution (counts>1 zeroed) → ACK ∈ {0 local, 1 success, −1
  collision} → reward (uses *pre-teleport* positions) → per-agent update
  (teleport mobility Q6, queue pop/age/expire/generate) → terminal info.
* reward branches (``:229-293``): see ``_reward``; the ``access_reward`` is
  computed but excluded from the returned reward (quirk Q3).
* observations: per-agent ``[last_ack, agent_inf(5)]`` or entity mode
  ``[ack_onehot(3), agent_inf(5), is_self]`` rows masked to same-MEC agents
  (``:148-182``); obs pass through a per-env Welford normalizer updated on
  every call including evaluation (Q4/Q5).
* job queues: the reference's Python lists with mid-list deletion
  (``:300-307``) become fixed-shape ``(max_jobs,)`` masked arrays with
  identical within-slot ordering — pop head → age all → drop expired →
  maybe generate (SURVEY.md §7.4(1)); ``max_jobs = latency_max/5 + 1``
  (bound stated at ``:90``).

Missing-module contracts supplied here (SURVEY.md §2.3): M1 (MEC/AGV/Job as
arrays; parameter values pinned in docs/SPEC.md), M2 (CRITIC, ``critic.py``),
M13 (uniform point in a circle), C2 (normalization as carried state).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..components.transforms import one_hot
from ..config import EnvConfig
from .critic import critic
from .normalization import (NormState, apply_norm, normalize,
                            normalize_batch, select_update,
                            welford_update_batch_factored)


def _round(x: jnp.ndarray, decimals: int = 0) -> jnp.ndarray:
    """Banker's rounding, matching python/numpy ``round`` in the reference."""
    return jnp.round(x, decimals)


@struct.dataclass
class EnvParams:
    """Per-instance scenario knobs (graftworld, docs/ENVS.md): every leaf
    is a jnp array, so the pytree vmaps alongside :class:`EnvState` — one
    compiled ``reset``/``step`` serves every scenario in a sampled
    distribution with zero extra dispatches and no per-family recompile
    (the JaxMARL/NAVIX parameterized-env pattern, PAPERS.md).

    The **default values are exactly the fixed scenario the physics
    constants below encode**: every knob enters the math as a
    multiply-by-1 / add-0 / all-true-mask neutral element, so
    ``env.default_params()`` reproduces the pre-graftworld env
    BIT-identically (pinned by tests/test_graftworld.py goldens). Knob
    groups:

    * fleet size — ``n_active`` of the static ``agv_num`` maximum; the
      rest are padded agents (no jobs, action 0 only, zero reward, a
      unique negative ``mec_index`` sentinel so they are invisible in
      every same-MEC visibility/collision structure);
    * channel fading / interference — linear SNR multiplier +
      additive interference power on the noise floor;
    * MEC placement & AGV mobility — placement stretch and per-step
      teleport probability (1.0 = the reference's always-teleport Q6);
    * job-arrival regime — base Bernoulli rate plus a sinusoidal
      surge modulation (non-stationary traffic);
    * deadline distribution — per-instance deadline budget (bounded by
      the static ``latency_max_ms``, which fixes the queue shape);
    * heterogeneous fleets — per-AGV compute/transmit capability
      scales (the first (A,)-shaped knobs);
    * ``family`` — the scenario-family tag carried through rollout
      stats for per-slice generalization eval (utils/stats.py).
    """

    n_active: jnp.ndarray           # () int32 — active AGVs (rest padded)
    gain_scale: jnp.ndarray         # () f32 — linear channel-gain multiplier
    interference_w: jnp.ndarray     # () f32 — adversarial interference [W]
    mec_scale: jnp.ndarray          # () f32 — MEC placement stretch
    teleport_prob: jnp.ndarray      # () f32 — per-step AGV teleport prob
    job_prob: jnp.ndarray           # () f32 — base job-arrival rate
    surge_amp: jnp.ndarray          # () f32 — traffic-surge amplitude
    surge_period: jnp.ndarray       # () f32 — surge period [slots]
    deadline_ms: jnp.ndarray        # () f32 — job deadline budget
    mec_compute_scale: jnp.ndarray  # () f32 — MEC compute-cap multiplier
    compute_scale: jnp.ndarray      # (A,) f32 — per-AGV compute capability
    tx_scale: jnp.ndarray           # (A,) f32 — per-AGV transmit power
    family: jnp.ndarray             # () int32 — scenario family/bucket id

    def agent_mask(self, n_agents: int) -> jnp.ndarray:
        """(A,) bool — True for active agents, False for padded ones."""
        return jnp.arange(n_agents) < self.n_active


@struct.dataclass
class EnvState:
    """Per-env dynamic state (one vmap lane = one reference subprocess env)."""

    time_slot: jnp.ndarray        # () int32
    mec_index: jnp.ndarray        # (A,) int32 — serving MEC per AGV
    pos: jnp.ndarray              # (A, 2) float32 — AGV positions [m]
    job_data: jnp.ndarray         # (A, J) float32 — data sizes [bits]
    job_deadline: jnp.ndarray     # (A, J) float32 — remaining deadline [ms]
    job_valid: jnp.ndarray        # (A, J) bool
    last_ack: jnp.ndarray         # (A,) int32 ∈ {-1, 0, 1}
    last_action: jnp.ndarray      # (A,) int32
    task_num: jnp.ndarray         # (A,) int32 — jobs generated
    task_success: jnp.ndarray     # (A,) int32 — jobs finished in deadline
    remain_delay: jnp.ndarray     # (A,) float32 — completion-delay accumulator
    norm: NormState               # obs Welford stats (shared across agents, Q4)


@struct.dataclass
class StepInfo:
    """Fixed-key ``info`` dict equivalent (SURVEY.md §5.5 metric contract)."""

    reward: jnp.ndarray
    delay_reward: jnp.ndarray
    overtime_penalty: jnp.ndarray
    channel_utilization_rate: jnp.ndarray
    conflict_ratio: jnp.ndarray
    episode_limit: jnp.ndarray          # bool: terminated due to time limit
    task_completion_rate: jnp.ndarray   # valid when episode_limit
    task_completion_delay: jnp.ndarray  # valid when episode_limit
    # deadline-miss rate: generated jobs neither completed in deadline nor
    # still queued, / generated (graftworld per-slice eval metric — counts
    # late local/offload completions AND queue-expired drops exactly once)
    deadline_miss_rate: jnp.ndarray


@dataclasses.dataclass(frozen=True)
class MultiAgvOffloadingEnv:
    """Static physics + topology; hashable, so ``jit`` can close over it.

    Physics constants are the reference's (``environment_multi_mec.py:49-57``);
    M1 parameter values (compute caps, transmit power, job distribution) are
    the pinned spec of docs/SPEC.md.
    """

    cfg: EnvConfig

    # ---- constants (reference :49-54)
    computation_cycles: float = 31250.0   # cycles/bit
    bandwidth: float = 5e6                # Hz
    noise_power: float = 1e-11            # W
    path_loss_base: float = 3.0           # NB reference uses base-3, not dB→10
    channel_gain_db: float = 5.0
    t_length: float = 5.0                 # ms/slot

    # ---- derived sizes
    @property
    def n_agents(self) -> int:
        return self.cfg.agv_num

    @property
    def n_mec(self) -> int:
        return self.cfg.mec_num

    @property
    def n_actions(self) -> int:
        return self.cfg.num_channels + 1

    @property
    def max_jobs(self) -> int:
        # latency_max/5 + 1 (reference :90): a job survives ≤ latency_max/5
        # slots after its generation slot, and ≤1 job is generated per slot.
        return int(self.cfg.latency_max_ms / self.t_length) + 1

    @property
    def obs_entity_feats(self) -> int:
        return 9  # ack_onehot(3) + agent_inf(5) + is_self(1)

    @property
    def state_entity_feats(self) -> int:
        # ack_onehot(3) + agent_inf(5); with state_last_action the per-agent
        # action one-hot joins the state (reference env_info arithmetic
        # divides the flat state length by n_agents, :435-438)
        if self.cfg.state_last_action:
            return 8 + self.n_actions
        return 8

    @property
    def obs_dim(self) -> int:
        if self.cfg.obs_entity_mode:
            return self.n_agents * self.obs_entity_feats
        return 6  # [last_ack, agent_inf(5)]

    @property
    def state_dim(self) -> int:
        return self.n_agents * self.state_entity_feats

    def default_params(self) -> EnvParams:
        """The fixed reference scenario as an :class:`EnvParams` instance:
        every knob is the neutral element of the expression it enters, so
        running with these is bit-identical to the pre-graftworld env
        (pinned golden digests in tests/test_graftworld.py)."""
        a = self.n_agents
        return EnvParams(
            n_active=jnp.asarray(a, jnp.int32),
            gain_scale=jnp.asarray(1.0, jnp.float32),
            interference_w=jnp.asarray(0.0, jnp.float32),
            mec_scale=jnp.asarray(1.0, jnp.float32),
            teleport_prob=jnp.asarray(1.0, jnp.float32),
            job_prob=jnp.asarray(self.cfg.job_prob, jnp.float32),
            surge_amp=jnp.asarray(0.0, jnp.float32),
            surge_period=jnp.asarray(40.0, jnp.float32),
            deadline_ms=jnp.asarray(self.cfg.latency_max_ms, jnp.float32),
            mec_compute_scale=jnp.asarray(1.0, jnp.float32),
            compute_scale=jnp.ones((a,), jnp.float32),
            tx_scale=jnp.ones((a,), jnp.float32),
            family=jnp.asarray(0, jnp.int32),
        )

    def _p(self, params: "EnvParams | None") -> EnvParams:
        """Resolve the optional ``params`` argument: None = the fixed
        default scenario (keeps every pre-graftworld call site valid)."""
        return self.default_params() if params is None else params

    def mec_positions(self, params: "EnvParams | None" = None) -> jnp.ndarray:
        """MECs on a line at spacing 2*radius (reference :23-28), stretched
        by ``params.mec_scale`` (1.0 = reference placement, bit-exact)."""
        r = self.cfg.mec_radius_m
        xs = np.arange(self.n_mec) * (2 * r) + r
        ys = np.full(self.n_mec, r)
        base = jnp.asarray(np.stack([xs, ys], axis=1), jnp.float32)
        if params is None:
            return base
        return base * params.mec_scale

    # ------------------------------------------------------------------ helpers

    def _random_positions(self, key: jax.Array, mec_index: jnp.ndarray,
                          params: EnvParams) -> jnp.ndarray:
        """M13: uniform point inside the serving MEC's communication circle."""
        k1, k2 = jax.random.split(key)
        a = self.n_agents
        u = jax.random.uniform(k1, (a,))
        theta = jax.random.uniform(k2, (a,), maxval=2 * np.pi)
        rad = self.cfg.communication_range_m * jnp.sqrt(u)
        offset = jnp.stack([rad * jnp.cos(theta), rad * jnp.sin(theta)], axis=1)
        return self.mec_positions(params)[mec_index] + offset

    def _local_delay(self, data: jnp.ndarray, decimals: int,
                     params: EnvParams) -> jnp.ndarray:
        """Local compute delay in ms (reference :127, :247-248); the cap is
        scaled per-AGV by ``params.compute_scale`` (heterogeneous fleets).
        The knob divides the reference expression as a TRAILING step:
        XLA rewrites the reference's divide-by-constant caps into
        reciprocal multiplies, so folding the scale into the divisor
        would change the lowering (and the bits) even at scale=1 —
        appending ``/ scale`` keeps the default path's ops identical
        (/1.0 is exact) and the parity goldens green."""
        return _round(self.computation_cycles * data
                      / self.cfg.user_compute_cap * 1000.0
                      / params.compute_scale, decimals)

    def _offload_delay(self, data: jnp.ndarray, pos: jnp.ndarray,
                       mec_index: jnp.ndarray,
                       params: EnvParams) -> jnp.ndarray:
        """Shannon-rate transmit delay + MEC compute delay in ms
        (reference ``calculate_offload_delay`` :106-121). Note the quirk kept
        verbatim: path-loss linearization uses base ``self.path_loss`` (=3),
        i.e. ``3 ** (-dB/10)``, not ``10 ** (-dB/10)`` (:112). graftworld
        knobs enter as TRAILING neutral operations on the reference
        expressions (multiply by 1 / divide by 1, exact): per-AGV transmit
        scale and channel-fading gain multiply the reference SNR,
        interference divides it by ``1 + I/N0`` (algebraically the lifted
        noise floor ``N0 + I``), MEC compute delay divides by the cap
        scale — so the default (1/1/0/1) path runs the reference ops
        bit-identically (see the ``_local_delay`` lowering note)."""
        gain_lin = 10.0 ** (self.channel_gain_db / 10.0)
        d = jnp.linalg.norm(pos - self.mec_positions(params)[mec_index],
                            axis=-1)
        pl_db = 128.1 + 37.6 * jnp.log10(d + 0.1)
        pl_lin = self.path_loss_base ** (-pl_db / 10.0)
        snr = (gain_lin * self.cfg.transmit_power_w * pl_lin
               / self.noise_power
               * params.gain_scale * params.tx_scale
               / (1.0 + params.interference_w / self.noise_power))
        rate = self.bandwidth * jnp.log2(1.0 + snr)
        transmit = data / rate * 1000.0
        compute = (self.computation_cycles * data
                   / self.cfg.mec_compute_cap) * 1000.0 \
            / params.mec_compute_scale
        return _round(transmit + compute, 2)

    def _agent_inf(self, state: EnvState, params: EnvParams) -> jnp.ndarray:
        """Per-agent feature rows ``[data_size, data_delay, offload_delay,
        remaining_delay, buffer_length]`` (reference ``get_agent_inf``
        :123-146), zeros for empty buffers (padded agents never hold a
        job, so their rows are zero by the same gate)."""
        has_job = state.job_valid[:, 0]
        data = state.job_data[:, 0]
        inf = jnp.stack([
            data,
            self._local_delay(data, 0, params),
            self._offload_delay(data, state.pos, state.mec_index, params),
            state.job_deadline[:, 0],
            state.job_valid.sum(axis=1).astype(jnp.float32),
        ], axis=1)
        return jnp.where(has_job[:, None], inf, 0.0)

    @staticmethod
    def _ack_onehot(last_ack: jnp.ndarray) -> jnp.ndarray:
        """ack_mapping {-1:[1,0,0], 0:[0,1,0], 1:[0,0,1]} (reference :7);
        built with the M15 OneHot transform."""
        return one_hot(last_ack + 1, 3)

    # ------------------------------------------------------------------ obs/state

    def _entity_parts(self, state: EnvState, params: EnvParams
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Factored entity obs pieces: feature ``rows (A, 8)`` and the
        ``same_mec (A, A)`` visibility mask. Padded agents carry a unique
        negative ``mec_index`` sentinel (set at reset/teleport), so the
        equality mask makes them visible only to themselves — the SAME
        rule the compact-entity storage path reconstructs from the stored
        ``mec_index`` (ops/query_slice.py), with zero schema change."""
        inf = self._agent_inf(state, params)
        ack1h = self._ack_onehot(state.last_ack)
        rows = jnp.concatenate([ack1h, inf], axis=1)               # (A, 8)
        same_mec = state.mec_index[:, None] == state.mec_index[None, :]
        return rows, same_mec

    def _raw_obs(self, state: EnvState, params: EnvParams) -> jnp.ndarray:
        """(A, obs_dim) pre-normalization observations."""
        if self.cfg.obs_entity_mode:
            a = self.n_agents
            rows, same_mec = self._entity_parts(state, params)
            ent = jnp.where(same_mec[:, :, None],
                            jnp.broadcast_to(rows[None], (a, a, 8)), 0.0)
            is_self = jnp.eye(a)[:, :, None]       # diagonal is always same-MEC
            ent = jnp.concatenate([ent, is_self], axis=2)          # (A, A, 9)
            return ent.reshape(a, a * self.obs_entity_feats)
        inf = self._agent_inf(state, params)
        return jnp.concatenate(
            [state.last_ack[:, None].astype(jnp.float32), inf], axis=1)

    def get_obs(self, state: EnvState, params: "EnvParams | None" = None,
                update_norm: bool = True) -> Tuple[EnvState, jnp.ndarray]:
        """Normalized per-agent observations. Default path: the Welford
        state is updated agent-by-agent in order, each agent normalized with
        the statistics *after its own update* — exactly the reference's
        sequential ``[self.obs_norm(self.get_obs_agent(i)) for i in
        range(n)]`` (``:184-186``, quirks Q4/Q5). With ``cfg.fast_norm`` the
        A-step sequential scan (the env-step serialization bottleneck at 64
        agents) becomes one order-free batched merge; equivalence-tolerance
        test in ``tests/test_normalization.py``."""
        params = self._p(params)
        if self.cfg.fast_norm and self.cfg.obs_entity_mode:
            # statistics from the FACTORED form (O(A·F), exact up to
            # reassociation — normalization.welford_update_batch_factored);
            # the normalized obs tensor is still produced from the
            # materialized raw matrix, but when no consumer reads it (the
            # entity-table acting + compact-storage stack) XLA dead-code
            # eliminates the whole O(A²) materialization from the rollout
            rows, same_mec = self._entity_parts(state, params)
            norm = select_update(
                state.norm,
                welford_update_batch_factored(state.norm, rows, same_mec),
                update_norm)
            obs = apply_norm(norm, self._raw_obs(state, params))
            return state.replace(norm=norm), obs

        raw = self._raw_obs(state, params)

        if self.cfg.fast_norm:
            norm, obs = normalize_batch(state.norm, raw, update=update_norm)
            return state.replace(norm=norm), obs

        def body(carry: NormState, x):
            carry, y = normalize(carry, x, update=update_norm)
            return carry, y

        norm, obs = jax.lax.scan(body, state.norm, raw)
        return state.replace(norm=norm), obs

    def compact_obs(self, state: EnvState,
                    params: "EnvParams | None" = None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                               jnp.ndarray]:
        """Factored form of the entity observation for the entity-table
        acting path (``ops/query_slice.agent_forward_qslice_entity``):
        ``(rows (A, 8), same_mec (A, A) bool, mean (A, 9), std (A, 9))``.

        The full entity obs (``_raw_obs``) is ``A`` copies of the same 8
        feature rows under the same-MEC visibility mask plus an is-self
        diagonal; with ``fast_norm`` every agent row is normalized by the
        SAME per-position statistics (one shared ``NormState``, Q4), so
        ``(rows, mask, stats)`` reconstructs every agent's normalized obs
        exactly (pinned in tests/test_entity_tables.py). Must be called on
        the post-``get_obs`` state (its ``norm`` already updated) — the
        runner calls it on the state ``step``/``reset`` returned. Only
        valid for ``obs_entity_mode`` + ``fast_norm`` (the sequential
        normalizer gives each agent different prefix statistics)."""
        assert self.cfg.obs_entity_mode and self.cfg.fast_norm
        rows, same_mec = self._entity_parts(state, self._p(params))
        a = self.n_agents
        mean = state.norm.mean.reshape(a, self.obs_entity_feats)
        std = state.norm.std.reshape(a, self.obs_entity_feats)
        return rows, same_mec, mean, std

    def get_state(self, state: EnvState,
                  params: "EnvParams | None" = None) -> jnp.ndarray:
        """Global state: all-agent ACK one-hots ++ all-agent agent_inf rows,
        flattened (reference ``get_state`` :188-204); not normalized. With
        ``state_last_action`` the per-agent action one-hots are prepended —
        the reference declares the flag (:11) and keeps the concat slot
        commented (:196); wiring it preserves that config surface."""
        params = self._p(params)
        ack1h = self._ack_onehot(state.last_ack)
        inf = self._agent_inf(state, params)
        parts = [ack1h.reshape(-1), inf.reshape(-1)]
        if self.cfg.state_last_action:
            # M15 OneHot: the reference stores np.eye(n_actions)[actions]
            # (:318) and would concat it here (:196)
            parts.insert(0, one_hot(state.last_action,
                                    self.n_actions).reshape(-1))
        return jnp.concatenate(parts)

    def get_avail_actions(self, state: EnvState,
                          params: "EnvParams | None" = None) -> jnp.ndarray:
        """(A, n_actions) availability (reference :61-82): empty buffer ⇒ only
        action 0; ``edge_only`` forbids local compute when a job exists.
        Padded agents are masked to action 0 EVERYWHERE — they can never
        hold a job (the generator is mask-gated), but the explicit mask
        pins the invariant against any future job-path change."""
        params = self._p(params)
        has_job = state.job_valid[:, 0]
        idle_only = jnp.concatenate(
            [jnp.ones((self.n_agents, 1)),
             jnp.zeros((self.n_agents, self.n_actions - 1))], axis=1)
        if self.cfg.edge_only:
            busy = jnp.concatenate(
                [jnp.zeros((self.n_agents, 1)),
                 jnp.ones((self.n_agents, self.n_actions - 1))], axis=1)
        else:
            busy = jnp.ones((self.n_agents, self.n_actions))
        avail = jnp.where(has_job[:, None], busy, idle_only)
        mask = params.agent_mask(self.n_agents)
        return jnp.where(mask[:, None], avail, idle_only).astype(jnp.int32)

    def get_critic_score(self, state: EnvState, key: jax.Array,
                         params: "EnvParams | None" = None) -> jnp.ndarray:
        """CRITIC indicator matrix [task_prior, queueing-delay ratio,
        buffer-fill ratio] (+1e-6-scale noise) → per-agent scores (reference
        ``get_critic_score`` :84-104). ``task_prior`` is 1.0 for all AGVs in
        the released slice's single-type fleet (docs/SPEC.md); queueing delay
        is ``latency_max - remaining_deadline`` of the head job. The
        queueing-delay ratio is against the instance's deadline budget
        (``params.deadline_ms``, = latency_max at default); the fill
        ratio keeps the STATIC latency_max — it is the queue-capacity
        bound, a shape property. Padded agents score zero through the
        has-job gate (they never hold a job)."""
        params = self._p(params)
        has_job = state.job_valid[:, 0]
        lm = params.deadline_ms
        prior = jnp.where(has_job, 1.0, 0.0)
        # reciprocal-multiply, not division: XLA lowers the reference's
        # divide-by-constant-lm to exactly this form, so the traced-lm
        # default stays bit-identical (tests/test_graftworld.py goldens)
        delay_q = jnp.where(has_job,
                            (lm - state.job_deadline[:, 0]) * (1.0 / lm),
                            0.0)
        fill = jnp.where(
            has_job,
            state.job_valid.sum(axis=1)
            / (self.cfg.latency_max_ms / self.t_length + 1), 0.0)
        mat = jnp.stack([prior, delay_q, fill], axis=1)
        noise = 1e-6 * _round(jax.random.uniform(
            key, mat.shape, minval=0.9, maxval=1.1), 2)
        return critic(mat + noise)

    # ------------------------------------------------------------------ queues

    def _generate_jobs(self, state: EnvState, key: jax.Array,
                       params: EnvParams) -> EnvState:
        """``AGV.generate_job`` (M1 spec): with prob ``job_prob`` append a job
        ``(data ~ U[min,max] bits, deadline = params.deadline_ms)``; count it
        in ``task_num``. graftworld regime knobs: the arrival rate is the
        instance's ``job_prob`` modulated by a sinusoidal surge
        (non-stationary traffic; ``amp=0`` multiplies by exactly 1), and
        padded agents never generate (mask-gated). Defaults keep the
        Bernoulli draw bit-identical — same uniform draw, same threshold
        value."""
        k1, k2 = jax.random.split(key)
        a, j = self.n_agents, self.max_jobs
        p_eff = jnp.clip(
            params.job_prob
            * (1.0 + params.surge_amp
               * jnp.sin(2.0 * np.pi * state.time_slot.astype(jnp.float32)
                         / params.surge_period)), 0.0, 1.0)
        gen = jax.random.bernoulli(k1, p_eff, (a,)) \
            & params.agent_mask(a)
        data_new = jax.random.uniform(
            k2, (a,), minval=self.cfg.data_size_min,
            maxval=self.cfg.data_size_max)
        cnt = state.job_valid.sum(axis=1)
        slot = (jnp.arange(j)[None, :] == cnt[:, None]) & gen[:, None] \
            & (cnt[:, None] < j)
        return state.replace(
            job_data=jnp.where(slot, data_new[:, None], state.job_data),
            job_deadline=jnp.where(slot, params.deadline_ms,
                                   state.job_deadline),
            job_valid=state.job_valid | slot,
            task_num=state.task_num + gen.astype(jnp.int32),
        )

    def _pad_sentinel(self, mec_index: jnp.ndarray,
                      params: EnvParams) -> jnp.ndarray:
        """Give every padded agent a UNIQUE negative serving-MEC index.
        One representation covers every padding consumer: the same-MEC
        equality mask makes padded agents visible only to themselves (and
        the compact-entity store reconstructs the identical visibility
        from the stored ``mec_index`` with no schema change), and the
        collision histogram's ``one_hot`` maps out-of-range indices to
        zero rows, so padded agents never occupy a channel or count
        toward utilization. All-active (the default) selects the real
        indices bit-identically."""
        a = self.n_agents
        return jnp.where(params.agent_mask(a), mec_index,
                         -1 - jnp.arange(a, dtype=mec_index.dtype))

    def _update_users(self, state: EnvState, ack: jnp.ndarray,
                      key: jax.Array, params: EnvParams) -> EnvState:
        """``update_users`` per agent (reference :295-307), vectorized:
        teleport mobility (Q6), then pop head on ACK≠−1, age all deadlines by
        5 ms, drop expired, maybe generate. Ordering is load-bearing
        (SURVEY.md §7.4(1)). graftworld mobility: each agent teleports with
        ``params.teleport_prob`` (1.0 = the reference's unconditional
        teleport — the gate draw comes from a ``fold_in`` side key, so the
        reference key stream and the selected values are bit-identical)."""
        k_mec, k_pos, k_gen = jax.random.split(key, 3)

        # Q6: i.i.d. teleport, serving MEC redrawn uniformly. The teleport
        # gate key is folded off the parent key, NOT split from it — a
        # fourth split would re-pair the threefry counters and change
        # every draw above even at the default
        new_mec = jax.random.randint(k_mec, (self.n_agents,), 0, self.n_mec)
        new_pos = self._random_positions(k_pos, new_mec, params)
        tel = jax.random.uniform(
            jax.random.fold_in(key, 7), (self.n_agents,)) \
            < params.teleport_prob
        new_mec = jnp.where(tel, new_mec, state.mec_index)
        new_pos = jnp.where(tel[:, None], new_pos, state.pos)
        new_mec = self._pad_sentinel(new_mec, params)

        # pop head job where ACK != -1 (local compute or successful offload)
        popped = (ack != -1) & state.job_valid[:, 0]
        shift = lambda arr, fill: jnp.concatenate(
            [arr[:, 1:], jnp.full_like(arr[:, :1], fill)], axis=1)
        data = jnp.where(popped[:, None], shift(state.job_data, 0.0),
                         state.job_data)
        deadline = jnp.where(popped[:, None],
                             shift(state.job_deadline, 0.0),
                             state.job_deadline)
        valid = jnp.where(popped[:, None], shift(state.job_valid, False),
                          state.job_valid)

        # age all remaining jobs by one slot; drop expired (deadline <= 0)
        deadline = deadline - self.t_length
        keep = valid & (deadline > 0)
        # compact survivors to the front in FIFO order: destination slot =
        # exclusive prefix count of kept jobs (cumsum is monotone over the
        # source order, so stability is free), realized as a one-hot gather
        # matmul — cheaper on TPU than a stable argsort's sorting network
        dest = jnp.cumsum(keep, axis=1) - 1                   # (A, J)
        j = self.max_jobs
        gather = (jnp.where(keep, dest, -1)[:, :, None]
                  == jnp.arange(j)[None, None, :])            # (A, Jsrc, Jdst)
        # HIGHEST precision: the default TPU matmul runs the MXU in bf16,
        # which would lossily round job payload sizes every step — the
        # compaction must stay an exact permutation like the take_along_axis
        # it replaces
        gf = gather.astype(jnp.float32)
        hp = jax.lax.Precision.HIGHEST
        data = jnp.einsum("aj,ajd->ad", data, gf, precision=hp)
        deadline = jnp.einsum("aj,ajd->ad", deadline, gf, precision=hp)
        valid = gather.any(axis=1)

        state = state.replace(mec_index=new_mec, pos=new_pos, job_data=data,
                              job_deadline=deadline, job_valid=valid)
        return self._generate_jobs(state, k_gen, params)

    # ------------------------------------------------------------------ reward

    def _reward(self, state: EnvState, ack: jnp.ndarray, params: EnvParams
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, EnvState]:
        """Reference ``get_reward`` (:229-293), vectorized over the six
        branches. Uses pre-teleport positions and pre-update queues. Also
        applies the task_success/remain_delay counter side-effects the
        reference performs inside the reward pass. Padded agents
        contribute exactly zero: they never hold a job, so every branch
        mask is False for them. The per-miss penalty and the completion-
        delay bookkeeping use the instance's deadline budget
        (``params.deadline_ms`` — the value every job was stamped with)."""
        has_job = state.job_valid[:, 0]
        data = state.job_data[:, 0]
        deadline = state.job_deadline[:, 0]
        lm = params.deadline_ms

        local_delay = self._local_delay(data, 2, params)      # round(x, 2)
        offload_delay = self._offload_delay(data, state.pos,
                                            state.mec_index, params)

        is_local = has_job & (ack == 0)
        is_collision = has_job & (ack == -1)
        is_offload = has_job & (ack == 1)

        local_ok = is_local & (deadline - local_delay > 0)
        local_miss = is_local & ~(deadline - local_delay > 0)
        collision_expiring = is_collision & (deadline - self.t_length <= 0)
        offload_ok = is_offload & (deadline - offload_delay > 0)
        offload_miss = is_offload & ~(deadline - offload_delay > 0)

        delay_reward = jnp.where(is_offload, local_delay - offload_delay,
                                 0.0).sum()
        overtime = (jnp.where(local_miss | collision_expiring | offload_miss,
                              lm, 0.0)).sum()

        success = local_ok | offload_ok
        finish_delay = jnp.where(local_ok, local_delay, offload_delay)
        new_success = state.task_success + success.astype(jnp.int32)
        new_remain = state.remain_delay + jnp.where(
            success, lm - deadline + finish_delay, 0.0)

        reward = delay_reward - overtime                       # Q3: access_reward unused
        state = state.replace(task_success=new_success, remain_delay=new_remain)
        return reward, delay_reward, overtime, state

    # ------------------------------------------------------------------ API

    def reset(self, key: jax.Array, norm: NormState | None = None,
              params: "EnvParams | None" = None
              ) -> Tuple[EnvState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """→ (state, obs, global_state, avail_actions). Mirrors reference
        ``reset``/``reset_user`` (:206-227): fresh positions, empty buffers,
        one ``generate_job`` call, zero ACK/last_action; obs normalizer
        persists across resets (it lives for the life of the subprocess in
        the reference — pass the previous episode's ``norm`` to carry it).
        ``params`` selects the scenario instance (graftworld, docs/ENVS.md);
        None = the fixed default scenario, bit-identical to pre-graftworld."""
        params = self._p(params)
        k_mec, k_pos, k_gen = jax.random.split(key, 3)
        a, j = self.n_agents, self.max_jobs
        mec_index = self._pad_sentinel(
            jax.random.randint(k_mec, (a,), 0, self.n_mec), params)
        state = EnvState(
            time_slot=jnp.zeros((), jnp.int32),
            mec_index=mec_index,
            pos=self._random_positions(k_pos, mec_index, params),
            job_data=jnp.zeros((a, j), jnp.float32),
            job_deadline=jnp.zeros((a, j), jnp.float32),
            job_valid=jnp.zeros((a, j), bool),
            last_ack=jnp.zeros((a,), jnp.int32),
            last_action=jnp.zeros((a,), jnp.int32),
            task_num=jnp.zeros((a,), jnp.int32),
            task_success=jnp.zeros((a,), jnp.int32),
            remain_delay=jnp.zeros((a,), jnp.float32),
            norm=NormState.create(self.obs_dim) if norm is None else norm,
        )
        state = self._generate_jobs(state, k_gen, params)
        state, obs = self.get_obs(state, params)
        return (state, obs, self.get_state(state, params),
                self.get_avail_actions(state, params))

    def fresh_norm(self, state: EnvState) -> EnvState:
        return state.replace(norm=NormState.create(self.obs_dim))

    def step(self, state: EnvState, actions: jnp.ndarray, key: jax.Array,
             params: "EnvParams | None" = None, update_norm: bool = True
             ) -> Tuple[EnvState, jnp.ndarray, jnp.ndarray, StepInfo,
                        jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """→ (state', reward, terminated, info, obs', global_state', avail').

        The reference worker protocol returns next-step obs/state/avail with
        the current-step reward (``parallel_runner.py:247-256``); this fuses
        both into one call. ``params`` is the lane's scenario instance —
        constant through the episode, resampled at reset by the runner's
        scenario distribution (graftworld)."""
        params = self._p(params)
        mask = params.agent_mask(self.n_agents)
        actions = actions.astype(jnp.int32)

        # per-MEC collision resolution (reference :319-326; Q14). The
        # (mec, action) histogram is a one-hot einsum rather than a
        # scatter-add: one MXU matmul instead of A serialized scatter
        # updates per env; f32 accumulation is exact for counts < 2^24.
        mec1h = one_hot(state.mec_index, self.n_mec)          # (A, M)
        act1h = one_hot(actions, self.n_actions)              # (A, C)
        counts = jnp.einsum("am,ac->mc", mec1h, act1h,
                            precision=jax.lax.Precision.HIGHEST
                            ).astype(jnp.int32)
        masked = jnp.where(counts > 1, 0, counts)
        # utilization sums ALL slots incl. action-0 (reference :327-329 quirk)
        utilization = masked.sum() / (self.cfg.num_channels * self.n_mec)

        chosen = masked[state.mec_index, actions]
        # explicit int32: a weak-typed ack in the carried state would give
        # the rollout program weak output avals and force a second compile
        # when the driver chains the state back in. Padded agents are
        # pinned to ack 0 — their sentinel mec_index wraps the histogram
        # gather, so the raw lookup could read any row
        ack = jnp.where(actions == 0, 0,
                        jnp.where(chosen == 1, 1, -1)).astype(jnp.int32)
        ack = jnp.where(mask, ack, 0)
        # reciprocal-multiply over the ACTIVE count: the reference's
        # ``.mean()`` lowers div-by-constant-A to exactly this form, so
        # the all-active default is bit-identical while padded scenarios
        # divide by the true fleet size
        conflict_ratio = (ack == -1).astype(jnp.float32).sum() \
            * (1.0 / params.n_active.astype(jnp.float32))

        state = state.replace(
            time_slot=state.time_slot + 1,
            last_action=actions,
            last_ack=ack,
        )

        reward, delay_reward, overtime, state = self._reward(state, ack,
                                                             params)
        state = self._update_users(state, ack, key, params)

        terminated = state.time_slot >= self.cfg.episode_limit
        tn = state.task_num.sum()
        ts = state.task_success.sum()
        # deadline misses = generated − completed-in-deadline − still
        # queued: late local/offload completions and queue-expired drops
        # each leave the queue exactly once, so each missed job is
        # counted exactly once (per-slice eval metric, docs/ENVS.md)
        queued = state.job_valid.sum()
        info = StepInfo(
            reward=reward,
            delay_reward=delay_reward,
            overtime_penalty=overtime,
            channel_utilization_rate=utilization,
            conflict_ratio=conflict_ratio,
            episode_limit=terminated,
            task_completion_rate=ts / jnp.maximum(tn, 1),
            task_completion_delay=state.remain_delay.sum()
            / jnp.maximum(ts, 1),
            deadline_miss_rate=(tn - ts - queued) / jnp.maximum(tn, 1),
        )

        state, obs = self.get_obs(state, params, update_norm=update_norm)
        return (state, reward, terminated, info, obs,
                self.get_state(state, params),
                self.get_avail_actions(state, params))

    def get_env_info(self) -> Dict[str, int]:
        """Reference ``get_env_info`` (:421-439); copied onto args by the
        driver (``per_run.py:112-114``)."""
        info = {
            "state_shape": self.state_dim,
            "obs_shape": self.obs_dim,
            "n_actions": self.n_actions,
            "n_agents": self.n_agents,
            "episode_limit": self.cfg.episode_limit,
            "n_entities": self.n_agents,
        }
        if self.cfg.obs_entity_mode:
            info["obs_entity_feats"] = self.obs_entity_feats
        if self.cfg.state_entity_mode:
            info["state_entity_feats"] = self.state_entity_feats
        return info
