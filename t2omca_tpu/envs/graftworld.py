"""graftworld: vmapped, domain-randomized scenario distributions.

ROADMAP item 3 (JaxMARL / NAVIX, PAPERS.md): the registry used to hold
ONE MEC-offload scenario with fixed parameters, so the "as many
scenarios as you can imagine" north star was untested beyond a single
configuration. graftworld lifts the frozen knobs into the
:class:`~t2omca_tpu.envs.mec_offload.EnvParams` pytree (which vmaps
alongside ``EnvState``) and supplies the sampling layer above it:

* **scenario families** — named parameter regimes implemented as
  EnvParams-driven variants sharing the ONE core ``step``: ``baseline``
  (the reference scenario), ``hetfleet`` (heterogeneous per-AGV
  compute/transmit capability), ``interference`` (adversarial channel
  interference + degraded fading), ``surge`` (non-stationary sinusoidal
  traffic surges). No family introduces control flow — every variant is
  purely parametric, so a mixture over families runs in one compiled
  program with zero per-family recompiles.
* **distributions** — :class:`FixedScenario` (one fixed parameter
  point), :class:`UniformScenario` (uniform ranges over named knobs),
  :class:`MixtureScenario` (weighted mixture over family
  distributions). All are frozen/hashable dataclasses, so jitted
  programs close over them as static structure; ``sample(key, env)``
  is traced — each env lane draws its own scenario at reset inside the
  rollout program (zero extra dispatches).
* **per-slice eval** — every sample carries its family id in
  ``EnvParams.family``; the runner threads it into ``RolloutStats.
  scenario`` and the stats accumulators report return / deadline-miss /
  collision rates PER family slice (utils/stats.py, ``obs report``),
  measuring generalization instead of a mixture-blurred mean.

Config surface: ``env_args.scenario.*`` (config.ScenarioConfig; YAML
exemplar configs/config6_scenarios.yaml); registry wiring:
``envs/registry.py`` (each env key carries a default scenario).
Contract: docs/ENVS.md.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .mec_offload import EnvParams, MultiAgvOffloadingEnv

#: family order IS the ``EnvParams.family`` id assignment — stable,
#: append-only (the per-slice metric keys and the report CLI's name
#: column depend on it; ``obs/report.py`` mirrors this tuple to stay
#: jax-free, pinned by tests/test_graftworld.py)
FAMILY_NAMES: Tuple[str, ...] = ("baseline", "hetfleet", "interference",
                                 "surge")
FAMILY_IDS: Dict[str, int] = {n: i for i, n in enumerate(FAMILY_NAMES)}

#: per-family canonical FIXED parameter points (``kind: fixed`` with a
#: non-baseline family): deterministic, key-free presets — hetfleet uses
#: a linspace capability gradient instead of random draws so a fixed
#: scenario is actually fixed
FAMILY_FIXED: Dict[str, Tuple[Tuple[str, object], ...]] = {
    "baseline": (),
    "hetfleet": (("compute_scale", ("linspace", 0.5, 2.0)),
                 ("tx_scale", ("linspace", 2.0, 0.5))),
    "interference": (("interference_w", 4e-11), ("gain_scale", 0.7)),
    "surge": (("surge_amp", 0.8), ("surge_period", 40.0)),
}

#: per-family default UNIFORM ranges (``kind: uniform`` with no explicit
#: ``ranges``): the domain-randomization envelope each family trains
#: over. Bounds live in the same units as the EnvParams leaf.
FAMILY_RANGES: Dict[str, Tuple[Tuple[str, float, float], ...]] = {
    "baseline": (),
    "hetfleet": (("compute_scale", 0.5, 2.0), ("tx_scale", 0.5, 2.0)),
    "interference": (("interference_w", 1e-11, 8e-11),
                     ("gain_scale", 0.4, 1.0)),
    "surge": (("surge_amp", 0.4, 1.0), ("surge_period", 20.0, 80.0),
              ("job_prob", 0.3, 0.7)),
}

#: EnvParams leaves a distribution may randomize / override (``family``
#: is assigned by the distribution, never listed). ``config.
#: sanity_check`` mirrors this tuple (it cannot import this module —
#: circular); tests/test_graftworld.py pins the mirror.
RANDOMIZABLE_FIELDS: Tuple[str, ...] = (
    "n_active", "gain_scale", "interference_w", "mec_scale",
    "teleport_prob", "job_prob", "surge_amp", "surge_period",
    "deadline_ms", "mec_compute_scale", "compute_scale", "tx_scale",
)


def _base_params(env: MultiAgvOffloadingEnv, family: str,
                 overrides: Tuple[Tuple[str, object], ...]) -> EnvParams:
    """Family-tagged default params + the family's fixed preset + caller
    overrides (override values may be scalars or, for (A,)-shaped
    leaves, the ``("linspace", lo, hi)`` gradient form)."""
    p = env.default_params()
    updates = {"family": jnp.asarray(FAMILY_IDS[family], jnp.int32)}
    for name, value in tuple(FAMILY_FIXED[family]) + tuple(overrides):
        leaf = getattr(p, name)
        if isinstance(value, tuple) and value and value[0] == "linspace":
            updates[name] = jnp.linspace(float(value[1]), float(value[2]),
                                         leaf.shape[0], dtype=leaf.dtype)
        else:
            updates[name] = jnp.broadcast_to(
                jnp.asarray(value, leaf.dtype), leaf.shape)
    return p.replace(**updates)


def _sample_n_active(key: jax.Array, env: MultiAgvOffloadingEnv,
                     min_agents: int) -> jnp.ndarray:
    """Uniform fleet size in [min_agents, agv_num] (the padding axis)."""
    return jax.random.randint(key, (), min_agents, env.n_agents + 1,
                              dtype=jnp.int32)


@dataclasses.dataclass(frozen=True)
class ScenarioDistribution:
    """Base: a hashable (jit-static) sampler of EnvParams instances."""

    def sample(self, key: jax.Array, env: MultiAgvOffloadingEnv
               ) -> EnvParams:
        raise NotImplementedError

    def families(self) -> Tuple[str, ...]:
        """Family names this distribution can emit (per-slice eval)."""
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class FixedScenario(ScenarioDistribution):
    """One fixed parameter point: the family's canonical preset plus
    ``overrides``. ``min_agents > 0`` still randomizes the fleet size
    (it is the padding axis, orthogonal to the family knobs)."""

    family: str = "baseline"
    overrides: Tuple[Tuple[str, float], ...] = ()
    min_agents: int = 0

    def sample(self, key: jax.Array, env: MultiAgvOffloadingEnv
               ) -> EnvParams:
        p = _base_params(env, self.family, self.overrides)
        if self.min_agents:
            p = p.replace(n_active=_sample_n_active(
                jax.random.fold_in(key, 0), env, self.min_agents))
        return p

    def families(self) -> Tuple[str, ...]:
        return (self.family,)


@dataclasses.dataclass(frozen=True)
class UniformScenario(ScenarioDistribution):
    """Uniform draws over named knob ranges, on top of the family's
    defaults + ``overrides``. Empty ``ranges`` means the family's
    canonical envelope (:data:`FAMILY_RANGES`). (A,)-shaped knobs draw
    i.i.d. per agent; ``n_active`` draws an integer fleet size."""

    family: str = "baseline"
    ranges: Tuple[Tuple[str, float, float], ...] = ()
    overrides: Tuple[Tuple[str, float], ...] = ()
    min_agents: int = 0

    def effective_ranges(self) -> Tuple[Tuple[str, float, float], ...]:
        return self.ranges or FAMILY_RANGES[self.family]

    def sample(self, key: jax.Array, env: MultiAgvOffloadingEnv
               ) -> EnvParams:
        p = _base_params(env, self.family, self.overrides)
        updates = {}
        # fold_in per field index: adding a range never reshuffles the
        # draws of the ranges before it
        for i, (name, lo, hi) in enumerate(self.effective_ranges()):
            k = jax.random.fold_in(key, i + 1)
            leaf = getattr(p, name)
            if name == "n_active":
                updates[name] = jax.random.randint(
                    k, (), int(lo), int(hi) + 1, dtype=jnp.int32)
            else:
                updates[name] = jax.random.uniform(
                    k, leaf.shape, leaf.dtype, minval=float(lo),
                    maxval=float(hi))
        if self.min_agents and "n_active" not in updates:
            updates["n_active"] = _sample_n_active(
                jax.random.fold_in(key, 0), env, self.min_agents)
        return p.replace(**updates)

    def families(self) -> Tuple[str, ...]:
        return (self.family,)


@dataclasses.dataclass(frozen=True)
class MixtureScenario(ScenarioDistribution):
    """Weighted mixture over component distributions: draw a component
    index, sample every component, select the drawn one leaf-wise — a
    ``jnp.stack`` + gather, so the mixture is ONE traced program (no
    per-family branch, no recompile; acceptance criterion of ISSUE 11)."""

    components: Tuple[ScenarioDistribution, ...] = ()
    weights: Tuple[float, ...] = ()

    def sample(self, key: jax.Array, env: MultiAgvOffloadingEnv
               ) -> EnvParams:
        if not self.components:
            raise ValueError("MixtureScenario needs >= 1 component")
        n = len(self.components)
        w = (jnp.asarray(self.weights, jnp.float32) if self.weights
             else jnp.full((n,), 1.0 / n, jnp.float32))
        k_pick, k_sample = jax.random.split(key)
        idx = jax.random.choice(k_pick, n, p=w / w.sum())
        cand = [c.sample(jax.random.fold_in(k_sample, i), env)
                for i, c in enumerate(self.components)]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *cand)
        return jax.tree.map(lambda s: s[idx], stacked)

    def families(self) -> Tuple[str, ...]:
        seen = []
        for c in self.components:
            for f in c.families():
                if f not in seen:
                    seen.append(f)
        return tuple(seen)


def family_distribution(name: str, min_agents: int = 0
                        ) -> ScenarioDistribution:
    """The canonical per-family distribution: baseline is its fixed
    point, every other family is its uniform envelope — the defaults
    the registry's family env keys train over."""
    if name not in FAMILY_IDS:
        raise KeyError(f"unknown scenario family {name!r}; known: "
                       f"{list(FAMILY_NAMES)}")
    if name == "baseline":
        return FixedScenario(min_agents=min_agents)
    return UniformScenario(family=name, min_agents=min_agents)


def make_distribution(scn) -> ScenarioDistribution:
    """``config.ScenarioConfig`` → distribution (the YAML/CLI surface:
    ``env_args.scenario.kind`` fixed | uniform | mixture). Validation
    beyond ``config.sanity_check``'s jax-free mirror happens here."""
    kind = scn.kind or "fixed"    # "" = registry-default sentinel; a
    # bare config resolves through registry.scenario_config first, so
    # reaching here with "" means "the fixed point of scn.family"
    if kind == "fixed":
        return FixedScenario(family=scn.family, overrides=scn.overrides,
                             min_agents=scn.min_agents)
    if kind == "uniform":
        return UniformScenario(family=scn.family, ranges=scn.ranges,
                               overrides=scn.overrides,
                               min_agents=scn.min_agents)
    if kind == "mixture":
        fams = scn.families or FAMILY_NAMES
        return MixtureScenario(
            components=tuple(family_distribution(f, scn.min_agents)
                             for f in fams),
            weights=tuple(scn.weights))
    raise ValueError(f"unknown scenario kind {kind!r}; "
                     f"valid: fixed/uniform/mixture")


def member_scenario_key(key: jax.Array, member: jnp.ndarray) -> jax.Array:
    """graftpop per-member scenario decorrelation
    (``population.scenario_salt``): fold the population member index
    into the scenario sampler key, so vmapped members draw DIFFERENT
    EnvParams instances from the SAME distribution even when their seed
    streams are replicated (``population.seed_stride=0``). A plain
    ``fold_in`` — never a split — for the same reason as the runner's
    ``_SCENARIO_SALT``: splitting would re-pair the threefry counters
    of the existing key chain. Deliberately NOT applied by default:
    ``fold_in(key, 0)`` is not the identity, so member 0 would stop
    matching the solo run's env streams."""
    return jax.random.fold_in(key, member)


def distribution_can_pad(dist: ScenarioDistribution,
                         n_agents: int) -> bool:
    """STATIC predicate: can ``dist`` ever draw ``n_active < n_agents``
    (i.e. produce padded agents)? Drives the learner's mixer-side
    padding mask (learners/qmix_learner.py) as a config-static gate —
    distributions that never pad (every pre-graftworld config, the
    audit config) leave the loss program byte-identical, so the
    graftprog fingerprints of the hot train programs never move for
    them (ROADMAP item 3's open remainder, ISSUE 15 satellite)."""
    if isinstance(dist, MixtureScenario):
        return any(distribution_can_pad(c, n_agents)
                   for c in dist.components)
    min_agents = getattr(dist, "min_agents", 0)
    if min_agents and min_agents < n_agents:
        return True
    for name, value in getattr(dist, "overrides", ()):
        # n_active is a scalar leaf — a ("linspace", ...) form here
        # would be a config error, never a padding opt-in
        if (name == "n_active" and not isinstance(value, tuple)
                and float(value) < n_agents):
            return True
    if isinstance(dist, UniformScenario):
        for name, lo, _hi in dist.effective_ranges():
            if name == "n_active" and float(lo) < n_agents:
                return True
    return False


def register_audit_programs(ctx):
    """graftprog registry hook: the vmapped PARAMETERIZED env programs,
    lowered over a mixture spanning every family — the scenario-path
    cost surface. Ratcheting ``env_reset``/``env_step`` in
    analysis/programs.json means a scenario-induced FLOPs/bytes
    regression (a family knob acquiring an accidental O(A²) term, say)
    fails the graftprog gate statically (ISSUE 11 satellite)."""
    from ..analysis.registry import AuditProgram
    env = ctx.exp.env
    cfg = ctx.cfg
    b = cfg.batch_size_run
    dist = MixtureScenario(components=tuple(
        family_distribution(f) for f in FAMILY_NAMES))

    def _sample(keys):
        return jax.vmap(lambda k: dist.sample(k, env))(keys)

    def _env_reset(keys, norms, params):
        return jax.vmap(env.reset)(keys, norms, params)

    def _env_step(states, actions, keys, params):
        return jax.vmap(env.step)(states, actions, keys, params)

    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    keys = jax.ShapeDtypeStruct((b,) + key.shape, key.dtype)
    params = jax.eval_shape(_sample, keys)
    norms = ctx.ts_shape.runner.env_states.norm
    states = ctx.ts_shape.runner.env_states
    actions = jax.ShapeDtypeStruct((b, env.n_agents), jnp.int32)
    return {
        "env_reset": AuditProgram(
            jax.jit(_env_reset), (keys, norms, params),
            description="vmapped parameterized env reset (graftworld "
                        "EnvParams, all-family mixture avals)"),
        "env_step": AuditProgram(
            jax.jit(_env_step), (states, actions, keys, params),
            description="vmapped parameterized env step (graftworld "
                        "EnvParams, all-family mixture avals)"),
    }
