"""CRITIC multi-criteria weighting (M2).

The reference imports ``critic(matrix)`` for per-agent scheduling scores
(``/root/reference/environment_multi_mec.py:3,101``); the module is not
released. This implements the standard CRITIC method (Criteria Importance
Through Intercriteria Correlation, Diakoulaki 1995), which SURVEY.md §2.3
pins as the contract: weight_j ∝ std_j · Σ_k (1 − r_jk) over min-max
normalized criteria, scores = normalized matrix · weights.

NaN-robustness (the reference guards against NaN at call-site
``environment_multi_mec.py:102-104``): degenerate columns (zero range or zero
std) are handled with epsilons instead of producing NaN.
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-12


def critic(matrix: jnp.ndarray) -> jnp.ndarray:
    """matrix ``(n_agents, n_criteria)`` → scores ``(n_agents,)``."""
    x = jnp.asarray(matrix, dtype=jnp.float32)
    lo = x.min(axis=0, keepdims=True)
    hi = x.max(axis=0, keepdims=True)
    xn = (x - lo) / jnp.maximum(hi - lo, _EPS)

    std = xn.std(axis=0)                                  # population std
    xc = xn - xn.mean(axis=0, keepdims=True)
    cov = (xc.T @ xc) / xn.shape[0]
    denom = jnp.maximum(std[:, None] * std[None, :], _EPS)
    corr = cov / denom

    info = std * (1.0 - corr).sum(axis=1)                 # C_j
    weights = info / jnp.maximum(info.sum(), _EPS)
    return xn @ weights
