"""Running normalization as pure functions on carried state (C2).

Re-creates ``/root/reference/normalization.py`` with its two quirks
(SURVEY.md §7.5):

* **Q5** — the Welford update's *first* sample sets ``std = x`` (not 0)
  (``normalization.py:16-18``), so the first normalized output is exactly 0
  via ``(x - x)/(x + 1e-8)``.
* **Q4** — the observation normalizer is updated on every call, including
  evaluation (``environment_multi_mec.py:184-186``); callers here decide by
  passing ``update``.

The reference keeps one mutable ``Normalization`` object per env subprocess;
here the statistics are a ``NormState`` pytree carried inside ``EnvState`` so
each vmapped env keeps independent statistics (SURVEY.md §7.4(3)).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class NormState:
    """Welford running statistics (reference ``RunningMeanStd``)."""

    n: jnp.ndarray       # scalar int32 sample count
    mean: jnp.ndarray    # (dim,)
    s: jnp.ndarray       # (dim,) sum of squared deviations
    std: jnp.ndarray     # (dim,)

    @classmethod
    def create(cls, dim: int) -> "NormState":
        # three DISTINCT zero buffers, not one shared array: a freshly
        # created state may be donated whole (the fused superstep donates
        # the full TrainState), and XLA rejects donating the same buffer
        # through two leaves ("donate twice in Execute")
        z = lambda: jnp.zeros((dim,), jnp.float32)
        return cls(n=jnp.zeros((), jnp.int32), mean=z(), s=z(), std=z())


def welford_update(state: NormState, x: jnp.ndarray) -> NormState:
    """One ``RunningMeanStd.update`` step (``normalization.py:12-22``)."""
    n1 = state.n + 1
    first = n1 == 1
    new_mean = jnp.where(first, x, state.mean + (x - state.mean) / n1)
    new_s = jnp.where(first, state.s,
                      state.s + (x - state.mean) * (x - new_mean))
    new_std = jnp.where(first, x, jnp.sqrt(new_s / n1))   # Q5: first std = x
    return NormState(n=n1, mean=new_mean, s=new_s, std=new_std)


def normalize(state: NormState, x: jnp.ndarray,
              update=True) -> Tuple[NormState, jnp.ndarray]:
    """``Normalization.__call__`` (``normalization.py:29-35``): optionally
    update, then normalize with the (post-update) statistics. ``update`` may
    be a Python bool or a traced scalar bool (so evaluation rollouts can flip
    it inside one jitted program)."""
    state = select_update(state, welford_update(state, x), update)
    return state, apply_norm(state, x)


def welford_update_batch(state: NormState, xs: jnp.ndarray) -> NormState:
    """Order-free batched Welford: merge ``A`` samples ``xs (A, dim)`` into
    the running statistics in ONE update (Chan et al. parallel combine).

    Algebraically identical to ``A`` sequential ``welford_update`` calls for
    ``n >= 1`` (the merge recurrences telescope); the only deviations from
    the reference's sequential per-agent loop
    (``/root/reference/environment_multi_mec.py:184-186``) are (a) the Q5
    first-sample ``std = x`` quirk is skipped when starting from ``n == 0``
    (std becomes the true batch std immediately) and (b) callers normalize
    every sample with the post-merge statistics rather than each sample with
    its own prefix — an ``O(A/n)`` transient that vanishes as ``n`` grows
    (equivalence-tolerance test: ``tests/test_normalization.py``).

    This replaces an ``A``-step sequential scan of tiny updates on the env
    hot path with one batched op (the scan was the env-step serialization
    bottleneck at 64 agents — VERDICT r2 Weak #1)."""
    a = xs.shape[0]
    bmean = xs.mean(axis=0)
    bs = ((xs - bmean) ** 2).sum(axis=0)
    return _welford_merge(state, a, bmean, bs)


def _welford_merge(state: NormState, a: int, bmean: jnp.ndarray,
                   bs: jnp.ndarray) -> NormState:
    """Chan-style merge of precomputed batch statistics (count ``a``,
    mean ``bmean``, sum of squared deviations ``bs``)."""
    n1 = state.n + jnp.asarray(a, state.n.dtype)
    # correction terms in f32: the int32 product n·A would wrap after
    # ~2^31/A samples and poison the variance with NaNs
    nf = state.n.astype(jnp.float32)
    bnf = jnp.float32(a)
    n1f = nf + bnf
    delta = bmean - state.mean
    # state.n == 0 ⇒ the merge reduces to the batch statistics exactly
    new_mean = state.mean + delta * bnf / n1f
    new_s = state.s + bs + delta ** 2 * (nf * bnf / n1f)
    new_std = jnp.sqrt(new_s / n1f)
    return NormState(n=n1, mean=new_mean, s=new_s, std=new_std)


def welford_update_batch_factored(state: NormState, rows: jnp.ndarray,
                                  same_mec: jnp.ndarray) -> NormState:
    """``welford_update_batch`` over the ENTITY-STRUCTURED batch without
    materializing it: the ``A`` samples are the rows of the entity obs
    matrix, whose position ``(j, f<F-1)`` holds ``same_mec[i, j] *
    rows[j, f]`` and whose last feature is the is-self indicator δ_ij
    (``envs/mec_offload._raw_obs``). Batch mean and squared-deviation sums
    reduce to closed forms in the per-entity visible count — O(A·F) work
    instead of O(A²·F):

        cnt_j   = Σ_i same_mec[i, j]
        bmean   = rows_j · cnt_j / A
        bs      = cnt_j (rows_j − bmean)² + (A − cnt_j) bmean²
        is-self: bmean = 1/A,  bs = (A−1)/A

    Exact up to float reassociation vs the materialized update
    (tests/test_normalization.py)."""
    a = rows.shape[0]
    cnt = same_mec.sum(axis=0).astype(jnp.float32)            # (A,)
    frac = (cnt / a)[:, None]
    bmean_f = rows * frac                                     # (A, F-1)
    bs_f = (cnt[:, None] * (rows - bmean_f) ** 2
            + (a - cnt)[:, None] * bmean_f ** 2)
    bmean_s = jnp.full((a, 1), 1.0 / a, jnp.float32)
    bs_s = jnp.full((a, 1), (a - 1.0) / a, jnp.float32)
    bmean = jnp.concatenate([bmean_f, bmean_s], axis=1).reshape(-1)
    bs = jnp.concatenate([bs_f, bs_s], axis=1).reshape(-1)
    return _welford_merge(state, a, bmean, bs)


def select_update(state: NormState, updated: NormState,
                  update) -> NormState:
    """Pick the updated statistics per the ``update`` flag, which may be a
    Python bool or a traced scalar bool (one shared implementation for the
    sequential, batched, and factored paths)."""
    if isinstance(update, bool):
        return updated if update else state
    u = jnp.asarray(update)
    return jax.tree.map(lambda p, q: jnp.where(u, p, q), updated, state)


def apply_norm(state: NormState, xs: jnp.ndarray) -> jnp.ndarray:
    """The normalization affine shared by every path (reference
    ``Normalization.__call__`` epsilon)."""
    return (xs - state.mean) / (state.std + 1e-8)


def normalize_batch(state: NormState, xs: jnp.ndarray,
                    update=True) -> Tuple[NormState, jnp.ndarray]:
    """Batched counterpart of ``normalize``: one order-free merge of all
    rows, every row normalized with the post-merge statistics."""
    state = select_update(state, welford_update_batch(state, xs), update)
    return state, apply_norm(state, xs)


@struct.dataclass
class RewardScaleState:
    """``RewardScaling`` carried state (``normalization.py:38-52``): a
    discounted return whose running std divides rewards. Imported by the
    reference env but never instantiated in the released slice — provided for
    capability parity."""

    norm: NormState
    r: jnp.ndarray       # discounted return accumulator
    gamma: float = struct.field(pytree_node=False, default=0.99)

    @classmethod
    def create(cls, gamma: float, dim: int = 1) -> "RewardScaleState":
        return cls(norm=NormState.create(dim),
                   r=jnp.zeros((dim,), jnp.float32), gamma=gamma)


def scale_reward(state: RewardScaleState,
                 x: jnp.ndarray) -> Tuple[RewardScaleState, jnp.ndarray]:
    r = state.gamma * state.r + x
    norm = welford_update(state.norm, r)
    y = x / (norm.std + 1e-8)
    return RewardScaleState(norm=norm, r=r, gamma=state.gamma), y


def reset_reward_scale(state: RewardScaleState) -> RewardScaleState:
    return RewardScaleState(norm=state.norm, r=jnp.zeros_like(state.r),
                            gamma=state.gamma)
