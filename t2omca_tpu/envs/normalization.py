"""Running normalization as pure functions on carried state (C2).

Re-creates ``/root/reference/normalization.py`` with its two quirks
(SURVEY.md §7.5):

* **Q5** — the Welford update's *first* sample sets ``std = x`` (not 0)
  (``normalization.py:16-18``), so the first normalized output is exactly 0
  via ``(x - x)/(x + 1e-8)``.
* **Q4** — the observation normalizer is updated on every call, including
  evaluation (``environment_multi_mec.py:184-186``); callers here decide by
  passing ``update``.

The reference keeps one mutable ``Normalization`` object per env subprocess;
here the statistics are a ``NormState`` pytree carried inside ``EnvState`` so
each vmapped env keeps independent statistics (SURVEY.md §7.4(3)).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class NormState:
    """Welford running statistics (reference ``RunningMeanStd``)."""

    n: jnp.ndarray       # scalar int32 sample count
    mean: jnp.ndarray    # (dim,)
    s: jnp.ndarray       # (dim,) sum of squared deviations
    std: jnp.ndarray     # (dim,)

    @classmethod
    def create(cls, dim: int) -> "NormState":
        z = jnp.zeros((dim,), jnp.float32)
        return cls(n=jnp.zeros((), jnp.int32), mean=z, s=z, std=z)


def welford_update(state: NormState, x: jnp.ndarray) -> NormState:
    """One ``RunningMeanStd.update`` step (``normalization.py:12-22``)."""
    n1 = state.n + 1
    first = n1 == 1
    new_mean = jnp.where(first, x, state.mean + (x - state.mean) / n1)
    new_s = jnp.where(first, state.s,
                      state.s + (x - state.mean) * (x - new_mean))
    new_std = jnp.where(first, x, jnp.sqrt(new_s / n1))   # Q5: first std = x
    return NormState(n=n1, mean=new_mean, s=new_s, std=new_std)


def normalize(state: NormState, x: jnp.ndarray,
              update=True) -> Tuple[NormState, jnp.ndarray]:
    """``Normalization.__call__`` (``normalization.py:29-35``): optionally
    update, then normalize with the (post-update) statistics. ``update`` may
    be a Python bool or a traced scalar bool (so evaluation rollouts can flip
    it inside one jitted program)."""
    if isinstance(update, bool):
        if update:
            state = welford_update(state, x)
    else:
        updated = welford_update(state, x)
        u = jnp.asarray(update)
        state = jax.tree.map(lambda a, b: jnp.where(u, a, b), updated, state)
    y = (x - state.mean) / (state.std + 1e-8)
    return state, y


@struct.dataclass
class RewardScaleState:
    """``RewardScaling`` carried state (``normalization.py:38-52``): a
    discounted return whose running std divides rewards. Imported by the
    reference env but never instantiated in the released slice — provided for
    capability parity."""

    norm: NormState
    r: jnp.ndarray       # discounted return accumulator
    gamma: float = struct.field(pytree_node=False, default=0.99)

    @classmethod
    def create(cls, gamma: float, dim: int = 1) -> "RewardScaleState":
        return cls(norm=NormState.create(dim),
                   r=jnp.zeros((dim,), jnp.float32), gamma=gamma)


def scale_reward(state: RewardScaleState,
                 x: jnp.ndarray) -> Tuple[RewardScaleState, jnp.ndarray]:
    r = state.gamma * state.r + x
    norm = welford_update(state.norm, r)
    y = x / (norm.std + 1e-8)
    return RewardScaleState(norm=norm, r=r, gamma=state.gamma), y


def reset_reward_scale(state: RewardScaleState) -> RewardScaleState:
    return RewardScaleState(norm=state.norm, r=jnp.zeros_like(state.r),
                            gamma=state.gamma)
