"""Environment registry (M3).

The reference runner builds envs through ``envs.REGISTRY[name](**env_args)``
(``/root/reference/parallel_runner.py:1,22``); here the registry maps names to
functional-env constructors taking an ``EnvConfig``.
"""

from __future__ import annotations

from typing import Callable, Dict

from ..config import EnvConfig
from .mec_offload import MultiAgvOffloadingEnv

REGISTRY: Dict[str, Callable[[EnvConfig], MultiAgvOffloadingEnv]] = {
    "multi_agv_offloading": MultiAgvOffloadingEnv,
    "multi_mec": MultiAgvOffloadingEnv,   # reference map_name alias
}


def make_env(cfg: EnvConfig) -> MultiAgvOffloadingEnv:
    try:
        ctor = REGISTRY[cfg.key]
    except KeyError:
        raise KeyError(
            f"unknown env '{cfg.key}'; registered: {sorted(REGISTRY)}")
    return ctor(cfg)
