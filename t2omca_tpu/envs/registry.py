"""Environment registry (M3 + graftworld scenario families).

The reference runner builds envs through ``envs.REGISTRY[name](**env_args)``
(``/root/reference/parallel_runner.py:1,22``); here the registry maps names
to :class:`EnvEntry` records — a functional-env constructor PLUS the env
key's default scenario (a ``config.ScenarioConfig``), so a registry key is
a (physics, parameter-distribution) pair. The scenario families
(``envs/graftworld.py``) share the ONE MEC-offload ``step``: each family
key selects a different default EnvParams distribution, not different
code. Aliases are declared per entry and deduped into one canonical map —
an alias and its canonical key resolve to the identical entry object.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Tuple

from ..config import EnvConfig, ScenarioConfig
from .mec_offload import MultiAgvOffloadingEnv


@dataclasses.dataclass(frozen=True)
class EnvEntry:
    """One registered env key: constructor + default scenario + aliases."""

    ctor: Callable[[EnvConfig], MultiAgvOffloadingEnv]
    default_scenario: ScenarioConfig = dataclasses.field(
        default_factory=ScenarioConfig)
    aliases: Tuple[str, ...] = ()


REGISTRY: Dict[str, EnvEntry] = {
    # the reference scenario: fixed baseline parameters
    "multi_agv_offloading": EnvEntry(
        MultiAgvOffloadingEnv,
        ScenarioConfig(kind="fixed", family="baseline"),
        aliases=("multi_mec",)),        # reference map_name alias
    # graftworld families (docs/ENVS.md): same physics/step, different
    # default parameter distributions
    "multi_agv_hetfleet": EnvEntry(
        MultiAgvOffloadingEnv,
        ScenarioConfig(kind="uniform", family="hetfleet"),
        aliases=("hetfleet",)),
    "multi_agv_interference": EnvEntry(
        MultiAgvOffloadingEnv,
        ScenarioConfig(kind="uniform", family="interference"),
        aliases=("interference",)),
    "multi_agv_surge": EnvEntry(
        MultiAgvOffloadingEnv,
        ScenarioConfig(kind="uniform", family="surge"),
        aliases=("surge",)),
    # the full domain-randomized mixture over every family
    "multi_agv_scenarios": EnvEntry(
        MultiAgvOffloadingEnv,
        ScenarioConfig(kind="mixture"),
        aliases=("scenarios", "graftworld")),
}


def _alias_map() -> Dict[str, str]:
    """alias -> canonical key, built once from the entries (single
    source: an alias is declared exactly where its entry is)."""
    amap: Dict[str, str] = {}
    for canonical, entry in REGISTRY.items():
        for alias in entry.aliases:
            if alias in REGISTRY or alias in amap:
                raise ValueError(f"env alias {alias!r} collides with an "
                                 f"existing key/alias")
            amap[alias] = canonical
    return amap


ALIASES: Dict[str, str] = _alias_map()


def resolve(key: str) -> Tuple[str, EnvEntry]:
    """→ (canonical key, entry); canonical keys and aliases both resolve.
    The unknown-key error names canonical keys and aliases separately —
    a typo'd alias should not read as 'not one of the canonical four'."""
    canonical = ALIASES.get(key, key)
    try:
        return canonical, REGISTRY[canonical]
    except KeyError:
        raise KeyError(
            f"unknown env '{key}'; canonical keys: {sorted(REGISTRY)}; "
            f"aliases: "
            f"{sorted(f'{a} -> {c}' for a, c in ALIASES.items())}"
        ) from None


def make_env(cfg: EnvConfig) -> MultiAgvOffloadingEnv:
    _, entry = resolve(cfg.key)
    return entry.ctor(cfg)


def scenario_config(cfg: EnvConfig) -> ScenarioConfig:
    """The effective scenario for an env config: an explicit
    ``env_args.scenario.kind`` wins; the empty-kind sentinel (the
    untouched default) falls back to the registry key's default — so
    ``key: multi_agv_surge`` alone trains over the surge envelope,
    while ``key: multi_agv_offloading`` + ``scenario: {kind: mixture}``
    overrides it, and ``kind: fixed`` over a family key explicitly
    pins the baseline point."""
    _, entry = resolve(cfg.key)
    if cfg.scenario.kind:
        return cfg.scenario
    return entry.default_scenario


def make_scenario_distribution(cfg: EnvConfig):
    """→ the ``graftworld.ScenarioDistribution`` the runner samples each
    lane's EnvParams from (jit-static; one per config)."""
    from .graftworld import make_distribution
    return make_distribution(scenario_config(cfg))
