from .critic import critic
from .mec_offload import EnvState, MultiAgvOffloadingEnv, StepInfo
from .normalization import (NormState, RewardScaleState, normalize,
                            reset_reward_scale, scale_reward, welford_update)
from .registry import REGISTRY, make_env

__all__ = [
    "critic",
    "EnvState",
    "MultiAgvOffloadingEnv",
    "StepInfo",
    "NormState",
    "RewardScaleState",
    "normalize",
    "welford_update",
    "scale_reward",
    "reset_reward_scale",
    "REGISTRY",
    "make_env",
]
