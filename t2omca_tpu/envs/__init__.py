from .critic import critic
from .graftworld import (FAMILY_IDS, FAMILY_NAMES, FixedScenario,
                         MixtureScenario, ScenarioDistribution,
                         UniformScenario, family_distribution,
                         make_distribution)
from .mec_offload import EnvParams, EnvState, MultiAgvOffloadingEnv, StepInfo
from .normalization import (NormState, RewardScaleState, normalize,
                            reset_reward_scale, scale_reward, welford_update)
from .registry import (ALIASES, REGISTRY, EnvEntry, make_env,
                       make_scenario_distribution, resolve, scenario_config)

__all__ = [
    "critic",
    "EnvParams",
    "EnvState",
    "MultiAgvOffloadingEnv",
    "StepInfo",
    "NormState",
    "RewardScaleState",
    "normalize",
    "welford_update",
    "scale_reward",
    "reset_reward_scale",
    "REGISTRY",
    "ALIASES",
    "EnvEntry",
    "make_env",
    "resolve",
    "scenario_config",
    "make_scenario_distribution",
    "FAMILY_NAMES",
    "FAMILY_IDS",
    "ScenarioDistribution",
    "FixedScenario",
    "UniformScenario",
    "MixtureScenario",
    "family_distribution",
    "make_distribution",
]
