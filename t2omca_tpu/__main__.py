"""CLI: ``python -m t2omca_tpu <train|evaluate|benchmark> [--config f]
[key=value ...]``.

Replaces the reference's sacred entry (M14): subcommands instead of sacred
command-line magic, ``key=value`` / ``section.key=value`` overrides instead
of ``with config.yaml``. Examples::

    python -m t2omca_tpu train t_max=50000 env_args.agv_num=16
    python -m t2omca_tpu evaluate checkpoint_path=results/models/<token>
    python -m t2omca_tpu benchmark checkpoint_path=... test_nepisode=32
"""

from __future__ import annotations

import argparse
import sys

from .config import load_config
from .run import run
from .utils.logging import Logger


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="t2omca_tpu")
    parser.add_argument("command",
                        choices=["train", "evaluate", "benchmark"])
    parser.add_argument("--config", default=None,
                        help="YAML/JSON config file")
    parser.add_argument("overrides", nargs="*",
                        help="key=value config overrides")
    args = parser.parse_args(argv)

    # multi-host (DCN) leg: no-op unless a coordinator topology is
    # configured in the environment (parallel/distributed.py)
    from .parallel import maybe_initialize_distributed
    maybe_initialize_distributed()

    cfg = load_config(args.config, tuple(args.overrides))
    if args.command in ("evaluate", "benchmark"):
        cfg = cfg.replace(evaluate=True)
    if args.command == "benchmark":
        cfg = cfg.replace(benchmark_mode=True)
    run(cfg, Logger())
    return 0


if __name__ == "__main__":
    sys.exit(main())
