"""GRU recurrent Q-agent — the PyMARL-lineage alternative agent family.

The reference release ships only the transformer agent (C6), but it is a
slice of a PyMARL-style framework whose controllers select the agent from a
registry (SURVEY.md §2.3 M7: ``mac_REGISTRY`` builds the agent; the parent
lineage's default is an RNN agent). This supplies that family TPU-natively:
``obs → Dense+relu → GRUCell → Q head``, same functional interface as
``TransformerAgent`` (fold agents into batch, explicit hidden carry), so the
MAC/learner/runner stack is agent-agnostic.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .transformer import orthogonal_or_default


class RNNAgent(nn.Module):
    n_agents: int
    n_entities: int          # unused (flat input); kept for interface parity
    feat_dim: int
    emb: int                 # GRU hidden size (= mixer emb when the
    #                          transformer mixer consumes the hidden tokens)
    heads: int = 1           # unused; interface parity
    depth: int = 1
    n_actions: int = 3
    ff_hidden_mult: int = 4
    dropout: float = 0.0
    noisy: bool = False
    standard_heads: bool = False
    use_orthogonal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "xla"   # unused; interface parity (kernels.attention)

    @nn.compact
    def __call__(self, inputs: jax.Array, hidden_state: jax.Array,
                 deterministic: bool = True) -> Tuple[jax.Array, jax.Array]:
        b, a, obs_dim = inputs.shape
        x = inputs.reshape(b * a, obs_dim).astype(self.dtype)
        h = hidden_state.reshape(b * a, self.emb).astype(self.dtype)

        init = orthogonal_or_default(self.use_orthogonal)
        x = nn.relu(nn.Dense(self.emb, name="fc1", dtype=self.dtype,
                             kernel_init=init)(x))
        h_new, _ = nn.GRUCell(self.emb, name="rnn", dtype=self.dtype)(h, x)
        h_new = h_new.astype(jnp.float32)

        if self.noisy:
            from .noisy import NoisyLinear
            q = NoisyLinear(self.n_actions, name="q_basic")(
                h_new, deterministic=deterministic)
        else:
            q = nn.Dense(self.n_actions, name="q_basic",
                         kernel_init=init)(h_new)

        return (q.astype(jnp.float32).reshape(b, a, self.n_actions),
                h_new.reshape(b, a, self.emb))

    def initial_hidden(self, batch_size: int) -> jax.Array:
        return jnp.zeros((batch_size, self.n_agents, self.emb))
