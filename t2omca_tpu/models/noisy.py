"""Factorized-Gaussian NoisyNet linear layer (M11).

The reference imports ``utils.noisy_liner.NoisyLinear`` for its
exploration-by-parameter-noise mode (``/root/reference/transf_agent.py:6,37-39``,
selected by ``action_selector == "noisy-new"``); the module itself is not
released, so this follows the standard NoisyNet formulation (Fortunato et al.
2018, factorized Gaussian):

    w = mu_w + sigma_w * (f(eps_out) ⊗ f(eps_in)),  f(x) = sign(x)*sqrt(|x|)
    b = mu_b + sigma_b * f(eps_out)

Noise is drawn from the flax ``"noise"`` RNG stream; with
``deterministic=True`` (evaluation) only the mean parameters are used.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


def _scaled_noise(key: jax.Array, n: int) -> jax.Array:
    x = jax.random.normal(key, (n,))
    return jnp.sign(x) * jnp.sqrt(jnp.abs(x))


def noisy_weights(w_mu, w_sigma, b_mu, b_sigma, key):
    """ONE factored-Gaussian draw of the noisy affine — the single source
    of the construction, shared by the flax module below (key from
    ``make_rng("noise")``) and the qslice q-head
    (``ops/query_slice._q_head``, explicit key). ``b_mu=None`` for
    bias-less layers."""
    k_in, k_out = jax.random.split(key)
    eps_in = _scaled_noise(k_in, w_mu.shape[0])
    eps_out = _scaled_noise(k_out, w_mu.shape[1])
    w = w_mu + w_sigma * jnp.outer(eps_in, eps_out)
    b = None if b_mu is None else b_mu + b_sigma * eps_out
    return w, b


class NoisyLinear(nn.Module):
    features: int
    use_bias: bool = True
    sigma0: float = 0.5

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        in_dim = x.shape[-1]
        bound = in_dim ** -0.5
        mu_init = nn.initializers.uniform(scale=2 * bound)  # ~U(0, 2/sqrt(in))
        sigma_init = nn.initializers.constant(self.sigma0 * bound)

        w_mu = self.param("w_mu", lambda k, s: mu_init(k, s) - bound,
                          (in_dim, self.features))
        w_sigma = self.param("w_sigma", sigma_init, (in_dim, self.features))
        if self.use_bias:
            b_mu = self.param("b_mu", lambda k, s: mu_init(k, s) - bound,
                              (self.features,))
            b_sigma = self.param("b_sigma", sigma_init, (self.features,))

        if deterministic:
            w = w_mu
            b = b_mu if self.use_bias else None
        else:
            w, b = noisy_weights(
                w_mu, w_sigma,
                b_mu if self.use_bias else None,
                b_sigma if self.use_bias else None,
                self.make_rng("noise"))

        y = x @ w
        if b is not None:
            y = y + b
        return y
