"""Transformer Q-agent.

Re-creates ``TransformerAgent`` (``/root/reference/transf_agent.py:8-76``):
entity-tokenized observations are linearly embedded, the recurrent hidden
state is **prepended as token 0**, the stack self-attends (q = k = tokens),
token 0 becomes the new hidden state and is projected to per-action Q-values.
Recurrence without an RNN — the hidden token is the memory (TransfQMIX).

Shapes: inputs ``(batch, n_agents, obs)`` are folded to
``(batch*n_agents, n_entities, feat)`` exactly as the reference does
(``transf_agent.py:56-59``), so all agents share parameters and one big MXU
matmul serves the whole batch×agent axis.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .noisy import NoisyLinear
from .transformer import Transformer, orthogonal_or_default


class TransformerAgent(nn.Module):
    n_agents: int
    n_entities: int          # reference: n_entities_obs override, else n_entities
    feat_dim: int            # obs_entity_feats
    emb: int
    heads: int
    depth: int
    n_actions: int
    ff_hidden_mult: int = 4
    dropout: float = 0.0
    noisy: bool = False      # action_selector == "noisy-new" (transf_agent.py:37-39)
    standard_heads: bool = False
    use_orthogonal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "xla"   # kernels.attention switch (models/transformer.py)

    @nn.compact
    def __call__(self, inputs: jax.Array, hidden_state: jax.Array,
                 deterministic: bool = True) -> Tuple[jax.Array, jax.Array]:
        b, a, _ = inputs.shape
        x = inputs.reshape(b * a, self.n_entities, self.feat_dim)
        h = hidden_state.reshape(b * a, 1, self.emb).astype(self.dtype)

        embs = nn.Dense(self.emb, name="feat_embedding", dtype=self.dtype,
                        kernel_init=orthogonal_or_default(self.use_orthogonal))(x)

        # hidden token prepended at position 0 (transf_agent.py:65)
        tokens = jnp.concatenate([h, embs], axis=1)

        out = Transformer(
            emb=self.emb, heads=self.heads, depth=self.depth,
            ff_hidden_mult=self.ff_hidden_mult, dropout=self.dropout,
            standard_heads=self.standard_heads,
            use_orthogonal=self.use_orthogonal, dtype=self.dtype,
            attn_impl=self.attn_impl,
            name="transformer")(tokens, tokens, deterministic=deterministic)

        h_new = out[:, 0:1, :].astype(jnp.float32)  # token 0 = new hidden (:71)

        if self.noisy:
            q = NoisyLinear(self.n_actions, name="q_basic")(
                h_new, deterministic=deterministic)
        else:
            q = nn.Dense(self.n_actions, name="q_basic",
                         kernel_init=orthogonal_or_default(self.use_orthogonal))(h_new)

        # Q-values and the carried hidden token stay f32 regardless of the
        # compute dtype (selector argmax + TD math need full precision)
        return (q.astype(jnp.float32).reshape(b, a, self.n_actions),
                h_new.reshape(b, a, self.emb))

    def initial_hidden(self, batch_size: int) -> jax.Array:
        """Zeros ``(batch, n_agents, emb)`` (reference ``init_hidden`` zeros
        ``(1, emb)`` broadcast by the MAC, ``transf_agent.py:50-52``)."""
        return jnp.zeros((batch_size, self.n_agents, self.emb))
