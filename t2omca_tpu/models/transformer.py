"""Transformer core (flax.linen).

Re-creates the exact math of the reference transformer
(``/root/reference/transformer.py``) with its three behavioural quirks, which
are load-bearing for loss-curve parity (SURVEY.md §7.5):

* **Q1 — non-standard head geometry.** K/Q/V projections map ``emb →
  emb*heads`` so *every head has the full emb dimension* (reference
  ``transformer.py:34-36,52-59``), and attention logits are scaled by dividing
  both queries and keys by ``emb ** (1/4)`` (``transformer.py:62-63``).
  ``standard_heads=True`` switches to conventional ``emb//heads`` heads for the
  performance configs (measured separately; BASELINE.md).
* **Q2 — post-LN residuals**, residual adds the *query* input, dropout after
  each sub-layer: ``x = norm1(attended + q); x = do(x); x = norm2(ff(x) + x);
  x = do(x)`` (``transformer.py:120-140``).
* **Key threading.** Blocks pass ``(q, k, mask)`` tuples and return the
  *original* ``k`` unchanged (``transformer.py:126,140``), so with ``depth>1``
  every block attends its evolving queries against the **layer-0 key
  embeddings** — not the previous block's output. Preserved exactly.

Everything is expressed as batched einsums so XLA tiles the contractions onto
the MXU; there are no data-dependent shapes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

NEG_MASK_VALUE = -1e9  # reference masked_fill value (transformer.py:73)


def orthogonal_or_default(use_orthogonal: bool, scale: float = 2 ** 0.5):
    """Kernel init selector: reference optionally applies ``orthogonal_init_``
    module-wise (``/root/reference/n_transf_mixer.py:48-50``, M12)."""
    if use_orthogonal:
        return nn.initializers.orthogonal(scale)
    return nn.initializers.lecun_normal()


class MultiHeadAttention(nn.Module):
    """Multi-head attention with the reference's full-emb head geometry (Q1).

    Reference: ``/root/reference/transformer.py:20-84``.
    """

    emb: int
    heads: int = 8
    causal: bool = False          # reference ``mask`` ctor flag (upper-tri fill)
    standard_heads: bool = False  # perf mode: per-head dim = emb // heads
    use_orthogonal: bool = False
    dtype: jnp.dtype = jnp.float32   # compute dtype (bf16 = MXU-native perf mode)
    # attention kernel (config kernels.attention, docs/PERF.md): "xla" =
    # the einsum→softmax→einsum path below (default; materializes the
    # (b, h, t_q, t_k) logits tensor); "pallas" = the fused flash-style
    # kernel (kernels/attention.py — tiled online softmax, f32
    # accumulators, logits live only in VMEM). Parity pinned by
    # tests/test_kernels.py; interpret mode makes pallas CPU-testable.
    attn_impl: str = "xla"

    @nn.compact
    def __call__(self, q: jax.Array, k: jax.Array,
                 mask: Optional[jax.Array] = None) -> jax.Array:
        b, t_q, e_q = q.shape
        _, t_k, e = k.shape
        assert e == e_q == self.emb, (e, e_q, self.emb)
        assert self.attn_impl in ("xla", "pallas"), self.attn_impl
        h = self.heads
        if self.standard_heads:
            assert self.emb % h == 0
            head_dim = self.emb // h
        else:
            head_dim = self.emb  # Q1: full-width heads

        dense = lambda name: nn.Dense(
            h * head_dim, use_bias=False, name=name, dtype=self.dtype,
            kernel_init=orthogonal_or_default(self.use_orthogonal))
        keys = dense("tokeys")(k).reshape(b, t_k, h, head_dim)
        queries = dense("toqueries")(q).reshape(b, t_q, h, head_dim)
        values = dense("tovalues")(k).reshape(b, t_k, h, head_dim)

        # Q1: scale queries AND keys by head_dim**(1/4) (transformer.py:62-63)
        scale = head_dim ** -0.25
        queries = queries * scale
        keys = keys * scale

        if mask is not None:
            # padding mask: 0 entries are suppressed (transformer.py:72-73).
            # Accepts (b, t_q, t_k) — broadcast over heads — or (b, h/1, t_q, t_k).
            if mask.ndim == 3:
                mask = mask[:, None, :, :]
            assert mask.ndim == 4, f"mask must be 3D or 4D, got {mask.shape}"

        if self.attn_impl == "pallas":
            # fused flash kernel: tiled QK^T → masked online softmax →
            # PV, f32 accumulators, never materializing the logits
            # tensor (kernels/attention.py). Same mask/causal semantics
            # as below; softmax statistics are f32 in BOTH dtypes (the
            # bf16 path is better-conditioned than the einsum one).
            from ..kernels.attention import flash_attention
            out = flash_attention(
                jnp.swapaxes(queries, 1, 2), jnp.swapaxes(keys, 1, 2),
                jnp.swapaxes(values, 1, 2), mask=mask, causal=self.causal)
            out = jnp.swapaxes(out, 1, 2).reshape(b, t_q, h * head_dim)
            return nn.Dense(self.emb, name="unifyheads", dtype=self.dtype,
                            kernel_init=orthogonal_or_default(
                                self.use_orthogonal))(out)

        logits = jnp.einsum("bqhd,bkhd->bhqk", queries, keys)

        if self.causal:
            # reference mask_ fills the upper triangle excluding the diagonal
            # with -inf when used from attention (transformer.py:69-70)
            tri = jnp.triu(jnp.ones((t_q, t_k), dtype=bool), k=1)
            logits = jnp.where(tri[None, None], -jnp.inf, logits)
        if mask is not None:
            logits = jnp.where(mask == 0, NEG_MASK_VALUE, logits)

        # parity mode (f32) keeps f32 softmax; bf16 perf mode stays in bf16
        # end-to-end — bf16 shares f32's exponent range, so max-subtracted
        # softmax is range-safe, and skipping the cast avoids materializing
        # the (b, h, t, t) logits twice
        if self.dtype == jnp.float32:
            attn = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        else:
            attn = jax.nn.softmax(logits, axis=-1)
        attn = attn.astype(values.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", attn, values)
        out = out.reshape(b, t_q, h * head_dim)
        return nn.Dense(self.emb, name="unifyheads", dtype=self.dtype,
                        kernel_init=orthogonal_or_default(self.use_orthogonal))(out)


class TransformerBlock(nn.Module):
    """Post-LN transformer block (Q2). Reference ``transformer.py:87-140``."""

    emb: int
    heads: int
    causal: bool = False
    ff_hidden_mult: int = 4
    dropout: float = 0.0
    standard_heads: bool = False
    use_orthogonal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "xla"        # kernels.attention switch (see MHA)

    @nn.compact
    def __call__(self, q: jax.Array, k: jax.Array,
                 mask: Optional[jax.Array] = None,
                 deterministic: bool = True) -> jax.Array:
        attended = MultiHeadAttention(
            emb=self.emb, heads=self.heads, causal=self.causal,
            standard_heads=self.standard_heads,
            use_orthogonal=self.use_orthogonal, dtype=self.dtype,
            attn_impl=self.attn_impl,
            name="attention")(q, k, mask)

        x = nn.LayerNorm(name="norm1", dtype=self.dtype)(attended + q)
        x = nn.Dropout(self.dropout, deterministic=deterministic)(x)

        init = orthogonal_or_default(self.use_orthogonal)
        ff = nn.Dense(self.ff_hidden_mult * self.emb, name="ff1",
                      dtype=self.dtype, kernel_init=init)(x)
        ff = nn.relu(ff)
        ff = nn.Dense(self.emb, name="ff2", dtype=self.dtype,
                      kernel_init=init)(ff)

        x = nn.LayerNorm(name="norm2", dtype=self.dtype)(ff + x)
        x = nn.Dropout(self.dropout, deterministic=deterministic)(x)
        return x


class Transformer(nn.Module):
    """Stack of ``depth`` non-causal blocks returning final queries.

    Reference ``transformer.py:143-178``. Keys stay pinned to the layer-0
    input across blocks (see module docstring).
    """

    emb: int
    heads: int
    depth: int
    ff_hidden_mult: int = 4
    dropout: float = 0.0
    standard_heads: bool = False
    use_orthogonal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "xla"        # kernels.attention switch (see MHA)

    @nn.compact
    def __call__(self, q: jax.Array, k: jax.Array,
                 mask: Optional[jax.Array] = None,
                 deterministic: bool = True) -> jax.Array:
        x = q
        for i in range(self.depth):
            x = TransformerBlock(
                emb=self.emb, heads=self.heads, causal=False,
                ff_hidden_mult=self.ff_hidden_mult, dropout=self.dropout,
                standard_heads=self.standard_heads,
                use_orthogonal=self.use_orthogonal, dtype=self.dtype,
                attn_impl=self.attn_impl,
                name=f"block_{i}")(x, k, mask, deterministic=deterministic)
        return x
