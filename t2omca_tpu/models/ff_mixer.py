"""Feed-forward QMIX hypernetwork mixer and VDN — alternative mixer families.

The reference's transformer mixer (C7) is the TransfQMIX variant of the
classic QMIX mixer; the parent PyMARL lineage selects mixers from a registry
(standard QMIX hypernet, VDN sum). These supply those families with the SAME
call signature as ``TransformerMixer`` — ``(qvals, hidden_states,
hyper_weights, states, obs) → (q_tot, hyper')`` — so the learner's recurrent
scan is mixer-agnostic (non-recurrent mixers just thread the dummy hyper
carry through unchanged).

QMIX math (monotonic two-layer mixing, hypernetworks conditioned on the
global state): ``q_tot = pos(w2(s)) · elu(pos(w1(s)) · q + b1(s)) + b2(s)``
with the same ``pos_func`` options as the transformer mixer.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .mixer import qmix_pos_func
from .transformer import orthogonal_or_default


class QMixFFMixer(nn.Module):
    """Standard QMIX: hypernet weights from MLPs over the flat state."""

    n_agents: int
    n_entities: int = 0       # unused; interface parity
    feat_dim: int = 0
    emb: int = 32             # mixing embed dim
    heads: int = 1
    depth: int = 1
    ff_hidden_mult: int = 4
    dropout: float = 0.0
    qmix_pos_func: str = "abs"
    qmix_pos_func_beta: float = 1.0
    state_entity_mode: bool = True
    standard_heads: bool = False
    use_orthogonal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "xla"   # unused; interface parity (kernels.attention)
    hypernet_layers: int = 2
    hypernet_emb: int = 64
    zero_init_gate: bool = False   # ReZero output gate (see models/mixer.py)

    def pos_func(self, x: jax.Array) -> jax.Array:
        return qmix_pos_func(x, self.qmix_pos_func, self.qmix_pos_func_beta)

    @nn.compact
    def __call__(self, qvals: jax.Array, hidden_states: jax.Array,
                 hyper_weights: jax.Array, states: jax.Array,
                 obs: jax.Array, deterministic: bool = True,
                 ) -> Tuple[jax.Array, jax.Array]:
        del hidden_states, deterministic
        b = qvals.shape[0]
        # LayerNorm the hypernet input: this env's global state is
        # intentionally unnormalized (reference get_state leaves state_norm
        # commented, :203) with O(1e4) feature magnitudes; the transformer
        # mixer bounds it through its post-LN blocks, the MLP hypernet needs
        # the same protection or the mixed Q explodes within episodes
        s = nn.LayerNorm(name="state_norm", dtype=self.dtype)(
            states.reshape(b, -1).astype(self.dtype))
        init = orthogonal_or_default(self.use_orthogonal)

        def hyper(name, out):
            x = s
            if self.hypernet_layers >= 2:
                x = nn.relu(nn.Dense(self.hypernet_emb, name=f"{name}_h",
                                     dtype=self.dtype, kernel_init=init)(x))
            return nn.Dense(out, name=name, dtype=self.dtype,
                            kernel_init=init)(x).astype(jnp.float32)

        w1 = self.pos_func(hyper("hyper_w1", self.n_agents * self.emb)
                           ).reshape(b, self.n_agents, self.emb)
        b1 = hyper("hyper_b1", self.emb).reshape(b, 1, self.emb)
        w2 = self.pos_func(hyper("hyper_w2", self.emb)
                           ).reshape(b, self.emb, 1)
        # V(s): unclamped (standard QMIX) — unlike the transformer mixer,
        # whose relu'd b2 mirrors the reference (n_transf_mixer.py:82); a
        # clamp here would zero the V-head gradient whenever V(s) < 0
        b2 = hyper("hyper_b2", 1).reshape(b, 1, 1)

        hidden = nn.elu(jnp.matmul(qvals.astype(jnp.float32), w1) + b1)
        y = jnp.matmul(hidden, w2) + b2
        if self.zero_init_gate:
            y = y * self.param("out_gate", nn.initializers.zeros, (1,))
        return y, hyper_weights          # non-recurrent: carry unchanged

    def initial_hyper(self, batch_size: int) -> jax.Array:
        """Dummy recurrent carry so the learner scan is mixer-agnostic."""
        return jnp.zeros((batch_size, 3, self.emb))


class VDNMixer(nn.Module):
    """Value decomposition by summation (VDN): ``q_tot = Σ_a q_a``."""

    n_agents: int
    n_entities: int = 0
    feat_dim: int = 0
    emb: int = 32
    heads: int = 1
    depth: int = 1
    ff_hidden_mult: int = 4
    dropout: float = 0.0
    qmix_pos_func: str = "abs"
    qmix_pos_func_beta: float = 1.0
    state_entity_mode: bool = True
    standard_heads: bool = False
    use_orthogonal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "xla"   # unused; interface parity (kernels.attention)
    zero_init_gate: bool = False   # accepted for registry-uniform kwargs;
    # a parameterless sum has no init-scale pathology to gate

    @nn.compact
    def __call__(self, qvals: jax.Array, hidden_states: jax.Array,
                 hyper_weights: jax.Array, states: jax.Array,
                 obs: jax.Array, deterministic: bool = True,
                 ) -> Tuple[jax.Array, jax.Array]:
        del hidden_states, states, obs, deterministic
        return (qvals.astype(jnp.float32).sum(axis=-1, keepdims=True),
                hyper_weights)

    def initial_hyper(self, batch_size: int) -> jax.Array:
        return jnp.zeros((batch_size, 3, self.emb))
