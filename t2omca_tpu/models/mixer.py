"""Transformer QMIX mixer.

Re-creates ``TransformerMixer`` (``/root/reference/n_transf_mixer.py:12-103``):
the QMIX hypernetwork weights are *read off positional output tokens* of a
transformer run over [state-entity embeddings ++ agent hidden states ++ 3
recurrent "hyper" tokens] (quirk Q11 — the concatenation order is
load-bearing):

    w1 = tokens[-3-n_agents:-3]   (one per agent)
    b1 = tokens[-3]
    w2 = tokens[-2]
    b2 = relu(hyper_b2(tokens[-1]))

Monotonicity in the per-agent Qs is enforced by ``pos_func`` on w1/w2
(``n_transf_mixer.py:84-85,95-103``), then
``q_tot = elu(q·w1 + b1)·w2 + b2``. The mixer returns its last 3 output
tokens so the learner can carry them recurrently across timesteps
(``n_transf_mixer.py:91``).

Quirk Q12: when ``state_entity_mode`` is false the mixer tokenizes *all
agents' observation entities* instead of state entities
(``n_transf_mixer.py:43,60-63``).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .transformer import Transformer, orthogonal_or_default


def qmix_pos_func(x: jax.Array, kind: str, beta: float = 1.0) -> jax.Array:
    """Monotonicity transform for QMIX mixing weights
    (``n_transf_mixer.py:95-103``); shared by every mixer family."""
    if kind == "softplus":
        return jax.nn.softplus(beta * x) / beta
    if kind == "quadratic":
        return 0.5 * x ** 2
    if kind == "abs":
        return jnp.abs(x)
    return x


class TransformerMixer(nn.Module):
    n_agents: int
    n_entities: int            # n_entities_state override, else n_entities
    feat_dim: int              # state_entity_feats
    emb: int                   # mixer_emb == agent emb (hidden tokens concat)
    heads: int
    depth: int
    ff_hidden_mult: int = 4
    dropout: float = 0.0
    qmix_pos_func: str = "abs"
    qmix_pos_func_beta: float = 1.0
    state_entity_mode: bool = True
    standard_heads: bool = False
    use_orthogonal: bool = False
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "xla"     # kernels.attention switch (models/transformer.py)
    # ReZero-style zero-init output gate (off = reference-parity init).
    # The readout q_tot = elu(q·|w1| + b1)·|w2| + b2 contracts emb-many
    # O(1) post-LN token entries against abs-positive weights, so its init
    # scale grows ~linearly with emb: measured O(+-600) at emb=128 —
    # garbage bootstrap targets that dwarf O(1) unit-normalized rewards
    # and condition the whole early loss landscape (the config-2 collapse
    # driver). With the gate, q_tot = out_gate * y with out_gate a scalar
    # param init 0: targets start at exactly the reward signal and the
    # value scale GROWS from data (gradient dL/d_gate = y*dL/dq_tot is
    # large, so the gate opens in a few steps).
    zero_init_gate: bool = False

    def pos_func(self, x: jax.Array) -> jax.Array:
        return qmix_pos_func(x, self.qmix_pos_func, self.qmix_pos_func_beta)

    @nn.compact
    def __call__(self, qvals: jax.Array, hidden_states: jax.Array,
                 hyper_weights: jax.Array, states: jax.Array,
                 obs: jax.Array, deterministic: bool = True,
                 ) -> Tuple[jax.Array, jax.Array]:
        """qvals ``(b, 1, n_agents)``; hidden_states ``(b, n_agents, emb)``;
        hyper_weights ``(b, 3, emb)``; returns ``(q_tot (b,1,1), hyper (b,3,emb))``."""
        b = qvals.shape[0]

        if self.state_entity_mode:
            inputs = states.reshape(b, self.n_entities, self.feat_dim)
        else:  # Q12: all agents' obs entities
            inputs = obs.reshape(b, self.n_agents * self.n_entities, self.feat_dim)

        embs = nn.Dense(self.emb, name="feat_embedding", dtype=self.dtype,
                        kernel_init=orthogonal_or_default(self.use_orthogonal))(inputs)

        tokens = jnp.concatenate(
            [embs, hidden_states.astype(embs.dtype),
             hyper_weights.astype(embs.dtype)], axis=1)

        out = Transformer(
            emb=self.emb, heads=self.heads, depth=self.depth,
            ff_hidden_mult=self.ff_hidden_mult, dropout=self.dropout,
            standard_heads=self.standard_heads,
            use_orthogonal=self.use_orthogonal, dtype=self.dtype,
            attn_impl=self.attn_impl,
            name="transformer")(tokens, tokens, deterministic=deterministic)
        out = out.astype(jnp.float32)   # hypernet weights + q_tot math in f32

        w1 = out[:, -3 - self.n_agents:-3, :]                  # (b, A, emb)
        b1 = out[:, -3, :].reshape(b, 1, self.emb)
        w2 = out[:, -2, :].reshape(b, self.emb, 1)
        b2 = nn.relu(
            nn.Dense(1, name="hyper_b2",
                     kernel_init=orthogonal_or_default(self.use_orthogonal))(
                out[:, -1, :])).reshape(b, 1, 1)

        w1 = self.pos_func(w1)
        w2 = self.pos_func(w2)

        hidden = nn.elu(jnp.matmul(qvals, w1) + b1)            # (b, 1, emb)
        y = jnp.matmul(hidden, w2) + b2                        # (b, 1, 1)
        if self.zero_init_gate:
            y = y * self.param("out_gate", nn.initializers.zeros, (1,))
        return y, out[:, -3:, :]

    def initial_hyper(self, batch_size: int) -> jax.Array:
        """Zeros ``(batch, 3, emb)``; the reference's ``init_hidden`` returns
        zeros ``(1, n_agents, emb)`` (``n_transf_mixer.py:52-53``) but the
        consumed shape at ``forward`` is the 3 hyper tokens — we expose the
        consumed shape directly."""
        return jnp.zeros((batch_size, 3, self.emb))
