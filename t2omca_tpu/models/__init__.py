from .agent import TransformerAgent
from .mixer import TransformerMixer
from .noisy import NoisyLinear
from .transformer import MultiHeadAttention, Transformer, TransformerBlock

__all__ = [
    "MultiHeadAttention",
    "Transformer",
    "TransformerBlock",
    "TransformerAgent",
    "TransformerMixer",
    "NoisyLinear",
]
