from .agent import TransformerAgent
from .ff_mixer import QMixFFMixer, VDNMixer
from .mixer import TransformerMixer
from .noisy import NoisyLinear
from .rnn_agent import RNNAgent
from .transformer import MultiHeadAttention, Transformer, TransformerBlock

__all__ = [
    "MultiHeadAttention",
    "Transformer",
    "TransformerBlock",
    "TransformerAgent",
    "TransformerMixer",
    "QMixFFMixer",
    "VDNMixer",
    "RNNAgent",
    "NoisyLinear",
]
