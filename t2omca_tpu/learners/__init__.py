from .qmix_learner import LearnerState, QMixLearner, LEARNER_REGISTRY

__all__ = ["QMixLearner", "LearnerState", "LEARNER_REGISTRY"]
