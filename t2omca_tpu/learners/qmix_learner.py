"""QMIX TD learner (M8, the unreleased ``learners`` package).

Contract pinned by the call sites (SURVEY.md §2.3 M8, §3.3): per-agent Qs
from the TransformerAgent, chosen-action Qs mixed by the TransformerMixer
into ``q_tot``, target-network + double-Q targets, importance-weighted MSE on
the TD error, and ``info["td_errors_abs"]`` flowing back as PER priorities
(``/root/reference/per_run.py:233-238``, Q9).

TPU shape: the reference's sequential Python ``for t in range(T)`` becomes a
``lax.scan`` over the time axis carrying BOTH recurrent streams — the agent
hidden token (``transf_agent.py:71``) and the mixer's 3 hyper tokens
(``n_transf_mixer.py:91``) — for the online and target networks. The whole
train step (two unrolls, loss, grads, optimizer update, conditional hard
target sync) is one pure function → one XLA program; batch and agent axes
ride the MXU, the only sequential dimension is episode time.

Masking: sampled episodes keep static length ``T`` (no ``max_t_filled``
truncation — XLA wants static shapes); the ``filled`` mask plays the role of
the reference's truncation (``per_run.py:226-227``), and time-limit episodes
bootstrap because ``terminated`` excludes the time-limit step (Q7).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from ..components.episode_buffer import EpisodeBatch
from ..config import TrainConfig
from ..controllers.basic_mac import BasicMAC
from ..models.ff_mixer import QMixFFMixer, VDNMixer
from ..models.mixer import TransformerMixer

#: mixer families (parent PyMARL lineage registry pattern); all share the
#: TransformerMixer call signature so the learner scan is mixer-agnostic
MIXER_REGISTRY = {"transformer": TransformerMixer, "qmix_ff": QMixFFMixer,
                  "vdn": VDNMixer}


@struct.dataclass
class LearnerState:
    params: Any                   # {"agent": ..., "mixer": ...}
    target_params: Any
    opt_state: Any
    train_steps: jnp.ndarray      # () int32
    last_target_update: jnp.ndarray  # () int32 — episode of last hard sync


def _make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    if cfg.optimizer == "rmsprop":
        opt = optax.rmsprop(cfg.lr, decay=cfg.optim_alpha, eps=cfg.optim_eps)
    else:
        opt = optax.adam(cfg.lr, eps=cfg.optim_eps)
    return optax.chain(optax.clip_by_global_norm(cfg.grad_norm_clip), opt)


@dataclasses.dataclass(frozen=True)
class QMixLearner:
    mac: BasicMAC
    mixer: Any                  # any MIXER_REGISTRY family
    cfg: TrainConfig
    obs_dim: int
    state_dim: int

    @classmethod
    def build(cls, cfg: TrainConfig, mac: BasicMAC,
              env_info: dict) -> "QMixLearner":
        n_entities = cfg.model.n_entities_state or env_info["n_entities"]
        state_entity_mode = "state_entity_feats" in env_info
        if state_entity_mode:
            feat = env_info["state_entity_feats"]
        else:
            # Q12 fallback: mixer tokenizes all agents' obs entities
            feat = env_info["obs_entity_feats"]
            n_entities = env_info["n_entities"]
        mixer = MIXER_REGISTRY[cfg.mixer](
            n_agents=env_info["n_agents"],
            n_entities=n_entities,
            feat_dim=feat,
            emb=cfg.model.mixer_emb,
            heads=cfg.model.mixer_heads,
            depth=cfg.model.mixer_depth,
            ff_hidden_mult=cfg.model.ff_hidden_mult,
            dropout=cfg.model.dropout,
            qmix_pos_func=cfg.model.qmix_pos_func,
            qmix_pos_func_beta=cfg.model.qmix_pos_func_beta,
            state_entity_mode=state_entity_mode,
            standard_heads=cfg.model.standard_heads,
            use_orthogonal=cfg.model.use_orthogonal,
            dtype=jnp.dtype(cfg.model.dtype),
            attn_impl=cfg.kernels.attention,
            zero_init_gate=cfg.model.mixer_zero_init,
        )
        return cls(mac=mac, mixer=mixer, cfg=cfg,
                   obs_dim=env_info["obs_shape"],
                   state_dim=env_info["state_shape"])

    # ------------------------------------------------------------------ state

    def init_state(self, key: jax.Array) -> LearnerState:
        k_agent, k_mixer = jax.random.split(key)
        agent_params = self.mac.init_params(k_agent, self.obs_dim)
        b, a, e = 1, self.mac.n_agents, self.cfg.model.mixer_emb
        mixer_params = self.mixer.init(
            k_mixer,
            jnp.zeros((b, 1, a)),                      # qvals
            jnp.zeros((b, a, self.cfg.model.emb)),     # agent hiddens
            self.mixer.initial_hyper(b),               # 3 hyper tokens
            jnp.zeros((b, self.state_dim)),            # state
            jnp.zeros((b, a, self.obs_dim)),           # obs (Q12 path)
        )
        params = {"agent": agent_params, "mixer": mixer_params}
        return LearnerState(
            params=params,
            target_params=jax.tree.map(jnp.copy, params),
            opt_state=_make_optimizer(self.cfg).init(params),
            train_steps=jnp.zeros((), jnp.int32),
            last_target_update=jnp.zeros((), jnp.int32),
        )

    # ------------------------------------------------------------------ unrolls

    @property
    def _agent_qslice(self) -> bool:
        """Learner-side qslice eligibility (the shared predicate — same
        fast path as acting, exact and differentiable)."""
        from ..ops.query_slice import agent_qslice_eligible
        return agent_qslice_eligible(self.cfg)

    @property
    def _mixer_qslice(self) -> bool:
        from ..ops.query_slice import mixer_qslice_eligible
        return mixer_qslice_eligible(self.cfg)

    @property
    def _mask_padded(self) -> bool:
        """STATIC gate for the mixer-side padding mask (ROADMAP item 3's
        open remainder): True only when the config's scenario
        distribution can draw ``n_active < n_agents``. Every
        non-padding config (the classic fixed scenario, the audit
        config) compiles the exact pre-mask loss — graftprog
        fingerprints of the hot train programs stay byte-identical."""
        from ..envs.graftworld import distribution_can_pad
        from ..envs.registry import make_scenario_distribution
        return distribution_can_pad(
            make_scenario_distribution(self.cfg.env_args),
            self.mac.n_agents)


    def _scan_body(self, body):
        """Wrap a scan body with jax.checkpoint when ``model.remat``: the
        backward pass then recomputes each timestep's forward instead of
        keeping O(T) residuals — the long-horizon HBM lever (exact: same
        values, same gradients)."""
        import jax as _jax
        return _jax.checkpoint(body) if self.cfg.model.remat else body

    @property
    def needs_rngs(self) -> bool:
        """True when training must sample noise/dropout masks: NoisyNet
        sigma params only receive gradient if noise is drawn during the
        loss unroll (``/root/reference/transf_agent.py:37-48``), and
        dropout>0 must be active in training."""
        return (self.cfg.action_selector == "noisy-new"
                or self.cfg.model.dropout > 0.0)

    def _fold_params(self, agent_params):
        from ..ops.query_slice import fold_agent_params
        a = self.mac.agent
        return fold_agent_params(
            agent_params, emb=a.emb, heads=a.heads, depth=a.depth,
            standard_heads=a.standard_heads, dtype=a.dtype)

    def _unroll_agent(self, agent_params, obs_tm: jnp.ndarray,
                      key: Optional[jax.Array] = None,
                      compact_tm=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """obs_tm ``(T1, B, A, O)`` → (q ``(T1, B, A, n_actions)``,
        hiddens ``(T1, B, A, emb)``); carries the recurrent hidden token.
        ``key`` (when the config is noisy / has dropout) drives per-step
        noise resampling, matching a fresh draw per forward. With
        ``compact_tm`` (time-major ``(rows, same_mec, mean, std)`` from
        compact entity storage) the unroll runs the entity-table forward —
        same function, ~20× less input data (obs_tm may be None).

        Fast-path coverage: qslice/entity unrolls serve the deterministic
        AND the noisy configs (noise is q-head-only, applied per step from
        the split keys — ops/query_slice._q_head); only dropout>0 falls
        back to the dense flax unroll."""
        if compact_tm is not None:
            b = compact_tm[0].shape[1]
            agent_params = self._fold_params(agent_params)

            if key is None:
                def body(h, xs):
                    q, h = self.mac.forward_entity(agent_params, xs, h)
                    return h, (q, h)

                _, (qs, hs) = jax.lax.scan(
                    self._scan_body(body), self.mac.init_hidden(b),
                    compact_tm)
            else:
                def body(h, xs):
                    compact_t, k_t = xs
                    q, h = self.mac.forward_entity(
                        agent_params, compact_t, h, key=k_t,
                        deterministic=False)
                    return h, (q, h)

                keys = jax.random.split(key, compact_tm[0].shape[0])
                _, (qs, hs) = jax.lax.scan(
                    self._scan_body(body), self.mac.init_hidden(b),
                    (compact_tm, keys))
            return qs, hs

        b = obs_tm.shape[1]

        if key is None:
            # the query-slice forward is the same function up to float
            # reassociation (forward+gradient equivalence pinned in
            # tests/test_qslice.py), so the deterministic unroll uses it
            # whenever eligible; the weight fold happens here, outside the
            # scan (differentiable, loop-invariant)
            if self._agent_qslice:
                agent_params = self._fold_params(agent_params)
                # the learner unroll is where kernels.attention lands on
                # the qslice path: under "pallas" the sliced attention
                # (and, through jax.grad, its flash BACKWARD kernels)
                # lowers into the train step at the train dtype; acting/
                # serving callers keep the einsum default (basic_mac)
                fwd = functools.partial(self.mac.forward_qslice,
                                        attn_impl=self.cfg.kernels.attention)
            else:
                fwd = self.mac.forward

            def body(h, obs_t):
                q, h = fwd(agent_params, obs_t, h)
                return h, (q, h)

            _, (qs, hs) = jax.lax.scan(self._scan_body(body),
                                       self.mac.init_hidden(b), obs_tm)
        else:
            if self._agent_qslice:
                # noisy config on the fast path: sliced stack + per-step
                # noise keys into the q-head
                agent_params = self._fold_params(agent_params)

                def body(h, xs):
                    obs_t, k_t = xs
                    q, h = self.mac.forward_qslice(
                        agent_params, obs_t, h, key=k_t,
                        deterministic=False,
                        attn_impl=self.cfg.kernels.attention)
                    return h, (q, h)
            else:
                def body(h, xs):
                    obs_t, k_t = xs
                    q, h = self.mac.forward(agent_params, obs_t, h,
                                            key=k_t, deterministic=False)
                    return h, (q, h)

            keys = jax.random.split(key, obs_tm.shape[0])
            _, (qs, hs) = jax.lax.scan(
                self._scan_body(body), self.mac.init_hidden(b),
                (obs_tm, keys))
        return qs, hs

    def _unroll_mixer(self, mixer_params, q_tm: jnp.ndarray,
                      hid_tm: jnp.ndarray, state_tm: jnp.ndarray,
                      obs_tm: jnp.ndarray,
                      key: Optional[jax.Array] = None) -> jnp.ndarray:
        """q_tm ``(T, B, A)`` → ``q_tot (T, B)``; carries the 3 hyper tokens
        across time (``n_transf_mixer.py:91``)."""
        b = q_tm.shape[1]

        if key is None:
            if self._mixer_qslice:
                from ..ops.query_slice import make_mixer_qslice
                fold, mix = make_mixer_qslice(self.mixer)
                # fold once, outside the scan (differentiable)
                mixer_params = fold(mixer_params)
            else:
                mix = self.mixer.apply

            def body(hyper, xs):
                qv, h, s, o = xs
                q_tot, hyper = mix(mixer_params, qv[:, None, :], h, hyper,
                                   s, o)
                return hyper, q_tot[:, 0, 0]

            _, q_tots = jax.lax.scan(
                self._scan_body(body), self.mixer.initial_hyper(b),
                (q_tm, hid_tm, state_tm, obs_tm))
        else:
            def body(hyper, xs):
                qv, h, s, o, k_t = xs
                q_tot, hyper = self.mixer.apply(
                    mixer_params, qv[:, None, :], h, hyper, s, o,
                    deterministic=False, rngs={"dropout": k_t})
                return hyper, q_tot[:, 0, 0]

            keys = jax.random.split(key, q_tm.shape[0])
            _, q_tots = jax.lax.scan(
                self._scan_body(body), self.mixer.initial_hyper(b),
                (q_tm, hid_tm, state_tm, obs_tm, keys))
        return q_tots

    # ------------------------------------------------------------------ loss

    def _loss(self, params, target_params, batch: EpisodeBatch,
              weights: jnp.ndarray, key: Optional[jax.Array] = None
              ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        cfg = self.cfg
        # time-major views; obs/state may be stored bf16 (ReplayConfig
        # store_dtype) — lift back to f32 for the loss math. Compact entity
        # storage (CompactEntityObs) unrolls through the entity-table
        # forward instead of reconstructing the flat obs (the mixer never
        # reads obs in state_entity_mode, which the storage gate requires).
        from ..components.episode_buffer import CompactEntityObs
        if isinstance(batch.obs, CompactEntityObs):
            co = batch.obs
            mec = jnp.swapaxes(co.mec_index, 0, 1)
            compact_tm = (
                jnp.swapaxes(co.rows, 0, 1).astype(jnp.float32),
                mec[..., :, None] == mec[..., None, :],
                jnp.swapaxes(co.mean, 0, 1),
                jnp.swapaxes(co.std, 0, 1),
            )
            obs = None
        else:
            compact_tm = None
            obs = jnp.swapaxes(batch.obs, 0, 1).astype(jnp.float32)
        state = jnp.swapaxes(batch.state, 0, 1).astype(jnp.float32)
        avail = jnp.swapaxes(batch.avail_actions, 0, 1)   # (T+1, B, A, n)
        actions = jnp.swapaxes(batch.actions, 0, 1)       # (T, B, A)
        reward = jnp.swapaxes(batch.reward, 0, 1)         # (T, B)
        term = jnp.swapaxes(batch.terminated, 0, 1).astype(jnp.float32)
        mask = jnp.swapaxes(batch.filled, 0, 1).astype(jnp.float32)

        if key is not None:
            k_ag, k_tag, k_mx, k_tmx = jax.random.split(key, 4)
            if cfg.model.dropout == 0.0:
                # noisy-only configs: the mixer has no noise source
                # (NoisyLinear lives in the agent q-head only), so its
                # unroll stays on the deterministic fast path — passing
                # keys here forced the dense flax mixer scan for nothing
                k_mx = k_tmx = None
        else:
            k_ag = k_tag = k_mx = k_tmx = None

        # the two unrolls stay SEPARATE deliberately: the target unroll
        # feeds only stop_gradient-terminated consumers, so partial eval
        # prunes its backward pass and saves no residuals for it — fusing
        # both into one stacked scan would re-attach the target lane to the
        # VJP (zero cotangents still cost full backward matmuls + 2x scan
        # residual memory), trading a halved forward for a heavier backward
        qs, hs = self._unroll_agent(params["agent"], obs, k_ag,
                                    compact_tm=compact_tm)
        target_qs, target_hs = self._unroll_agent(
            target_params["agent"], obs, k_tag, compact_tm=compact_tm)

        # mixer-side padding mask (graftworld fleet-size randomization,
        # ROADMAP item 3's open remainder): padded agents are
        # action-0-only at EVERY step by construction (the env masks
        # them at reset and they can never acquire a job) AND always
        # occupy the TRAILING agent slots (EnvParams.agent_mask is
        # `arange < n_active`), so the maximal trailing block of agents
        # with "no non-idle action ever available across the episode
        # incl. the bootstrap step" identifies them from the stored
        # avail mask alone — no schema change, works for dense AND
        # compact storage. The suffix rule matters: an ACTIVE agent
        # whose job stream delivered nothing all episode is also
        # idle-only-forever, and a plain any-step test would zero its
        # (real) idle-Q contribution; with the suffix rule it is only
        # conservatively masked when every agent after it is idle-only
        # too (rare — and its sole contribution would have been the
        # idle-action Q of an agent that never interacted). Masked
        # agents' chosen/target Qs and hidden tokens enter the mixer
        # multiplied by 0.0 (the neutral contribution of a monotonic
        # mixer); active agents multiply by 1.0, which is bitwise-
        # identity, so a full-fleet batch where any tail agent saw a
        # job is bit-identical to the unmasked loss (pinned by
        # tests/test_population.py). The gate is config-STATIC
        # (_mask_padded): non-padding configs never compile any of
        # this.
        if self._mask_padded:
            saw_job = (avail[..., 1:] > 0).any(axis=(0, -1))  # (B, A)
            # active = suffix-any of saw_job: agent i is masked only
            # when agents i..A-1 ALL never saw a job (the padded tail)
            act_m = jnp.flip(jax.lax.cummax(
                jnp.flip(saw_job.astype(jnp.int32), -1), axis=1),
                -1) > 0

            def _padmask(x):
                # zero padded agents along the trailing agent axis
                # (x: (T?, B, A) or (T?, B, A, F))
                m = act_m.astype(x.dtype)
                return x * (m[None] if x.ndim == 3 else m[None, ..., None])

            hs, target_hs = _padmask(hs), _padmask(target_hs)
            if obs is not None:
                # Q12 fallback path: the mixer tokenizes all agents'
                # obs — padded rows go in as zeros too
                obs = _padmask(obs)
        else:
            _padmask = lambda x: x  # noqa: E731 — static no-op branch

        chosen = _padmask(jnp.take_along_axis(
            qs[:-1], actions[..., None], axis=-1)[..., 0])  # (T, B, A)

        # illegal actions suppressed in targets (MAC masking contract);
        # computed over ALL T+1 steps so the target mixer can unroll its
        # hyper-token recurrence from t=0 with the same history depth as
        # the online mixer (the targets themselves use steps [1:])
        masked_all = jnp.where(avail > 0, qs, -jnp.inf)
        if cfg.double_q:
            best = jnp.argmax(masked_all, axis=-1)         # online argmax
            target_max = jnp.take_along_axis(
                target_qs, best[..., None], axis=-1)[..., 0]
        else:
            target_max = jnp.where(
                avail > 0, target_qs, -jnp.inf).max(axis=-1)
        target_max = _padmask(target_max)

        obs_m = None if obs is None else obs[:-1]
        q_tot = self._unroll_mixer(
            params["mixer"], chosen, hs[:-1], state[:-1], obs_m, k_mx)
        # target unroll spans t=0..T (recurrence semantics of
        # /root/reference/n_transf_mixer.py:55,91: both nets start their
        # hyper recurrence at the episode start); outputs [1:] are the
        # bootstrap values
        target_q_tot = self._unroll_mixer(
            target_params["mixer"], target_max, target_hs, state,
            obs, k_tmx)[1:]   # obs may be None (compact storage: the
        # state-entity mixer never reads it)

        # reward_unit: static train-time unit normalization (the value
        # function is learned in reward/reward_unit units; logged returns
        # stay raw — see config.py loss-scale levers). 1.0 = off, exact.
        if cfg.reward_unit != 1.0:
            reward = reward / cfg.reward_unit
        targets = reward + cfg.gamma * (1.0 - term) * target_q_tot
        td = (q_tot - jax.lax.stop_gradient(targets)) * mask

        denom = jnp.maximum(mask.sum(), 1.0)
        if cfg.td_loss == "huber":
            # 2x-scaled Huber: td^2 inside |td|<=delta (matches the MSE
            # branch exactly), linear with slope 2*delta outside — bounds
            # each element's dLoss/dq_tot at 2*delta (config.py rationale).
            # Deliberately NOT optax.huber_loss: its min()-based form
            # accumulates backward cotangents as q + delta - delta, which
            # cancels catastrophically in f32 once delta >> |td| (grads of
            # small TDs round to 0 at delta=1e9, breaking the delta->inf
            # == MSE identity the tests pin); branch selection via where
            # keeps each cotangent path exact at any delta.
            d = cfg.huber_delta
            abs_td = jnp.abs(td)
            elem = jnp.where(abs_td <= d, td ** 2, 2.0 * d * abs_td - d * d)
        else:
            elem = td ** 2
        loss = (weights[None, :] * elem).sum() / denom

        ep_mask = jnp.maximum(mask.sum(axis=0), 1.0)
        info = {
            "loss": loss,
            "td_error_abs": jnp.abs(td).sum() / denom,
            "q_taken_mean": (chosen.mean(axis=-1) * mask).sum() / denom,
            "target_mean": (targets * mask).sum() / denom,
            # per-episode priorities (Q9): masked mean |TD| per sample
            "td_errors_abs": jnp.abs(td).sum(axis=0) / ep_mask,   # (B,)
        }
        if cfg.obs.sight.enabled:
            # graftsight in-graph diagnostics (docs/OBSERVABILITY.md §6):
            # value-scale histograms + one-timestep attention-entropy
            # probes, reduced on device into the info dict so they ride
            # the log-cadence fetch. STATIC gate — off leaves this
            # program byte-identical (graftprog fingerprints pinned);
            # stop_gradient severs every probe from the backward pass.
            from ..obs import sight as graftsight
            sg = jax.lax.stop_gradient
            info.update(graftsight.loss_sight_info(
                cfg.obs.sight, sg(td), sg(chosen), sg(targets), mask))
            if cfg.agent == "transformer":
                info["sight_attn_entropy_agent"] = \
                    graftsight.agent_attention_entropy(
                        self, params["agent"],
                        None if obs is None else obs[0],
                        None if compact_tm is None
                        else tuple(x[0] for x in compact_tm))
            if cfg.mixer == "transformer":
                info["sight_attn_entropy_mixer"] = \
                    graftsight.mixer_attention_entropy(
                        self, params["mixer"], state[0],
                        None if obs is None else obs[0], sg(hs[0]))
        return loss, info

    # ------------------------------------------------------------------ train

    def train_info_zeros(self, batch_size: int) -> Dict[str, jnp.ndarray]:
        """Aval-matched zero info dict for a SKIPPED train step — the
        superstep's ``lax.cond`` needs both branches to return identical
        pytrees (``run.Experiment.superstep_program``). Must mirror the
        keys/shapes/dtypes ``train`` emits; ``all_finite=True`` so skipped
        sub-iterations never feed the driver's non-finite streak
        accounting."""
        z = jnp.zeros((), jnp.float32)
        out = {
            "loss": z, "td_error_abs": z, "q_taken_mean": z,
            "target_mean": z, "grad_norm": z,
            "td_errors_abs": jnp.zeros((batch_size,), jnp.float32),
            "all_finite": jnp.ones((), bool),
        }
        if self.cfg.obs.sight.enabled:
            # graftsight keys are part of the emitted pytree when the
            # static gate is on — the skip branch must mirror them
            # (aval-exact; the key set is a function of the CONFIG)
            from ..obs import sight as graftsight
            out.update(graftsight.train_info_extras_zeros(self.cfg))
        return out

    def train(self, ls: LearnerState, batch: EpisodeBatch,
              weights: jnp.ndarray, t_env: jnp.ndarray,
              episode: jnp.ndarray, key: Optional[jax.Array] = None,
              spec=None) -> Tuple[LearnerState, Dict[str, jnp.ndarray]]:
        """One importance-weighted QMIX update; hard target sync every
        ``target_update_interval`` episodes (PyMARL convention, M8).
        ``key`` drives NoisyLinear/dropout sampling and is required when the
        config uses either (otherwise sigma params get zero gradient).

        ``spec`` (a graftpop ``PopulationSpec`` of traced per-member
        scalars, ``None`` for every pre-population caller) applies the
        member's learning rate as an update-tree scale: lr enters
        optax's adam/rmsprop linearly AFTER the moment statistics, so
        ``updates · (lr_i/lr)`` is exactly training at ``lr_i`` — and
        the clip-by-global-norm rung acts on raw gradients, which are
        lr-independent. 1.0 multiplies bitwise-identically (the P=1
        parity contract).

        Non-finite guard rail (docs/RESILIENCE.md): ``info["all_finite"]``
        flags whether loss AND gradients came out finite; when it trips,
        params and optimizer state pass through UNCHANGED (elementwise
        select inside jit — no host sync, the async dispatch pipeline
        stays unblocked) and the driver decides at its log cadence whether
        the streak warrants a checkpoint restore. ``train_steps`` counts
        train-step *invocations* (skipped updates included) so fault
        injection and step-indexed diagnostics stay monotonic across
        skips. ``isfinite(global_norm)`` covers every grad leaf: one
        NaN/Inf anywhere poisons the norm."""
        del t_env
        if self.needs_rngs and key is None:
            raise ValueError(
                "QMixLearner.train needs a PRNG key when "
                "action_selector='noisy-new' or dropout>0 (noise/dropout "
                "must be sampled during the loss unroll)")
        if not self.needs_rngs:
            key = None   # identical program for all callers in the pure path
        opt = _make_optimizer(self.cfg)

        inject_at = self.cfg.resilience.inject_nan_at_step

        def loss_fn(params):
            loss, info = self._loss(params, ls.target_params, batch,
                                    weights, key)
            if inject_at >= 0:       # fault injection (static: free when off)
                trip = ls.train_steps == inject_at
                loss = loss * jnp.where(trip, jnp.float32(jnp.nan),
                                        jnp.float32(1.0))
                info = dict(info, loss=loss)
            return loss, info

        grads, info = jax.grad(loss_fn, has_aux=True)(ls.params)
        info["grad_norm"] = optax.global_norm(grads)
        all_finite = (jnp.isfinite(info["loss"])
                      & jnp.isfinite(info["grad_norm"]))
        info["all_finite"] = all_finite
        updates, opt_state = opt.update(grads, ls.opt_state, ls.params)
        if spec is not None:
            # graftpop per-member lr: scale the update tree (exact — see
            # the docstring; opt_state is lr-independent by construction)
            updates = jax.tree.map(
                lambda u: u * spec.lr_scale.astype(u.dtype), updates)
        params = optax.apply_updates(ls.params, updates)
        # guard rail: a tripped step is a no-op on params AND opt state
        # (a NaN grad corrupts Adam's mu/nu permanently, so opt_state must
        # pass through too, not just params)
        params = jax.tree.map(
            lambda n, o: jnp.where(all_finite, n, o), params, ls.params)
        opt_state = jax.tree.map(
            lambda n, o: jnp.where(all_finite, n, o), opt_state,
            ls.opt_state)
        if self.cfg.obs.sight.enabled:
            # graftsight learner-tail block: per-module grad/update
            # norms, importance-weight ESS, target drift — computed
            # AFTER the guard select so a tripped step reports the
            # surviving (unchanged) params' drift, not the poisoned ones
            from ..obs import sight as graftsight
            info.update(graftsight.learner_train_info(
                self.cfg, grads, updates, params, ls.target_params,
                weights))

        episode = jnp.asarray(episode, jnp.int32)
        sync = (episode - ls.last_target_update
                ) >= self.cfg.target_update_interval
        target_params = jax.tree.map(
            lambda p, tp: jnp.where(sync, p, tp), params, ls.target_params)
        return LearnerState(
            params=params,
            target_params=target_params,
            opt_state=opt_state,
            train_steps=ls.train_steps + 1,
            last_target_update=jnp.where(sync, episode,
                                         ls.last_target_update),
        ), info


LEARNER_REGISTRY = {"qmix_learner": QMixLearner}


def register_audit_programs(ctx):
    """graftprog registry hook (``analysis/registry.py``): the bare
    learner update as its own named program — the narrowest surface the
    dtype-churn rule (GP203) watches, so an upcast introduced in the
    loss/optimizer math is attributed to the learner even before it
    shows up in the fused superstep's budgets. Audited from abstract
    avals only (the replay sample's eval_shape); never executed."""
    import jax

    from ..analysis.registry import AuditProgram, kernels_audit_context

    def entry(c, description):
        exp, ts, cfg = c.exp, c.ts_shape, c.cfg
        key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
        batch, _, weights = jax.eval_shape(
            lambda b, k, t: exp.buffer.sample(b, k, cfg.batch_size, t),
            ts.buffer, key, ts.runner.t_env)
        train = jax.jit(exp.learner.train)
        return AuditProgram(
            train, (ts.learner, batch, weights, ts.runner.t_env,
                    ts.episode, key),
            description=description)

    out = {"learner_train": entry(
        ctx, "one importance-weighted QMIX update (loss + optimizer + "
             "target sync)")}
    # kernel-mode byte-comparison pair (PR 13): the bare learner update
    # under each kernels.attention mode at the kernel audit scale —
    # narrows the train_iter_pallas[_ref] comparison to the learner
    # alone, so a bytes regression is attributable before it shows up in
    # the composite program (lowered level; pallas pinned strictly below
    # the _ref twin by tests/test_graftprog.py)
    for mode, name in (("pallas", "learner_train_pallas"),
                       ("xla", "learner_train_pallas_ref")):
        out[name] = entry(
            kernels_audit_context(mode),
            f"one QMIX update under kernels.attention={mode} at the "
            f"kernel audit scale — the flash-vs-einsum learner byte "
            f"comparison (pallas must stay strictly below the _ref "
            f"twin)")
    return out
