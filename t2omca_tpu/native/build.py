"""Build + load the native sum-tree via ctypes.

No pybind11 in the image (environment constraint), so the C++ side is a
plain ``extern "C"`` shared object compiled with g++ on first use and cached
next to the source keyed by source mtime. Callers should catch
``NativeBuildError`` and fall back to the pure-NumPy sum-tree
(``components/host_replay.PySumTree``) when no toolchain is present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_SRC = os.path.join(os.path.dirname(__file__), "sumtree.cpp")
_LIB_CACHE = {}


class NativeBuildError(RuntimeError):
    pass


def _build_lib() -> str:
    cache_dir = os.path.join(tempfile.gettempdir(), "t2omca_native")
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, "libsumtree.so")
    if (os.path.exists(so_path)
            and os.path.getmtime(so_path) >= os.path.getmtime(_SRC)):
        return so_path
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", so_path + ".tmp", _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        detail = getattr(e, "stderr", str(e))
        raise NativeBuildError(f"g++ build failed: {detail}") from e
    os.replace(so_path + ".tmp", so_path)
    return so_path


def load_sumtree() -> ctypes.CDLL:
    """→ CDLL with typed signatures; raises NativeBuildError when no g++."""
    if "lib" in _LIB_CACHE:
        return _LIB_CACHE["lib"]
    lib = ctypes.CDLL(_build_lib())
    c = ctypes
    lib.sumtree_create.restype = c.c_void_p
    lib.sumtree_create.argtypes = [c.c_int64]
    lib.sumtree_free.argtypes = [c.c_void_p]
    lib.sumtree_set.argtypes = [c.c_void_p, c.c_int64, c.c_double]
    lib.sumtree_set_batch.argtypes = [
        c.c_void_p, c.POINTER(c.c_int64), c.POINTER(c.c_double), c.c_int64]
    lib.sumtree_total.restype = c.c_double
    lib.sumtree_total.argtypes = [c.c_void_p]
    lib.sumtree_get.restype = c.c_double
    lib.sumtree_get.argtypes = [c.c_void_p, c.c_int64]
    lib.sumtree_get_batch.argtypes = [
        c.c_void_p, c.POINTER(c.c_int64), c.c_int64,
        c.POINTER(c.c_double)]
    lib.sumtree_find.restype = c.c_int64
    lib.sumtree_find.argtypes = [c.c_void_p, c.c_double]
    lib.sumtree_sample.argtypes = [
        c.c_void_p, c.POINTER(c.c_double), c.c_int64,
        c.POINTER(c.c_int64), c.POINTER(c.c_double)]
    _LIB_CACHE["lib"] = lib
    return lib
