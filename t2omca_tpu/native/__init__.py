from .build import load_sumtree

__all__ = ["load_sumtree"]
