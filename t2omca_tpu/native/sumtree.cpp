// Sum-tree priority index for host-side prioritized replay.
//
// The reference keeps its replay on CPU when `buffer_cpu_only` is set
// (/root/reference/per_run.py:143-146 device selection) — episodes live in
// host RAM and only sampled batches move to the accelerator. This is the
// native backend for that mode in the TPU framework: a classic binary
// sum-tree over per-episode priorities giving O(log n) set / prefix-sum
// sampling, called from Python through ctypes (no pybind11 in the image).
//
// The device-resident PER (components/episode_buffer.py) stays the default;
// this path exists for buffer sizes beyond HBM (e.g. 10^5+ long episodes).
//
// Layout: standard implicit binary tree in a flat array of 2*cap floats;
// leaves at [cap, 2*cap), internal node i sums children 2i/2i+1. Capacity is
// rounded up to a power of two by the Python wrapper.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

struct SumTree {
    int64_t cap;       // leaf count (power of two)
    double *tree;      // 2*cap nodes; [0] unused, root at [1]
};

SumTree *sumtree_create(int64_t cap) {
    SumTree *t = static_cast<SumTree *>(std::malloc(sizeof(SumTree)));
    if (!t) return nullptr;
    t->cap = cap;
    t->tree = static_cast<double *>(std::calloc(2 * cap, sizeof(double)));
    if (!t->tree) { std::free(t); return nullptr; }
    return t;
}

void sumtree_free(SumTree *t) {
    if (!t) return;
    std::free(t->tree);
    std::free(t);
}

void sumtree_set(SumTree *t, int64_t idx, double priority) {
    int64_t i = t->cap + idx;
    double delta = priority - t->tree[i];
    for (; i >= 1; i >>= 1) t->tree[i] += delta;
}

void sumtree_set_batch(SumTree *t, const int64_t *idx, const double *pri,
                       int64_t n) {
    for (int64_t j = 0; j < n; ++j) sumtree_set(t, idx[j], pri[j]);
}

double sumtree_total(const SumTree *t) { return t->tree[1]; }

double sumtree_get(const SumTree *t, int64_t idx) {
    return t->tree[t->cap + idx];
}

// Batched leaf read: one ctypes crossing for n leaves instead of the
// Python-side one-call-per-element loop (the O(n) FFI overhead the
// wrapper's old list comprehension paid on every priority readback).
void sumtree_get_batch(const SumTree *t, const int64_t *idx, int64_t n,
                       double *out) {
    for (int64_t j = 0; j < n; ++j) out[j] = t->tree[t->cap + idx[j]];
}

// Descend from the root following the prefix sum `u` in [0, total).
int64_t sumtree_find(const SumTree *t, double u) {
    int64_t i = 1;
    while (i < t->cap) {
        double left = t->tree[2 * i];
        if (u < left) {
            i = 2 * i;
        } else {
            u -= left;
            i = 2 * i + 1;
        }
    }
    return i - t->cap;
}

// Stratified sampling: one uniform per equal-mass stratum (the same scheme
// as the device buffer's inverse-CDF sampler). `us` are n uniforms in [0,1).
void sumtree_sample(const SumTree *t, const double *us, int64_t n,
                    int64_t *out_idx, double *out_pri) {
    double total = t->tree[1];
    for (int64_t j = 0; j < n; ++j) {
        double u = (static_cast<double>(j) + us[j]) / static_cast<double>(n);
        int64_t idx = sumtree_find(t, u * total);
        out_idx[j] = idx;
        out_pri[j] = t->tree[t->cap + idx];
    }
}

}  // extern "C"
