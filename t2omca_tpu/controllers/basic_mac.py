"""Multi-agent controller (M7, the unreleased ``controllers`` package).

Contract pinned by the call sites (SURVEY.md §2.3 M7): owns the shared-
parameter agent network and the action selector; ``init_hidden(batch)``;
``select_actions(batch_slice, t_env, key, test_mode)`` masking illegal
actions with ``avail_actions``; agents grouped under ``"agents"`` share one
parameter set (the reference folds the agent axis into the batch axis,
``/root/reference/transf_agent.py:56-59`` — we do the same inside
``TransformerAgent``).

Functional form: the MAC is a frozen descriptor (module + selector); all
state (params, hidden tokens) is passed explicitly, so the same MAC drives
the jitted rollout scan, the learner's time unroll, and greedy evaluation.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from ..components.action_selectors import SELECTOR_REGISTRY
from ..components.schedules import DecayThenFlatSchedule
from ..config import TrainConfig
from ..models.agent import TransformerAgent
from ..models.rnn_agent import RNNAgent

#: agent families (parent PyMARL lineage registry pattern, SURVEY.md §2.3 M7)
AGENT_REGISTRY = {"transformer": TransformerAgent, "rnn": RNNAgent}


@dataclasses.dataclass(frozen=True)
class BasicMAC:
    agent: TransformerAgent
    selector: object            # EpsilonGreedySelector | NoisySelector
    n_agents: int
    n_actions: int
    emb: int
    use_qslice: bool = False    # exact token-0-only forward (ops/query_slice)
    use_entity_tables: bool = False   # table-contracted entity acting
    # acting-path compute dtype (model.act_dtype, docs/PERF.md): None =
    # inherit the agent's (train) dtype — byte-identical to pre-act_dtype
    # builds. When it differs, select_actions runs its forwards in this
    # dtype over params pre-cast once per rollout
    # (prepare_acting_params), while the learner unrolls keep the train
    # dtype (acting=False default on the forwards below).
    act_dtype: object = None
    # dense-path module clone at act_dtype (None = share `agent`); the
    # qslice/entity forwards take the dtype as an argument instead
    act_agent: object = None

    @classmethod
    def build(cls, cfg: TrainConfig, env_info: dict) -> "BasicMAC":
        n_agents = env_info["n_agents"]
        n_entities = cfg.model.n_entities_obs or env_info["n_entities"]
        feat = env_info.get("obs_entity_feats")
        if feat is None or cfg.agent == "rnn":
            # flat-obs mode / flat-input agents: the whole obs vector is one
            # entity token
            n_entities, feat = 1, env_info["obs_shape"]
        agent = AGENT_REGISTRY[cfg.agent](
            n_agents=n_agents,
            n_entities=n_entities + 0,
            feat_dim=feat,
            emb=cfg.model.emb,
            heads=cfg.model.heads,
            depth=cfg.model.depth,
            n_actions=env_info["n_actions"],
            ff_hidden_mult=cfg.model.ff_hidden_mult,
            dropout=cfg.model.dropout,
            noisy=cfg.action_selector == "noisy-new",
            standard_heads=cfg.model.standard_heads,
            use_orthogonal=cfg.model.use_orthogonal,
            dtype=jnp.dtype(cfg.model.dtype),
            attn_impl=cfg.kernels.attention,
        )
        schedule = DecayThenFlatSchedule(
            cfg.epsilon_start, cfg.epsilon_finish, cfg.epsilon_anneal_time)
        selector = SELECTOR_REGISTRY[cfg.action_selector](schedule)
        # query-slice eligibility (shared predicate, ops/query_slice.py)
        from ..ops.query_slice import (agent_qslice_eligible,
                                       entity_tables_eligible)
        use_qslice = agent_qslice_eligible(cfg)
        act_dtype = jnp.dtype(cfg.model.act_dtype or cfg.model.dtype)
        # param shapes are dtype-independent, so the acting clone applies
        # the SAME param tree — only the activation casts differ
        act_agent = (agent.clone(dtype=act_dtype)
                     if act_dtype != agent.dtype else None)
        return cls(agent=agent, selector=selector, n_agents=n_agents,
                   n_actions=env_info["n_actions"], emb=cfg.model.emb,
                   use_qslice=use_qslice,
                   use_entity_tables=(use_qslice
                                      and entity_tables_eligible(cfg)),
                   act_dtype=act_dtype, act_agent=act_agent)

    # ------------------------------------------------------------------ state

    def init_params(self, key: jax.Array, obs_dim: int):
        obs = jnp.zeros((1, self.n_agents, obs_dim))
        h = self.init_hidden(1)
        return self.agent.init(key, obs, h)

    def init_hidden(self, batch_size: int) -> jnp.ndarray:
        """Zeros ``(batch, n_agents, emb)`` (``transf_agent.py:50-52``)."""
        return self.agent.initial_hidden(batch_size)

    # ------------------------------------------------------------------ forward

    @property
    def _acting_dtype(self):
        """Acting-path compute dtype (falls back to the train dtype for
        MACs constructed directly in tests/legacy callers)."""
        return (self.act_dtype if self.act_dtype is not None
                else self.agent.dtype)

    def forward(self, params, obs: jnp.ndarray, hidden: jnp.ndarray,
                key: jax.Array | None = None, deterministic: bool = True,
                acting: bool = False
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """obs ``(B, A, obs_dim)`` → (q ``(B, A, n_actions)``, hidden').
        ``key`` seeds NoisyLinear resampling and dropout when
        ``deterministic`` is False. ``acting=True`` (select_actions)
        runs the act_dtype module clone; the learner unroll keeps the
        default (train dtype)."""
        if key is not None:
            k_noise, k_drop = jax.random.split(key)
            rngs = {"noise": k_noise, "dropout": k_drop}
        else:
            rngs = None
        module = (self.act_agent if acting and self.act_agent is not None
                  else self.agent)
        return module.apply(params, obs, hidden,
                            deterministic=deterministic, rngs=rngs)

    def _noise_key(self, key, deterministic: bool):
        """Noise key for the qslice/entity q-head: only noisy agents in
        non-deterministic (train rollout / learner) mode sample noise —
        mirroring ``TransformerAgent``'s eval-mode mu path."""
        if key is None or deterministic or not self.agent.noisy:
            return None
        return key

    def forward_qslice(self, params, obs: jnp.ndarray, hidden: jnp.ndarray,
                       key: jax.Array | None = None,
                       deterministic: bool = True,
                       acting: bool = False,
                       attn_impl: str | None = None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Exact token-0-only forward over the same param tree
        (ops/query_slice). Plain jnp, differentiable — also used by the
        learner's deterministic AND noisy unrolls (the noise lives only in
        the q-head). ``params`` may be the raw tree or a
        ``prepare_acting_params`` result; ``acting=True`` computes in the
        act_dtype (and must be paired with the acting-dtype fold — the
        folded tree short-circuits the per-call fold).

        ``attn_impl`` selects the sliced-attention lowering
        (``kernels.attention``); ``None`` keeps the einsum path, so
        acting, serving and every legacy caller stay byte-identical —
        ONLY the learner unroll passes the config switch (the flash
        kernel's win is the train-path backward, docs/PERF.md)."""
        from ..ops.query_slice import agent_forward_qslice
        a = self.agent
        return agent_forward_qslice(
            params, obs, hidden,
            n_entities=a.n_entities, feat_dim=a.feat_dim, emb=a.emb,
            heads=a.heads, depth=a.depth, n_actions=a.n_actions,
            standard_heads=a.standard_heads,
            dtype=self._acting_dtype if acting else a.dtype,
            noise_key=self._noise_key(key, deterministic),
            attn_impl=attn_impl or "xla")

    def forward_entity(self, params, compact, hidden: jnp.ndarray,
                       key: jax.Array | None = None,
                       deterministic: bool = True,
                       acting: bool = False
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Entity-table forward (ops/query_slice): ``compact`` is the
        ``env.compact_obs`` tuple, batched over envs."""
        from ..ops.query_slice import agent_forward_qslice_entity
        rows, same_mec, mean, std = compact
        a = self.agent
        return agent_forward_qslice_entity(
            params, rows, same_mec, mean, std, hidden,
            emb=a.emb, heads=a.heads, depth=a.depth, n_actions=a.n_actions,
            standard_heads=a.standard_heads,
            dtype=self._acting_dtype if acting else a.dtype,
            noise_key=self._noise_key(key, deterministic))

    def prepare_acting_params(self, params, dtype=None):
        """Pre-fold the qslice projection products ONCE, outside any scan
        that calls ``select_actions``/``forward_qslice`` in its body (the
        fold is loop-invariant; XLA is not guaranteed to hoist it). The
        fold runs in the ACTING dtype; under the bf16-acting mode
        (model.act_dtype over an f32 train dtype) the remaining float
        leaves are pre-cast here too, so every scan step reads half the
        param bytes instead of re-casting f32 storage per step. No-op
        on the dense path with the default act_dtype.

        ``dtype`` overrides the fold dtype (the serving exporter passes
        the TRAIN dtype so the artifact's canonical f32 variant stays
        act_dtype-free — serving's dtype story is the per-variant cast,
        not the training run's rollout knob)."""
        ad = jnp.dtype(dtype) if dtype is not None else self._acting_dtype
        if not self.use_qslice:
            return self._cast_acting(params, ad)
        from ..ops.query_slice import fold_agent_params
        a = self.agent
        folded = fold_agent_params(params, emb=a.emb, heads=a.heads,
                                   depth=a.depth,
                                   standard_heads=a.standard_heads,
                                   dtype=ad)
        return self._cast_acting(folded, ad)

    def _cast_acting(self, tree, ad):
        """Pre-cast f32 param leaves to the acting dtype — only in the
        explicit mixed mode (act_dtype != train dtype), so every default
        config keeps its exact pre-act_dtype numerics. LayerNorm/softmax
        STATISTICS stay f32 regardless (computed in f32 inside the
        forwards; docs/PERF.md dtype policy)."""
        if ad == self.agent.dtype:
            return tree
        cast = lambda x: (x.astype(ad)
                          if (hasattr(x, "dtype")
                              and x.dtype == jnp.float32) else x)
        return jax.tree.map(cast, tree)

    def select_actions(self, params, obs: jnp.ndarray, avail: jnp.ndarray,
                       hidden: jnp.ndarray, key: jax.Array,
                       t_env: jnp.ndarray, test_mode: bool = False,
                       compact=None, eps_scale=None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """→ (actions ``(B, A)`` int32, hidden', epsilon). The avail mask is
        applied inside the selector (illegal-action masking, M7).
        ``compact`` (the batched ``env.compact_obs`` tuple) activates the
        entity-table forward when the MAC was built eligible.
        ``eps_scale`` (optional traced scalar) is the graftpop
        per-member epsilon multiplier, forwarded to the selector."""
        k_noise, k_sel = jax.random.split(key)
        if self.use_entity_tables and compact is not None:
            q, hidden = self.forward_entity(params, compact, hidden,
                                            key=k_noise,
                                            deterministic=test_mode,
                                            acting=True)
        elif self.use_qslice:
            q, hidden = self.forward_qslice(params, obs, hidden,
                                            key=k_noise,
                                            deterministic=test_mode,
                                            acting=True)
        else:
            q, hidden = self.forward(params, obs, hidden, key=k_noise,
                                     deterministic=test_mode, acting=True)
        actions, eps = self.selector.select(k_sel, q, avail, t_env,
                                            test_mode=test_mode,
                                            eps_scale=eps_scale)
        return actions.astype(jnp.int32), hidden, eps


MAC_REGISTRY = {"basic_mac": BasicMAC}
