from .basic_mac import BasicMAC, MAC_REGISTRY

__all__ = ["BasicMAC", "MAC_REGISTRY"]
