"""t2omca_tpu — a TPU-native multi-agent RL framework.

A brand-new JAX/XLA implementation of the capabilities of hj5717/T2OMCA
(a QMIX-family multi-agent RL system with transformer agents and a
transformer mixing network trained on a multi-AGV/MEC task-offloading
environment). Instead of the reference's subprocess-per-environment
rollout (`/root/reference/parallel_runner.py`) and single-device PyTorch
learner (`/root/reference/per_run.py`), everything here — environment,
rollout, replay, train step — is a pure function on pytrees composed with
`jax.vmap` (env batch), `jax.lax.scan` (episode time) and `jax.sharding`
meshes (data parallelism over ICI).

Package map:
  envs/         pure-functional MultiAgvOffloading environment + registry
  models/       flax modules: Transformer core, TransformerAgent, TransformerMixer
  controllers/  multi-agent controller (MAC) + action selectors
  learners/     QMIX TD learner (scan-over-time, double-Q, PER weights)
  runners/      vmapped rollout runner + single-env episode runner
  replay/       episode batch pytree + uniform & prioritized replay (device-resident)
  parallel/     mesh construction, sharded train step, ring attention (SP extension)
  ops/          hot-path op reductions (query-slice / entity tables)
  utils/        logging, time helpers, schedules, checkpointing
"""

__version__ = "0.1.0"
