"""Sebulba-style decoupled actor/learner device partitioning (Podracer).

The Anakin-style fused superstep (docs/SPEC.md §8) runs rollout and
training *serialized on the same devices* — each phase idles the other,
and the measured env-steps/s/chip caps well below the ROADMAP target.
Podracer's Sebulba variant (PAPERS.md, arXiv 2104.06272) splits the
visible devices into a disjoint **actor set** (runs the rollout) and
**learner set** (owns the replay ring and the train step) with a bounded
**device-resident trajectory queue** between them, so both stay
saturated; EnvPool (arXiv 2206.10558) shows the same async-batching
principle pays even at single-host scale. This module holds the device
machinery; the driver loop lives in ``run.run_sebulba`` (host threads
only orchestrate dispatches — every value stays on device).

Pieces:

* :func:`mesh.partition_devices` — the disjoint (actor, learner) split.
* :class:`QueueState` — a ring of ``queue_slots`` trajectory slots on
  the learner devices, each holding one rollout batch in the rollout
  scan's TIME-MAJOR emission form (``TimeMajorEpisodes`` — never the
  assembled ``(B, T+1, ...)`` episode batch). ``put`` is one scatter
  per leaf into the slot axis; ``get`` gathers a slot and feeds it
  straight to ``ReplayBuffer.insert_time_major`` (the PR 9 combined
  ``(slot, t)`` index-map machinery — one scatter per leaf into the
  ring), so an episode batch is never materialized anywhere on the
  actor→queue→ring path.
* :class:`LearnerSideState` — the learner-device half of the train
  state (learner params/opt + replay ring + episode counter); the
  runner state is the actor-device half. ``split``/``join`` convert to
  and from the driver's checkpointable ``TrainState`` pytree.
* :class:`Sebulba` — builds the per-mesh placements and the four jitted
  programs (``_actor_step``, ``_queue_put``, ``_queue_get``,
  ``_learner_step``) plus the learner→actor parameter publish (an async
  device-to-device copy). Queue ordering/backpressure is host-side SPSC
  bookkeeping (``run.run_sebulba``); device-side correctness needs no
  locks because every queue/learner-state handle is threaded linearly
  through donated programs — each dispatch consumes its predecessor's
  output, so execution order is enforced by dataflow.

Correctness anchor (ROADMAP item 2): the lockstep mode
(``queue_slots=1, staleness=0``) is **bit-identical** to the classic
K=1 three-program loop — same rollout definition (``run_raw``), a ring
insert pinned bit-identical to ``insert_episode_batch`` (PR 9), the
same sample→train→priority-feedback arithmetic and the same host-side
key threading — pinned by tests/test_sebulba.py on forced multi-device
CPU hosts (the DP test trick).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..components.episode_buffer import BufferState, TimeMajorEpisodes
from ..learners.qmix_learner import LearnerState
# one source for the weak_type-stripping invariant (run.py's chained-
# output retrace guard) and the graftpop P=1 bit-parity bridge (squeeze
# the member axis inside the jit, restore it on the way out); run.py
# imports nothing from parallel/ at module level, so this is cycle-free
from ..run import _expand0, _squeeze0, _strong


@struct.dataclass
class LearnerSideState:
    """The learner-device half of ``run.TrainState`` (everything except
    the actor-resident runner state): what the learner thread's consume
    and train programs carry and donate."""

    learner: LearnerState
    buffer: BufferState
    episode: jnp.ndarray        # () int32 — episodes consumed into the ring


@struct.dataclass
class QueueState:
    """Bounded ring of trajectory slots on the learner devices. Leaves
    are the rollout scan's time-major emission with a leading
    ``(queue_slots,)`` axis. Which slots hold live data is host-side
    SPSC bookkeeping (put/get counters in ``run.run_sebulba``) — the
    device state is pure storage."""

    slots: TimeMajorEpisodes    # leaves (S, T(+1 via last_*), B, ...)


@dataclasses.dataclass(frozen=True)
class Sebulba:
    """Decoupled actor/learner programs for an ``Experiment``.

    Usage (the ``run.run_sebulba`` shape)::

        seb = Sebulba.build(exp, actor_devs, learner_devs, queue_slots)
        rs, ls = seb.init_states(seed)       # born on their meshes
        q = seb.init_queue()
        params = seb.publish_params(ls.learner.params["agent"])
        rs, tm, stats = seb.actor_step(params, rs)
        q = seb.queue_put(q, slot, seb.to_learner(tm))
        ls, q = seb.queue_get(ls, q, slot)   # gather slot -> ring insert
        ls, info = seb.learner_step(ls, key, t_env)

    Both device sets are 1-D ``data`` meshes: env lanes shard over the
    actor mesh, replay episodes (and queue slots' batch axis) over the
    learner mesh, params/scalars replicate — the same placement rules as
    ``DataParallel``, applied per set. Size-1 sets reduce to plain
    single-device placement, so the 1+1 smoke/lockstep configs pay no
    SPMD machinery.

    ``population=P`` (graftlattice, docs/POPULATION.md §composition)
    stacks a leading ``(P,)`` member axis on EVERY state/emission leaf
    and swaps the placement rule: the member axis shards over each
    mesh (whole members per device — members never communicate) and
    the programs vmap the same bodies over it. ``spec`` is the
    concrete :class:`~t2omca_tpu.population.PopulationSpec` baked into
    the programs as a closure constant — legal because PBT (the only
    spec mutator) is rejected under sebulba, so the spec is static for
    the life of the run. P=1 squeezes instead of vmapping (the
    population bit-parity bridge).
    """

    exp: object                 # run.Experiment (duck-typed, avoids cycle)
    actor_mesh: Mesh
    learner_mesh: Mesh
    queue_slots: int
    axis: str = "data"
    population: int = 0         # P members; 0 = no population axis
    spec: object = None         # PopulationSpec (static — PBT rejected)

    @classmethod
    def build(cls, exp, actor_devices: Sequence, learner_devices: Sequence,
              queue_slots: int, population: int = 0,
              spec: object = None) -> "Sebulba":
        if set(actor_devices) & set(learner_devices):
            raise ValueError("actor and learner device sets must be "
                             "disjoint — overlap re-serializes the phases "
                             "the split exists to overlap")
        if queue_slots < 1:
            raise ValueError(f"queue_slots must be >= 1, got {queue_slots}")
        cfg = exp.cfg
        if population:
            # the (P,) MEMBER axis shards over each set — whole members
            # per device — so P must tile each mesh; the env-lane and
            # episode-axis tilings below only bind the solo layout
            if spec is None:
                raise ValueError("population > 0 requires the concrete "
                                 "PopulationSpec (build_spec(cfg))")
            for what, devs in (("actor", actor_devices),
                               ("learner", learner_devices)):
                if population % len(devs):
                    raise ValueError(
                        f"population={population} must divide over "
                        f"{len(devs)} {what} devices (member-axis "
                        f"sharding)")
        else:
            if cfg.batch_size_run % len(actor_devices):
                raise ValueError(
                    f"batch_size_run={cfg.batch_size_run} must divide over "
                    f"{len(actor_devices)} actor devices")
            if (cfg.batch_size % len(learner_devices)
                    or cfg.replay.buffer_size % len(learner_devices)):
                raise ValueError(
                    f"batch_size={cfg.batch_size} and replay.buffer_size="
                    f"{cfg.replay.buffer_size} must divide over "
                    f"{len(learner_devices)} learner devices")
        return cls(exp=exp,
                   actor_mesh=Mesh(np.asarray(actor_devices), ("data",)),
                   learner_mesh=Mesh(np.asarray(learner_devices),
                                     ("data",)),
                   queue_slots=int(queue_slots),
                   population=int(population), spec=spec)

    # ------------------------------------------------------------ shardings

    def _sh(self, mesh: Mesh, *axes) -> NamedSharding:
        return NamedSharding(mesh, P(*axes))

    def runner_shardings(self, rs_like):
        """Actor-mesh placement for the runner state: env lanes sharded,
        key/t_env replicated, reward-scale per-ndim (the
        ``DataParallel.state_shardings`` runner rules, on the actor
        mesh). Under a population EVERY leaf is ``(P,)``-stacked and
        shards uniformly on its leading member axis instead."""
        if self.population:
            member = self._sh(self.actor_mesh, self.axis)
            return jax.tree.map(lambda _: member, rs_like)
        lane = self._sh(self.actor_mesh, self.axis)
        rep = self._sh(self.actor_mesh)
        return rs_like.replace(
            env_states=jax.tree.map(lambda _: lane, rs_like.env_states),
            key=rep, t_env=rep,
            rscale=jax.tree.map(
                lambda x: lane if getattr(x, "ndim", 0) else rep,
                rs_like.rscale),
            # graftworld scenario instances shard with their env lanes
            # (every EnvParams leaf is batched (B, ...))
            env_params=jax.tree.map(lambda _: lane, rs_like.env_params))

    def learner_shardings(self, ls_like):
        """Learner-mesh placement: params/opt replicated (grads psum'd by
        GSPMD when the loss averages over a sharded batch), replay
        episodes sharded, PER bookkeeping replicated — the
        ``DataParallel`` buffer rules, on the learner mesh. Under a
        population: uniform leading-member-axis sharding (params and
        ring alike — whole members per device)."""
        if self.population:
            member = self._sh(self.learner_mesh, self.axis)
            return jax.tree.map(lambda _: member, ls_like)
        ep = self._sh(self.learner_mesh, self.axis)
        rep = self._sh(self.learner_mesh)
        buffer = ls_like.buffer.replace(
            storage=jax.tree.map(lambda _: ep, ls_like.buffer.storage),
            insert_pos=rep, episodes_in_buffer=rep,
            priorities=rep, max_priority=rep)
        return ls_like.replace(
            learner=jax.tree.map(lambda _: rep, ls_like.learner),
            buffer=buffer, episode=rep)

    def tm_shardings(self, tm_like, mesh: Mesh, leading: int = 0):
        """Placement for a ``TimeMajorEpisodes`` pytree (or the queue's
        slot-stacked form with ``leading=1``): the batch axis shards
        over ``mesh`` — axis ``leading+1`` for the time-major scan
        leaves, axis ``leading`` for the bootstrap ``last_*`` leaves.
        Under a population the MEMBER axis (position ``leading``:
        emissions are ``(P, T, B, ...)``, queue slots ``(S, P, T, B,
        ...)``) shards instead, uniformly for every leaf."""
        if self.population:
            member = self._sh(mesh, *((None,) * leading), self.axis)
            return jax.tree.map(lambda _: member, tm_like)
        seq = self._sh(mesh, *((None,) * (leading + 1)), self.axis)
        last = self._sh(mesh, *((None,) * leading), self.axis)

        def fill(subtree, s):
            return jax.tree.map(lambda _: s, subtree)

        return TimeMajorEpisodes(
            obs=fill(tm_like.obs, seq),
            state=fill(tm_like.state, seq),
            avail_actions=fill(tm_like.avail_actions, seq),
            actions=fill(tm_like.actions, seq),
            reward=fill(tm_like.reward, seq),
            terminated=fill(tm_like.terminated, seq),
            last_obs=fill(tm_like.last_obs, last),
            last_state=fill(tm_like.last_state, last),
            last_avail=fill(tm_like.last_avail, last))

    def params_sharding(self):
        """Actor-mesh placement for the published acting params:
        replicated solo, member-axis-sharded under a population (the
        published stack is ``(P, ...)`` per leaf)."""
        if self.population:
            return self._sh(self.actor_mesh, self.axis)
        return self._sh(self.actor_mesh)

    # ------------------------------------------------------------ state

    def _state_shapes(self, seed: int):
        if self.population:
            from .. import population as graftpop
            return jax.eval_shape(
                lambda: graftpop.init_population(self.exp,
                                                 self.exp.cfg))[0]
        return jax.eval_shape(lambda: self.exp.init_train_state(seed))

    def split_shapes(self, ts_like) -> Tuple[object, object]:
        """(runner, learner-side) abstract halves of a TrainState."""
        return ts_like.runner, LearnerSideState(
            learner=ts_like.learner, buffer=ts_like.buffer,
            episode=ts_like.episode)

    def init_states(self, seed: int):
        """Fresh (runner, learner-side) states BORN on their meshes —
        two jitted builds with ``out_shardings`` (one per mesh; a single
        program cannot output onto two disjoint device sets), so the
        replay ring's zeros materialize as learner-mesh shards only and
        no full-state single-device transient ever exists (the
        ``DataParallel.init_sharded`` reasoning). Both builds run the
        same deterministic ``init_train_state(seed)``, so the halves are
        consistent. Under a population both builds run
        ``graftpop.init_population`` instead (P explicit solo inits
        stacked — member i bit-identical to a solo init at seed_i; the
        spec half is dead code the jit DCEs)."""
        shapes = self._state_shapes(seed)
        rs_shape, ls_shape = self.split_shapes(shapes)
        if self.population:
            from .. import population as graftpop
            cfg = self.exp.cfg
            rs = jax.jit(
                lambda: graftpop.init_population(self.exp, cfg)[0].runner,
                out_shardings=self.runner_shardings(rs_shape))()
            ls = jax.jit(
                lambda: self.split_shapes(
                    graftpop.init_population(self.exp, cfg)[0])[1],
                out_shardings=self.learner_shardings(ls_shape))()
            return rs, ls
        rs = jax.jit(
            lambda: self.exp.init_train_state(seed).runner,
            out_shardings=self.runner_shardings(rs_shape))()
        ls = jax.jit(
            lambda: self.split_shapes(self.exp.init_train_state(seed))[1],
            out_shardings=self.learner_shardings(ls_shape))()
        return rs, ls

    def place(self, ts) -> Tuple[object, object]:
        """Place an EXISTING TrainState (the resume path) onto the two
        meshes: runner half to the actor set, learner half to the
        learner set (host→device copies; peak = old + new, like
        ``DataParallel.shard``)."""
        rs, ls = self.split_shapes(ts)
        return (jax.device_put(rs, self.runner_shardings(rs)),
                jax.device_put(ls, self.learner_shardings(ls)))

    def join(self, rs, ls):
        """Reassemble the driver's checkpointable TrainState pytree from
        the two halves (device placement is irrelevant to the
        checkpoint writer — it gathers to host per leaf)."""
        from ..run import TrainState
        return TrainState(learner=ls.learner, runner=rs,
                          buffer=ls.buffer, episode=ls.episode)

    def tm_abstract(self):
        """eval_shape of the rollout scan's time-major emission (the
        queue slot payload) — ``(P,)``-stacked per leaf under a
        population (one member's emission, batched by the actor vmap)."""
        shapes = jax.eval_shape(
            lambda: self.exp.init_train_state(self.exp.cfg.seed))
        params = shapes.learner.params["agent"]
        _, tm, _ = jax.eval_shape(
            lambda p, r: self.exp.runner.run_raw(p, r, test_mode=False),
            params, shapes.runner)
        if self.population:
            return jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(
                    (self.population,) + l.shape, l.dtype), tm)
        return tm

    def init_queue(self) -> QueueState:
        """Zero-filled trajectory queue, born on the learner mesh."""
        tm = self.tm_abstract()
        slots_shape = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct((self.queue_slots,) + l.shape,
                                           l.dtype), tm)
        sh = QueueState(slots=self.tm_shardings(slots_shape,
                                                self.learner_mesh,
                                                leading=1))
        return jax.jit(
            lambda: QueueState(slots=jax.tree.map(
                lambda l: jnp.zeros(l.shape, l.dtype), slots_shape)),
            out_shardings=sh)()

    # ------------------------------------------------------------ transfers

    def to_learner(self, tm: TimeMajorEpisodes) -> TimeMajorEpisodes:
        """Async device-to-device copy of a rollout emission from the
        actor mesh to the learner mesh (the queue's ingress hop)."""
        return jax.device_put(tm, self.tm_shardings(tm, self.learner_mesh))

    def publish_params(self, agent_params):
        """Async learner→actor copy of the acting params (replicated on
        the actor mesh) — the ``params.sync`` hop. The caller bounds the
        staleness window host-side (``sebulba.staleness``)."""
        return jax.device_put(
            agent_params,
            jax.tree.map(lambda _: self.params_sharding(), agent_params))

    # ------------------------------------------------------------ programs

    def programs(self):
        """→ (actor_step, queue_put, queue_get, learner_step) jitted.

        * ``actor_step(params, rs, test_mode=False) -> (rs', tm, stats)``
          — ``runner.run_raw`` on the actor mesh (the same single rollout
          definition as the classic/fused paths).
        * ``queue_put(q, slot, tm) -> q'`` (q donated) — one scatter per
          leaf into the slot axis.
        * ``queue_get(ls, q, slot) -> (ls', q)`` (both donated) — gather
          the slot and scatter it straight into the replay ring via
          ``insert_time_major`` (bit-identical to
          ``insert_episode_batch(tm.to_batch())``), advancing the
          episode counter. ``q`` passes through aliased, which threads
          the queue handle linearly through puts and gets — device
          execution order then follows host enqueue order by dataflow.
        * ``learner_step(ls, key, t_env) -> (ls', info)`` (ls donated) —
          the exact ``run.Experiment.jitted_programs._train_iter``
          arithmetic (sample → train → non-finite-guarded priority
          feedback) on the learner-side state.

        Under ``population=P`` the same bodies vmap over the leading
        member axis (per-member key column ``(P, 2)`` into the learner
        step; ``t_env`` stays a shared scalar), mirroring
        ``run.Experiment.population_superstep_program``: P=1 squeezes
        through the UNBATCHED body (bit-parity — a batched rank
        reassociates f32 reduces), and a statically NEUTRAL P=1 spec
        drops the spec seams entirely (``spec=None`` into the body, the
        fusion-sensitivity gotcha).
        """
        exp = self.exp
        runner, buffer, learner, cfg = (exp.runner, exp.buffer, exp.learner,
                                        exp.cfg)
        wsc = jax.lax.with_sharding_constraint
        rs_c = lambda rs: self.runner_shardings(rs)
        ls_c = lambda ls: self.learner_shardings(ls)
        batch_sh = self._sh(self.learner_mesh, self.axis)
        pop, spec = self.population, self.spec
        pc = cfg.population
        neutral = (pop == 1 and not pc.lr and not pc.eps_scale
                   and not pc.per_alpha and not pc.scenario_salt
                   and not pc.pbt.enabled)

        def _roll_one(params, rs, test_mode, s):
            # one member's rollout: the spec's epsilon scale (and
            # scenario salt) thread in exactly like the classic
            # population superstep body; greedy test rollouts take no
            # spec seams (population_rollout_program's shape)
            roll_kw = {}
            if s is not None and not test_mode:
                roll_kw["eps_scale"] = s.eps_scale
                if pc.scenario_salt:
                    roll_kw["member"] = s.member
            rs2, tm, stats = runner.run_raw(params, rs,
                                            test_mode=test_mode, **roll_kw)
            return _strong(rs2), tm, stats

        def _actor_step(params, rs, test_mode):
            if pop == 1:
                r2, tm, stats = _roll_one(
                    _squeeze0(params), _squeeze0(rs), test_mode,
                    None if neutral else _squeeze0(spec))
                rs2, tm, stats = (_expand0(r2), _expand0(tm),
                                  _expand0(stats))
            elif pop:
                rs2, tm, stats = jax.vmap(
                    lambda p, r, s: _roll_one(p, r, test_mode, s))(
                        params, rs, spec)
            else:
                # solo path verbatim (run_raw -> wsc -> _strong op
                # order): the audited actor_step fingerprint is pinned
                rs2, tm, stats = runner.run_raw(params, rs,
                                                test_mode=test_mode)
                rs2 = jax.tree.map(wsc, rs2, rs_c(rs2))
                tm = jax.tree.map(wsc, tm, self.tm_shardings(
                    tm, self.actor_mesh))
                return _strong(rs2), tm, stats
            rs2 = jax.tree.map(wsc, rs2, rs_c(rs2))
            tm = jax.tree.map(wsc, tm, self.tm_shardings(
                tm, self.actor_mesh))
            return rs2, tm, stats

        actor_step = jax.jit(_actor_step, static_argnames="test_mode")

        def _queue_put(q: QueueState, slot, tm) -> QueueState:
            slots = jax.tree.map(
                lambda s, x: jax.lax.dynamic_update_index_in_dim(
                    s, x.astype(s.dtype), slot, 0), q.slots, tm)
            return QueueState(slots=jax.tree.map(
                wsc, slots, self.tm_shardings(slots, self.learner_mesh,
                                              leading=1)))

        queue_put = jax.jit(_queue_put, donate_argnums=(0,))

        def _queue_get(ls: LearnerSideState, q: QueueState, slot):
            tm = jax.tree.map(
                lambda s: jax.lax.dynamic_index_in_dim(s, slot, 0,
                                                       keepdims=False),
                q.slots)
            if pop == 1:
                buf = _expand0(buffer.insert_time_major(
                    _squeeze0(ls.buffer), _squeeze0(tm),
                    alpha=None if neutral
                    else jnp.squeeze(spec.per_alpha, 0)))
            elif pop:
                # per-member PER exponent into the ring writes, like the
                # classic population superstep's insert
                buf = jax.vmap(
                    lambda b, t, a: buffer.insert_time_major(
                        b, t, alpha=a))(ls.buffer, tm, spec.per_alpha)
            else:
                buf = buffer.insert_time_major(ls.buffer, tm)
            ls = ls.replace(buffer=buf,
                            episode=ls.episode + cfg.batch_size_run)
            return _strong(jax.tree.map(wsc, ls, ls_c(ls))), q

        queue_get = jax.jit(_queue_get, donate_argnums=(0, 1))

        def _train_core(ls: LearnerSideState, key: jax.Array,
                        t_env: jnp.ndarray, s):
            # identical arithmetic + key threading to run._train_iter /
            # run._superstep_fn._train — the lockstep bit-parity anchors
            # (solo AND population) depend on it
            k_sample, k_learn = jax.random.split(key)
            batch, idx, weights = buffer.sample(
                ls.buffer, k_sample, cfg.batch_size, t_env)
            if not pop:
                # episode-axis constraint — solo layout only (invalid
                # inside the member vmap; the stacked output takes the
                # member-axis constraint below instead)
                batch = jax.tree.map(lambda x: wsc(x, batch_sh), batch)
            learner_state, info = learner.train(
                ls.learner, batch, weights, t_env, ls.episode, k_learn,
                spec=s)
            buf = buffer.update_priorities(
                ls.buffer, idx, info["td_errors_abs"] + 1e-6,      # Q9
                valid=info["all_finite"],
                alpha=None if s is None else s.per_alpha)
            # graftsight PER health (run._train_iter's in-graph read,
            # re-homed with the rest of this program — the one shared
            # definition keeps the emitted pytrees in sync)
            from ..obs import sight as graftsight
            info = graftsight.maybe_buffer_info(cfg, info, buf)
            return ls.replace(learner=learner_state, buffer=buf), info

        def _learner_step(ls: LearnerSideState, key: jax.Array,
                          t_env: jnp.ndarray):
            if pop == 1:
                l2, info = _train_core(
                    _squeeze0(ls), jnp.squeeze(key, 0), t_env,
                    None if neutral else _squeeze0(spec))
                ls2, info = _expand0(l2), _expand0(info)
            elif pop:
                # per-member (2,) key columns; t_env stays the shared
                # scalar (counters evolve identically across members)
                ls2, info = jax.vmap(
                    lambda l, k, s: _train_core(l, k, t_env, s))(
                        ls, key, spec)
            else:
                ls2, info = _train_core(ls, key, t_env, None)
            return _strong(jax.tree.map(wsc, ls2, ls_c(ls2))), info

        learner_step = jax.jit(_learner_step, donate_argnums=(0,))
        return actor_step, queue_put, queue_get, learner_step


def make_sebulba(exp) -> Sebulba:
    """Build the Sebulba machinery from ``exp.cfg.sebulba`` (the driver
    entry): partition the visible devices into the configured disjoint
    sets and size the queue; a configured population rides in with its
    concrete spec (sanity_check already restricted the combo to
    lockstep with PBT off, so the spec is static)."""
    from .mesh import partition_devices
    sb = exp.cfg.sebulba
    actor, learner = partition_devices(sb.actor_devices, sb.learner_devices)
    pop = int(exp.cfg.population.size)
    spec = None
    if pop:
        from .. import population as graftpop
        spec = graftpop.build_spec(exp.cfg)
    return Sebulba.build(exp, actor, learner, sb.queue_slots,
                         population=pop, spec=spec)


#: the fixed audit split (2 actor + 2 learner devices) the registered
#: ``actor_step``/``learner_step`` programs are lowered under — like
#: ``mesh.AUDIT_MESH_DEVICES``, fixed so the checked-in fingerprints
#: don't vary with the auditing host's device count
AUDIT_SPLIT = (2, 2)


def register_audit_programs(ctx):
    """graftprog registry hook: the re-homed Sebulba hot programs under
    the fixed 2+2-device split. ``actor_step`` is the rollout re-homed
    onto the actor mesh; ``learner_step`` the sample→train→priority
    program re-homed onto the learner mesh. Lowered-level only (like
    ``dp_superstep`` — the SPMD compile is not worth the gate time).
    Skipped, never failed, on hosts exposing fewer devices."""
    from ..analysis.registry import AuditProgram
    n_actor, n_learner = AUDIT_SPLIT
    need = n_actor + n_learner
    if len(jax.devices()) < need:
        skip = AuditProgram.skipped(
            f"needs >= {need} devices (hint: XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need})")
        return {"actor_step": skip, "learner_step": skip,
                "pop_learner_step": skip}
    from .mesh import partition_devices
    actor, learner = partition_devices(n_actor, n_learner)
    seb = Sebulba.build(ctx.exp, actor, learner, queue_slots=2)
    actor_step, _, _, learner_step = seb.programs()
    rs_shape, ls_shape = seb.split_shapes(ctx.ts_shape)

    def annotate(shapes, shardings):
        return jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            shapes, shardings)

    agent_shape = ctx.ts_shape.learner.params["agent"]
    params = annotate(
        agent_shape,
        jax.tree.map(lambda _: seb.params_sharding(), agent_shape))
    rs = annotate(rs_shape, seb.runner_shardings(rs_shape))
    ls = annotate(ls_shape, seb.learner_shardings(ls_shape))
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    t_env = jnp.asarray(0)          # weak-typed, like the driver's
    # population x sebulba lockstep twin (graftlattice): the vmapped
    # sample->train->priority step behind the queue — its own Sebulba
    # at the population audit scale (P=2 members over the 2-device
    # learner mesh, lockstep queue), so the solo actor/learner
    # baselines above stay byte-identical
    from ..analysis.registry import population_audit_context
    from .. import population as graftpop
    pctx = population_audit_context()
    p = int(pctx.cfg.population.size)
    pseb = Sebulba.build(pctx.exp, actor, learner, queue_slots=1,
                         population=p,
                         spec=graftpop.build_spec(pctx.cfg))
    _, _, _, pop_learner_step = pseb.programs()
    # the population context's ts_shape is the stacked (ts, spec) pair
    pts_shape, _pspec_shape = pctx.ts_shape
    _, pls_shape = pseb.split_shapes(pts_shape)
    pls = annotate(pls_shape, pseb.learner_shardings(pls_shape))
    pkeys = jax.ShapeDtypeStruct((p,) + key.shape, key.dtype)
    return {
        "actor_step": AuditProgram(
            actor_step, (params, rs), kwargs=dict(test_mode=False),
            description=f"sebulba rollout re-homed onto a {n_actor}-device "
                        f"actor mesh (parallel/sebulba.py)"),
        "learner_step": AuditProgram(
            learner_step, (ls, key, t_env), donate_argnums=(0,),
            description=f"sebulba sample->train->priority step re-homed "
                        f"onto a {n_learner}-device learner mesh"),
        "pop_learner_step": AuditProgram(
            pop_learner_step, (pls, pkeys, t_env), donate_argnums=(0,),
            description=f"population x sebulba lockstep learner step: "
                        f"P={p} members vmapped behind the trajectory "
                        f"queue, member axis sharded over the "
                        f"{n_learner}-device learner mesh "
                        f"(graftlattice)"),
    }


def register_transfer_audits(ctx):
    """graftshard registry hook (``analysis.registry.
    collect_transfer_audits``): the ``params.sync`` publish as a static
    src→dst sharding pair. ``publish_params`` is a cross-mesh
    ``device_put`` — it never lowers to HLO, so the comms audit checks
    the pair directly: agent params replicated on the learner mesh
    (what ``learner_step`` outputs) against ``params_sharding()`` on
    the actor mesh (what the publish requests). Every destination
    shard is a full replica that exists verbatim on each learner
    device, so the audit classifies the hop as a pure d2d copy — the
    baseline entry in programs.json pins that, and a future dp×mp
    learner mesh (ROADMAP item 3) that turns the publish into a
    gather/reshard flips GP404 here before it ships."""
    from ..analysis.registry import TransferAudit
    n_actor, n_learner = AUDIT_SPLIT
    need = n_actor + n_learner
    if len(jax.devices()) < need:
        return {"params_sync": TransferAudit.skipped(
            f"needs >= {need} devices (hint: XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need})")}
    from .mesh import partition_devices
    actor, learner = partition_devices(n_actor, n_learner)
    seb = Sebulba.build(ctx.exp, actor, learner, queue_slots=2)
    agent_shape = ctx.ts_shape.learner.params["agent"]
    src_sh = seb._sh(seb.learner_mesh)      # replicated, learner mesh
    src = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=src_sh),
        agent_shape)
    dst = jax.tree.map(lambda _: seb.params_sharding(), agent_shape)
    return {"params_sync": TransferAudit(
        src=src, dst_shardings=dst,
        description=f"staleness-bounded learner→actor acting-params "
                    f"publish (``Sebulba.publish_params``) under the "
                    f"fixed {n_actor}+{n_learner} audit split — pinned "
                    f"as a pure device-to-device copy")}
