"""Multi-host initialization — the DCN leg of the communication backend.

The reference's entire "distributed backend" is a per-host subprocess farm
over ``multiprocessing.Pipe`` (``/root/reference/parallel_runner.py:21-32,
234-273``, SURVEY.md §5.8); it has no cross-host story at all. Here the
cross-chip path is XLA collectives over ICI (``parallel/mesh.py``), and this
module supplies the cross-HOST leg: one ``jax.distributed.initialize`` call
makes ``jax.devices()`` span every host, after which ``make_mesh`` lays the
data axis across hosts and NOTHING else changes — GSPMD routes collectives
ICI-first, DCN only across host boundaries.

Environment contract (standard JAX multi-process convention): the
coordinator address and process topology come either from explicit arguments
or from the scheduler environment (``JAX_COORDINATOR_ADDRESS``,
``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``). On a Cloud TPU pod, where
``jax.distributed.initialize()`` resolves the topology from pod metadata
without any of those variables, set ``T2OMCA_MULTIHOST=1`` to opt in — an
unconditional auto-detect would be wrong for the common single-host case.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def maybe_initialize_distributed(
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None) -> bool:
    """Initialize the multi-host runtime when a topology is configured.

    Returns True when ``jax.distributed.initialize`` ran (or had already
    run), False when no multi-host topology is configured — single-host
    runs are unaffected. Idempotent: a second call is a no-op.
    """
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "0") or 0)
    pid = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "-1") or -1)

    pod_auto = os.environ.get("T2OMCA_MULTIHOST") == "1"
    if not addr and nproc <= 1 and not pod_auto:
        return False
    kwargs = {}
    if addr:
        kwargs["coordinator_address"] = addr
    if nproc > 0:
        kwargs["num_processes"] = nproc
    if pid >= 0:
        kwargs["process_id"] = pid
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # idempotency via the runtime's own double-init error (there is no
        # public already-initialized predicate to query)
        if "already" in str(e).lower():
            return True
        raise
    return True
