"""Multi-host initialization — the DCN leg of the communication backend.

The reference's entire "distributed backend" is a per-host subprocess farm
over ``multiprocessing.Pipe`` (``/root/reference/parallel_runner.py:21-32,
234-273``, SURVEY.md §5.8); it has no cross-host story at all. Here the
cross-chip path is XLA collectives over ICI (``parallel/mesh.py``), and this
module supplies the cross-HOST leg: one ``jax.distributed.initialize`` call
makes ``jax.devices()`` span every host, after which ``make_mesh`` lays the
data axis across hosts and NOTHING else changes — GSPMD routes collectives
ICI-first, DCN only across host boundaries.

Environment contract (standard JAX multi-process convention): the
coordinator address and process topology come either from explicit arguments
or from the scheduler environment (``JAX_COORDINATOR_ADDRESS``,
``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``). On a Cloud TPU pod, where
``jax.distributed.initialize()`` resolves the topology from pod metadata
without any of those variables, set ``T2OMCA_MULTIHOST=1`` to opt in — an
unconditional auto-detect would be wrong for the common single-host case.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

from ..utils import resilience
from ..utils.watchdog import retry_call


def maybe_initialize_distributed(
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
        retries: Optional[int] = None) -> bool:
    """Initialize the multi-host runtime when a topology is configured.

    Returns True when ``jax.distributed.initialize`` ran (or had already
    run), False when no multi-host topology is configured — single-host
    runs are unaffected. Idempotent: a second call is a no-op.

    The init is a rendezvous: every process races to the coordinator, and
    a transient loss (coordinator pod not yet scheduled, gloo transport
    handshake crashing — the ``EnforceNotMet`` flake CHANGES.md records at
    ~50% on oversubscribed CPU) used to kill the whole job at step zero.
    Transient-classified failures now retry with exponential backoff
    (``utils.watchdog.retry_call``). ``retries`` — from the argument or
    ``T2OMCA_INIT_RETRIES``, default 2 — counts retries BEYOND the first
    attempt (total attempts = 1 + retries), matching the
    ``resilience.dispatch_retries`` convention everywhere else; a
    non-numeric env value is ignored with a warning. Deterministic
    errors (bad topology arguments) still fail on the first attempt. The
    ``backend.init`` fault-injection point fires inside each attempt
    (docs/RESILIENCE.md §4).
    """
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "0") or 0)
    pid = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "-1") or -1)

    pod_auto = os.environ.get("T2OMCA_MULTIHOST") == "1"
    if not addr and nproc <= 1 and not pod_auto:
        return False
    kwargs = {}
    if addr:
        kwargs["coordinator_address"] = addr
    if nproc > 0:
        kwargs["num_processes"] = nproc
    if pid >= 0:
        kwargs["process_id"] = pid
    if retries is None:
        raw = os.environ.get("T2OMCA_INIT_RETRIES", "")
        try:
            retries = int(raw) if raw else 2
        except ValueError:
            logging.getLogger("t2omca").warning(
                f"ignoring non-numeric T2OMCA_INIT_RETRIES={raw!r} "
                f"(using the default of 2 retries)")
            retries = 2
    # retries counts attempts BEYOND the first (resilience.dispatch_retries
    # convention): retries=2 -> 3 total attempts
    attempts = 1 + max(retries, 0)
    attempt_box = [0]

    def _reset_partial_init() -> None:
        # jax 0.4.37 assigns global_state.service/.client BEFORE
        # client.connect() (jax/_src/distributed.py), so a failed
        # rendezvous leaves the runtime half-initialized and a bare
        # retry dies on the double-init RuntimeError instead of
        # re-attempting. Best-effort teardown so the next attempt
        # starts from a clean state; never let cleanup mask the
        # original (classifiable) error.
        try:
            jax.distributed.shutdown()
        except Exception:       # noqa: BLE001 — half-connected client
            try:
                from jax._src import distributed as _dist
                st = _dist.global_state
                st.client = None
                if st.service is not None:
                    try:
                        st.service.shutdown()
                    except Exception:   # noqa: BLE001
                        pass
                    st.service = None
            except Exception:   # noqa: BLE001 — jax internals moved
                pass

    def _init_once() -> bool:
        attempt_box[0] += 1
        resilience.fire("backend.init", attempt=attempt_box[0])
        try:
            jax.distributed.initialize(**kwargs)
        except Exception as e:
            # idempotency via the runtime's own double-init error (there
            # is no public already-initialized predicate to query; jax
            # 0.4.37 phrases it "should only be called once") — but only
            # on the FIRST attempt, where it can only mean a previous
            # successful call. On a retry the same message means THIS
            # call's failed attempt left the runtime half-initialized
            # and _reset_partial_init could not clean it up; reporting
            # success would hand back a never-connected runtime that
            # wedges at the first collective.
            msg = str(e).lower()
            if ("already" in msg or "only be called once" in msg) \
                    and attempt_box[0] == 1:
                return True
            _reset_partial_init()
            raise
        return True

    return retry_call(_init_once, attempts=attempts,
                      label="jax.distributed.initialize")
