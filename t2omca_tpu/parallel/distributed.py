"""Multi-host initialization — the DCN leg of the communication backend.

The reference's entire "distributed backend" is a per-host subprocess farm
over ``multiprocessing.Pipe`` (``/root/reference/parallel_runner.py:21-32,
234-273``, SURVEY.md §5.8); it has no cross-host story at all. Here the
cross-chip path is XLA collectives over ICI (``parallel/mesh.py``), and this
module supplies the cross-HOST leg: one ``jax.distributed.initialize`` call
makes ``jax.devices()`` span every host, after which ``make_mesh`` lays the
data axis across hosts and NOTHING else changes — GSPMD routes collectives
ICI-first, DCN only across host boundaries.

Environment contract (standard JAX multi-process convention): the
coordinator address and process topology come either from explicit arguments
or from the scheduler environment (``JAX_COORDINATOR_ADDRESS``,
``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``). On a Cloud TPU pod, where
``jax.distributed.initialize()`` resolves the topology from pod metadata
without any of those variables, set ``T2OMCA_MULTIHOST=1`` to opt in — an
unconditional auto-detect would be wrong for the common single-host case.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional, Tuple

import jax

from ..utils import resilience
from ..utils.watchdog import retry_call

logger = logging.getLogger(__name__)

#: coordinator key-value namespace for the coordinated-preemption
#: protocol (docs/RESILIENCE.md §6)
_KV_PREFIX = "t2omca/preempt"


def maybe_initialize_distributed(
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
        retries: Optional[int] = None) -> bool:
    """Initialize the multi-host runtime when a topology is configured.

    Returns True when ``jax.distributed.initialize`` ran (or had already
    run), False when no multi-host topology is configured — single-host
    runs are unaffected. Idempotent: a second call is a no-op.

    The init is a rendezvous: every process races to the coordinator, and
    a transient loss (coordinator pod not yet scheduled, gloo transport
    handshake crashing — the ``EnforceNotMet`` flake CHANGES.md records at
    ~50% on oversubscribed CPU) used to kill the whole job at step zero.
    Transient-classified failures now retry with exponential backoff
    (``utils.watchdog.retry_call``). ``retries`` — from the argument or
    ``T2OMCA_INIT_RETRIES``, default 2 — counts retries BEYOND the first
    attempt (total attempts = 1 + retries), matching the
    ``resilience.dispatch_retries`` convention everywhere else; a
    non-numeric env value is ignored with a warning. Deterministic
    errors (bad topology arguments) still fail on the first attempt. The
    ``backend.init`` fault-injection point fires inside each attempt
    (docs/RESILIENCE.md §4).
    """
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "0") or 0)
    pid = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "-1") or -1)

    pod_auto = os.environ.get("T2OMCA_MULTIHOST") == "1"
    if not addr and nproc <= 1 and not pod_auto:
        return False
    kwargs = {}
    if addr:
        kwargs["coordinator_address"] = addr
    if nproc > 0:
        kwargs["num_processes"] = nproc
    if pid >= 0:
        kwargs["process_id"] = pid
    if retries is None:
        raw = os.environ.get("T2OMCA_INIT_RETRIES", "")
        try:
            retries = int(raw) if raw else 2
        except ValueError:
            logging.getLogger("t2omca").warning(
                f"ignoring non-numeric T2OMCA_INIT_RETRIES={raw!r} "
                f"(using the default of 2 retries)")
            retries = 2
    # retries counts attempts BEYOND the first (resilience.dispatch_retries
    # convention): retries=2 -> 3 total attempts
    attempts = 1 + max(retries, 0)
    attempt_box = [0]

    def _reset_partial_init() -> None:
        # jax 0.4.37 assigns global_state.service/.client BEFORE
        # client.connect() (jax/_src/distributed.py), so a failed
        # rendezvous leaves the runtime half-initialized and a bare
        # retry dies on the double-init RuntimeError instead of
        # re-attempting. Best-effort teardown so the next attempt
        # starts from a clean state; never let cleanup mask the
        # original (classifiable) error.
        try:
            jax.distributed.shutdown()
        except Exception:       # noqa: BLE001 — half-connected client
            try:
                from jax._src import distributed as _dist
                st = _dist.global_state
                st.client = None
                if st.service is not None:
                    try:
                        st.service.shutdown()
                    except Exception:   # noqa: BLE001
                        pass
                    st.service = None
            except Exception:   # noqa: BLE001 — jax internals moved
                pass

    def _init_once() -> bool:
        attempt_box[0] += 1
        resilience.fire("backend.init", attempt=attempt_box[0])
        try:
            jax.distributed.initialize(**kwargs)
        except Exception as e:
            # idempotency via the runtime's own double-init error (there
            # is no public already-initialized predicate to query; jax
            # 0.4.37 phrases it "should only be called once") — but only
            # on the FIRST attempt, where it can only mean a previous
            # successful call. On a retry the same message means THIS
            # call's failed attempt left the runtime half-initialized
            # and _reset_partial_init could not clean it up; reporting
            # success would hand back a never-connected runtime that
            # wedges at the first collective.
            msg = str(e).lower()
            if ("already" in msg or "only be called once" in msg) \
                    and attempt_box[0] == 1:
                return True
            _reset_partial_init()
            raise
        return True

    return retry_call(_init_once, attempts=attempts,
                      label="jax.distributed.initialize")


# --------------------------------------------------------------------------
# Coordinated multi-host preemption (docs/RESILIENCE.md §6)
# --------------------------------------------------------------------------
#
# A SIGTERM lands on ONE host (the scheduler rarely signals a pod slice
# atomically), but the emergency checkpoint is a collective — every host
# must cut at the SAME t_env or the gathered save interleaves two
# different steps. The protocol runs over the coordinator's key-value
# store (the same service jax.distributed.initialize stood up — no new
# transport):
#
#   1. the signaled host ANNOUNCES (``announce_shutdown``) as soon as its
#      ShutdownGuard trips;
#   2. every host's driver loop polls ``peer_shutdown_requested`` (time-
#      throttled — one cheap KV scan per interval, never per step) and
#      trips its own guard when a peer announced, so the signal
#      propagates without any host-to-host signal delivery;
#   3. once triggered, every host calls ``negotiate_stop_step`` with its
#      current t_env: publish, meet at a BOUNDED barrier, take the max —
#      hosts behind the consensus keep stepping until they reach it, so
#      the collective emergency save runs in lockstep at one t_env.
#
# A dead peer fails the barrier inside ``timeout_s`` and the call
# degrades explicitly — ``ok=False`` tells the driver to skip every
# collective and write a per-host shard save instead
# (``utils.checkpoint.save_checkpoint_shards``), which cannot hang on
# the corpse.


def _kv_client():
    """The coordinator key-value/barrier client, or None when the
    distributed runtime is not initialized (single-host) or jax's
    internals moved. Private-API access is deliberately fenced here so
    every caller degrades instead of crashing."""
    try:
        from jax._src import distributed as _dist
        return _dist.global_state.client
    except Exception:               # noqa: BLE001 — jax internals moved
        return None


def announce_shutdown(t_env: int) -> None:
    """Publish this host's shutdown intent (+ its t_env at signal time)
    to the coordinator KV store — step 1 of the protocol. Best-effort
    and idempotent: a lost announce only costs propagation latency (the
    peer barrier still bounds the exit), never correctness."""
    if jax.process_count() <= 1:
        return
    client = _kv_client()
    if client is None:
        return
    try:
        client.key_value_set(
            f"{_KV_PREFIX}/announce/{jax.process_index()}",
            str(int(t_env)))
    except Exception as e:          # noqa: BLE001 — KV RPC is best-effort
        logger.warning("announce_shutdown: coordinator KV set failed "
                       "(%r) — peers will rely on their own signals", e)


_peer_poll_state = {"last": 0.0, "hit": False}


def peer_shutdown_requested(min_interval_s: float = 1.0) -> bool:
    """True once ANY peer announced a shutdown — step 2, the driver
    loop-top poll. Time-throttled to one KV scan per ``min_interval_s``
    (a KV RPC per train step would dominate small steps); a positive
    result latches, mirroring ShutdownGuard semantics. Single-host runs
    return False without touching the KV store."""
    if _peer_poll_state["hit"]:
        return True
    if jax.process_count() <= 1:
        return False
    now = time.monotonic()
    if now - _peer_poll_state["last"] < min_interval_s:
        return False
    _peer_poll_state["last"] = now
    client = _kv_client()
    if client is None:
        return False
    try:
        entries = client.key_value_dir_get(f"{_KV_PREFIX}/announce/")
    except Exception:               # noqa: BLE001 — empty dir / RPC loss
        return False
    me = str(jax.process_index())
    for item in entries or []:
        key = item[0] if isinstance(item, (tuple, list)) else item
        if str(key).rstrip("/").rsplit("/", 1)[-1] != me:
            _peer_poll_state["hit"] = True
            logger.warning(
                "peer_shutdown_requested: a peer announced preemption "
                "(%s) — tripping the local shutdown guard", key)
            return True
    return False


def negotiate_stop_step(t_env: int,
                        timeout_s: float = 10.0) -> Tuple[int, bool]:
    """Step 3: agree on the SINGLE t_env every host cuts its emergency
    checkpoint at. Returns ``(target, ok)``:

    * ``ok=True``: all hosts met the barrier; ``target`` is the max of
      the published steps — hosts behind it keep stepping until they
      reach it, then run the collective save in lockstep.
    * ``ok=False``: the barrier timed out or the KV store is gone (a
      peer died mid-preemption). ``target`` is the caller's own t_env
      and the driver must DEGRADE: skip every collective and write a
      per-host shard save (``save_checkpoint_shards``) instead.

    Single-host runs return ``(t_env, True)`` immediately. The
    ``preempt.barrier`` resilience hook fires inside the guarded region,
    so chaos tests inject a peer-timeout by raising here
    (docs/RESILIENCE.md §4)."""
    t = int(t_env)
    try:
        # fault-injection point (docs/RESILIENCE.md §4): the bounded
        # peer barrier — raising here simulates a peer dying
        # mid-negotiation and exercises the degraded shard-save path
        resilience.fire("preempt.barrier", t_env=t,
                        processes=jax.process_count())
        if jax.process_count() <= 1:
            return t, True
        client = _kv_client()
        if client is None:
            logger.warning(
                "negotiate_stop_step: multi-host run without a "
                "coordinator KV client — degrading to per-host save")
            return t, False
        pid = jax.process_index()
        client.key_value_set(f"{_KV_PREFIX}/step/{pid}", str(t))
        client.wait_at_barrier("t2omca_preempt_cut",
                               max(int(timeout_s * 1000), 1))
        entries = client.key_value_dir_get(f"{_KV_PREFIX}/step/") or []
        steps = []
        for item in entries:
            val = item[1] if isinstance(item, (tuple, list)) \
                and len(item) > 1 else item
            try:
                steps.append(int(val))
            except (TypeError, ValueError):
                continue
        if len(steps) < jax.process_count():
            logger.warning(
                "negotiate_stop_step: barrier passed but only %d/%d "
                "hosts published a step — degrading to per-host save",
                len(steps), jax.process_count())
            return t, False
        target = max(steps)
        logger.info("negotiate_stop_step: consensus cut at t_env=%d "
                    "(local %d, %d hosts)", target, t, len(steps))
        return target, True
    except Exception as e:          # noqa: BLE001 — timeout/dead peer
        logger.warning(
            "negotiate_stop_step: peer barrier failed (%r) — a peer is "
            "likely dead; degrading to per-host shard save", e)
        return t, False
