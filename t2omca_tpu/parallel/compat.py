"""JAX version compatibility shims for the parallel package.

The installed JAX floor is 0.4.x (the axon image pins 0.4.37), where
``shard_map`` still lives in ``jax.experimental.shard_map`` and the
replication-check kwarg is ``check_rep``; newer JAX promotes it to
``jax.shard_map`` with ``check_vma``. Callers import the one symbol from
here and always write the NEW spelling (``check_vma``) — the shim
translates downward so the codebase never forks on version.
"""

from __future__ import annotations

try:                                    # JAX >= 0.5: public API
    from jax import shard_map as _shard_map

    def shard_map(f, /, *, mesh, in_specs, out_specs, **kw):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)
except ImportError:                     # JAX 0.4.x: experimental API
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, /, *, mesh, in_specs, out_specs, **kw):
        if "check_vma" in kw:           # renamed from check_rep in 0.5
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

__all__ = ["shard_map"]
