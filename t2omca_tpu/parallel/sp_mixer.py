"""Sequence-parallel mixer forward — the consumer of ``ring_attention``.

At the config-5 scale point (256 AGVs × 16 MECs, BASELINE.json) the mixer's
token axis is ``n_entities + n_agents + 3`` = 515 tokens; beyond that —
entity-token models with thousands of entities — the (b, h, T, T) attention
matrix and the token activations outgrow one chip. This module runs
``TransformerMixer``'s exact forward math (``models/mixer.py``, quirks
Q1/Q2/Q11/Q12 included) with the TOKEN axis sharded across a mesh axis:

* embedding / LayerNorm / FFN are token-local → run unchanged per shard;
* attention runs as ``ring_attention`` (K/V rotate over ICI via
  ``lax.ppermute``; the full T×T score matrix never exists on any device);
* layer-0 key pinning (``transformer.py:126,140`` threading) is preserved —
  every depth attends against the sharded layer-0 token blocks;
* the hypernet readout (Q11: weights read off the LAST ``3`` positional
  output tokens plus one per agent) happens after the (small) output gather.

The functions read the SAME flax param tree the dense module owns — no
separate parameters, no checkpoint divergence (same pattern as
``ops/query_slice``). Dense-equivalence is asserted on the virtual 8-device
mesh in ``tests/test_ring_attention.py``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.mixer import TransformerMixer
from .compat import shard_map
from .ring_attention import ring_attention

LN_EPS = 1e-6   # flax nn.LayerNorm default, matches models/transformer.py


def _ln(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=-1, keepdims=True)
    var = jnp.maximum((x32 * x32).mean(axis=-1, keepdims=True)
                      - mean * mean, 0.0)
    y = (x32 - mean) * jax.lax.rsqrt(var + LN_EPS)
    return (y * scale + bias).astype(x.dtype)


def _sp_transformer(tf_params, tokens, valid, *, heads: int, depth: int,
                    head_dim: int, axis: str) -> jnp.ndarray:
    """Runs INSIDE shard_map. tokens ``(B, T_local, E)`` — the local block
    of the token axis; ``valid (T_local,)`` marks real (non-pad) tokens.
    Mirrors ``models/transformer.py`` with keys pinned to layer-0 tokens."""
    b, t_loc, e = tokens.shape
    dt = tokens.dtype                 # compute dtype (mixer.dtype, cast by caller)
    k0 = tokens                       # layer-0 key pinning
    kv_mask = jnp.broadcast_to(valid[None, None, :], (b, heads, t_loc))
    x = tokens
    scale = head_dim ** -0.25         # Q1: applied to queries AND keys
    w = lambda p_: p_.astype(dt)

    for i in range(depth):
        bp = tf_params[f"block_{i}"]
        at = bp["attention"]
        split = lambda z, wk: (z @ w(wk)).reshape(b, t_loc, heads, head_dim
                                                  ).transpose(0, 2, 1, 3)
        q = split(x, at["toqueries"]["kernel"]) * scale
        k = split(k0, at["tokeys"]["kernel"]) * scale
        v = split(k0, at["tovalues"]["kernel"])

        ctx = ring_attention(q, k, v, axis, kv_mask)   # (B, H, T_loc, D)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t_loc, heads * head_dim)
        attended = (ctx @ w(at["unifyheads"]["kernel"])
                    + w(at["unifyheads"]["bias"]))

        # Q2: post-LN residuals; FFN is token-local
        x1 = _ln(attended + x, bp["norm1"]["scale"], bp["norm1"]["bias"])
        ff = jnp.maximum(x1 @ w(bp["ff1"]["kernel"]) + w(bp["ff1"]["bias"]),
                         0.0)
        ff = ff @ w(bp["ff2"]["kernel"]) + w(bp["ff2"]["bias"])
        x = _ln(ff + x1, bp["norm2"]["scale"], bp["norm2"]["bias"])
    return x


def mixer_apply_sp(mixer: TransformerMixer, variables, qvals: jnp.ndarray,
                   hidden_states: jnp.ndarray, hyper_weights: jnp.ndarray,
                   states: jnp.ndarray, obs: jnp.ndarray, mesh: Mesh,
                   axis: str = "sp") -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in for ``mixer.apply`` (deterministic, dropout=0) with the token
    axis sharded over ``mesh[axis]``. Same signature tail and returns:
    ``(q_tot (b,1,1), hyper_tokens (b,3,emb))``."""
    p = variables["params"]
    b = qvals.shape[0]
    n_sp = mesh.shape[axis]

    # ---- token construction, exactly models/mixer.py:71-81 ----
    if mixer.state_entity_mode:
        inputs = states.reshape(b, mixer.n_entities, mixer.feat_dim)
    else:   # Q12: all agents' obs entities
        inputs = obs.reshape(b, mixer.n_agents * mixer.n_entities,
                             mixer.feat_dim)
    # compute dtype mirrors the dense module (flax Dense/Transformer with
    # dtype=mixer.dtype): bf16 perf mode keeps token activations and the
    # ring's K/V traffic in bf16; LN statistics and the hypernet readout
    # stay f32 either way
    dt = mixer.dtype
    fe = p["feat_embedding"]
    embs = inputs.astype(dt) @ fe["kernel"].astype(dt) + fe["bias"].astype(dt)
    tokens = jnp.concatenate(
        [embs, hidden_states.astype(dt), hyper_weights.astype(dt)], axis=1)
    t = tokens.shape[1]

    # pad the token axis to a multiple of the axis size; padded keys are
    # excluded from every softmax via the ring kv mask
    tp = -(-t // n_sp) * n_sp
    if tp != t:
        tokens = jnp.pad(tokens, [(0, 0), (0, tp - t), (0, 0)])
    valid = jnp.arange(tp) < t

    head_dim = mixer.emb // mixer.heads if mixer.standard_heads else mixer.emb
    inner = functools.partial(_sp_transformer, heads=mixer.heads,
                              depth=mixer.depth, head_dim=head_dim,
                              axis=axis)
    out = shard_map(
        inner, mesh=mesh,
        in_specs=(P(), P(None, axis, None), P(axis)),
        out_specs=P(None, axis, None),
        check_vma=False,
    )(p["transformer"], tokens, valid)
    out = out[:, :t, :].astype(jnp.float32)

    # ---- hypernet readout, exactly models/mixer.py:91-104 (Q11) ----
    a, e = mixer.n_agents, mixer.emb
    w1 = mixer.pos_func(out[:, -3 - a:-3, :])
    b1 = out[:, -3, :].reshape(b, 1, e)
    w2 = mixer.pos_func(out[:, -2, :].reshape(b, e, 1))
    hb = p["hyper_b2"]
    b2 = jnp.maximum(out[:, -1, :] @ hb["kernel"] + hb["bias"],
                     0.0).reshape(b, 1, 1)
    hidden = jax.nn.elu(jnp.matmul(qvals, w1) + b1)
    y = jnp.matmul(hidden, w2) + b2
    if "out_gate" in p:        # zero_init_gate configs (models/mixer.py)
        y = y * p["out_gate"]
    return y, out[:, -3:, :]
