"""Data-parallel scaling over a device mesh (SURVEY.md §2.2, §7.2(6)).

The reference has no multi-device story at all — its only "distributed"
tier is the subprocess env farm over Pipes (``parallel_runner.py:21-32``) and
a single CUDA device for the learner (``per_run.py:26``). The TPU-native
replacement (SURVEY.md §2.2 table): a ``jax.sharding.Mesh`` with a ``data``
axis; env lanes and replay episodes are sharded along it, model/optimizer
state is replicated, and XLA inserts the gradient ``psum`` over ICI when the
jitted train step consumes sharded batches — no hand-written collectives, no
NCCL/MPI equivalent to port.

Axis layout (why DP only): agent/entity token axes are tiny (≤ a few hundred
entries even at 256 AGVs, SURVEY.md §5.7) and models are ≤ a few M params, so
TP/PP/SP would ship more bytes over ICI than they save in FLOPs; the scaling
dimension of this workload is *environments*. The mesh helpers still accept
extra axes so a ``model`` axis can be added without restructuring
(extension point noted in SURVEY.md §2.2).

Multi-host: the same code scales to DCN via ``jax.distributed.initialize``
— ``jax.devices()`` then spans hosts and ``make_mesh`` lays the data axis
across them; nothing else changes (XLA routes collectives ICI-first).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Sequence[str] = ("data",)) -> Mesh:
    """1-D (default) mesh over the first ``n_devices`` devices."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                f"(hint: XLA_FLAGS=--xla_force_host_platform_device_count=N)")
        devs = devs[:n_devices]
    shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    return Mesh(np.asarray(devs).reshape(shape), axis_names)


def partition_devices(n_actor: int, n_learner: int,
                      devices: Optional[Sequence] = None
                      ) -> tuple:
    """Disjoint (actor, learner) device sets for the Sebulba decoupled
    loop (``parallel/sebulba.py``): the first ``n_actor`` visible devices
    act, the next ``n_learner`` train. Disjointness is the point — the
    two meshes never contend for a chip, so rollout and training overlap
    instead of serializing (Podracer's Sebulba split, PAPERS.md)."""
    devs = list(devices) if devices is not None else jax.devices()
    need = n_actor + n_learner
    if n_actor < 1 or n_learner < 1:
        raise ValueError(f"actor/learner device counts must be >= 1, got "
                         f"({n_actor}, {n_learner})")
    if len(devs) < need:
        raise ValueError(
            f"sebulba needs {n_actor}+{n_learner}={need} devices, have "
            f"{len(devs)} (hint: XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need})")
    return tuple(devs[:n_actor]), tuple(devs[n_actor:need])


def population_shardings(mesh: Mesh, tree_like, axis: str = "data"):
    """NamedSharding pytree for population-over-dp (graftlattice): every
    leaf of the (P,)-stacked population state — TrainState halves AND the
    ``PopulationSpec`` — sharded on its LEADING member axis over the mesh.

    This is deliberately simpler than ``DataParallel.state_shardings``:
    the population superstep vmaps over members and members never
    communicate, so the mesh cuts between whole members (P must divide
    the axis size — ``sanity_check`` enforces it) and no leaf needs a
    per-field placement rule. Replicated-vs-sharded parity: no
    cross-member collective is ever inserted, so control/integer state
    is bit-equal; float leaves sit at f32 ULP scale, NOT bitwise —
    partitioning retiles the batched reduces (batch-P arrays on one
    device vs batch-P/n shards), measured ~1e-7 absolute / up to
    2.4e-5 rel on small adam moments after a train step
    (tests/test_lattice.py)."""
    member = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda _: member, tree_like)


@dataclasses.dataclass(frozen=True)
class DataParallel:
    """Sharded program wrapper for an ``Experiment`` (``run.Experiment``).

    Usage::

        dp = DataParallel(exp, make_mesh(8))
        ts = dp.init_sharded(seed)          # fresh state, born sharded
        rollout, insert, train_iter = dp.jitted_programs()

    (``dp.shard(restored_ts)`` places an EXISTING state — the resume
    path; for fresh states prefer ``init_sharded``, which never holds a
    single-device copy of the replay ring.)

    The jitted programs are the experiment's own pure functions; sharding
    comes entirely from the placement of their inputs (GSPMD propagates it),
    so the single-chip and multi-chip paths are the same code. Requirements:
    ``batch_size_run`` and ``batch_size`` divisible by the data-axis size.
    """

    exp: object                  # run.Experiment (duck-typed to avoid cycle)
    mesh: Mesh
    axis: str = "data"

    def __post_init__(self):
        from ..config import check_dp_divisibility
        check_dp_divisibility(self.exp.cfg, self.mesh.shape[self.axis],
                              axis_label=f"the '{self.axis}' axis size")

    # ------------------------------------------------------------------ state

    def state_shardings(self, ts_like):
        """NamedSharding pytree for a TrainState (or its
        ``jax.eval_shape`` struct): learner replicated, env lanes and
        replay episodes sharded over the data axis. Single source of the
        placement rules — consumed by ``shard`` (device_put of an
        existing state) and ``init_sharded`` (jit out_shardings, so big
        states are BORN sharded)."""
        lane = NamedSharding(self.mesh, P(self.axis))
        rep = NamedSharding(self.mesh, P())

        def fill(subtree, s):
            return jax.tree.map(lambda _: s, subtree)

        runner = ts_like.runner.replace(
            env_states=fill(ts_like.runner.env_states, lane),
            key=rep, t_env=rep,
            # reward-scale state is per-lane except the scalar Welford count
            rscale=jax.tree.map(
                lambda x: lane if getattr(x, "ndim", 0) else rep,
                ts_like.runner.rscale),
            # graftworld scenario instances: every EnvParams leaf is
            # batched (B, ...) — sharded with its env lane
            env_params=fill(ts_like.runner.env_params, lane))
        buffer = ts_like.buffer.replace(
            storage=fill(ts_like.buffer.storage, lane),
            insert_pos=rep, episodes_in_buffer=rep,
            priorities=rep, max_priority=rep)
        return ts_like.replace(
            learner=fill(ts_like.learner, rep),
            runner=runner, buffer=buffer, episode=rep)

    def shard(self, ts):
        """Place an existing TrainState onto the mesh (host→device copy;
        peak = old + new. For states whose replay ring is a large share
        of host/device memory prefer ``init_sharded``)."""
        return jax.device_put(ts, self.state_shardings(ts))

    def init_sharded(self, seed: int):
        """Build the initial TrainState DIRECTLY under the mesh sharding:
        jit with out_shardings means XLA materializes each leaf (notably
        the replay ring's zeros) as per-device shards only — no
        full-state single-device transient, which at config-5 ring sizes
        (~59 GiB bf16) is the difference between fitting and OOM at
        startup. Equivalent to ``shard(exp.init_train_state(seed))`` up
        to jit-fusion float reassociation in the env-reset math (measured
        rel ~1e-8 on 3 env-state leaves; params bit-identical)."""
        shapes = jax.eval_shape(lambda: self.exp.init_train_state(seed))
        return jax.jit(
            lambda: self.exp.init_train_state(seed),
            out_shardings=self.state_shardings(shapes))()

    # ------------------------------------------------------------------ programs

    def jitted_programs(self, donate: bool = False):
        """The experiment's own three programs with
        ``with_sharding_constraint`` injected on every chained value:
        episode batches (episode axis distributed end-to-end: rollout →
        insert → sample → train; grads are psum'd by GSPMD since params
        are replicated and the loss averages over a sharded batch) AND
        the runner/replay/learner states the driver loop feeds back in.
        Output constraints pin each program's outputs to the exact
        placement ``shard`` gives its inputs — otherwise GSPMD may pick
        different output shardings and every later loop iteration would
        compile and run a second, differently-sharded executable.

        ``donate`` has the same contract as
        ``Experiment.jitted_programs(donate=...)``: in-place replay ring and
        train state for drivers that never reuse the pre-call value."""
        return self.exp.jitted_programs(donate=donate,
                                        **self._constraint_hooks())

    def superstep_program(self, k: int, donate: bool = False):
        """The fused K-iteration superstep
        (``run.Experiment.superstep_program``) under the mesh: the same
        constraint hooks pin every value the scan carries across
        sub-iterations — env lanes / replay episodes stay sharded on the
        data axis, learner state replicated (grads psum'd by GSPMD) — so
        one executable serves every dispatch, exactly like
        ``jitted_programs``."""
        return self.exp.superstep_program(k, donate=donate,
                                          **self._constraint_hooks())

    def audit_avals(self, ts_like):
        """The TrainState avals the DRIVER hands this wrapper's
        programs: each eval_shape leaf annotated with its canonical
        ``state_shardings`` placement, so the auditor lowers the same
        SPMD program ``run_sequential`` dispatches (unsharded avals
        would lower a different — single-device — executable and the
        recorded fingerprint/budgets would be fiction)."""
        return jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            ts_like, self.state_shardings(ts_like))

    def _constraint_hooks(self):
        """The shared ``constrain_*`` kwargs: one source for the canonical
        placement of every value the driver loop (or the superstep scan)
        chains back in."""
        data = NamedSharding(self.mesh, P(self.axis))
        rep = NamedSharding(self.mesh, P())
        wsc = jax.lax.with_sharding_constraint

        def constrain_runner(rs):
            return rs.replace(
                env_states=jax.tree.map(lambda x: wsc(x, data),
                                        rs.env_states),
                key=wsc(rs.key, rep),
                t_env=wsc(rs.t_env, rep),
                rscale=jax.tree.map(
                    lambda x: wsc(x, data if x.ndim else rep), rs.rscale),
                env_params=jax.tree.map(lambda x: wsc(x, data),
                                        rs.env_params))

        def constrain_buffer(buf):
            return buf.replace(
                storage=jax.tree.map(lambda x: wsc(x, data), buf.storage),
                insert_pos=wsc(buf.insert_pos, rep),
                episodes_in_buffer=wsc(buf.episodes_in_buffer, rep),
                priorities=wsc(buf.priorities, rep),
                max_priority=wsc(buf.max_priority, rep))

        return dict(
            constrain_batch=lambda b: wsc(b, data),
            constrain_runner=constrain_runner,
            constrain_buffer=constrain_buffer,
            constrain_learner=lambda l: jax.tree.map(
                lambda x: wsc(x, rep), l))


#: data-axis width the audit builds with — the smallest real mesh, so
#: the SPMD program structure (partitioned scatter/psum) is audited
#: without depending on how many devices the auditing host happens to
#: expose beyond two
AUDIT_MESH_DEVICES = 2

# --------------------------------------------------------------- dp × mp
#
# ROADMAP item 3: T5X-style 2-D (dp, mp) partitioning. The PARTITIONER
# is not built yet — what lives here is its declared-intent artifact
# (the logical axis rules, SNIPPETS.md [2]/[3] pattern) plus the fixed
# synthetic 2×2 audit mesh the comms gate (analysis/graftshard.py,
# GP405) dry-runs a transformer block under, so sharding regressions
# against the declared rules fail statically before the first real
# dp×mp line is written.

#: logical axis name -> mesh axis (None = replicated). First match
#: wins, T5X `logical_axis_rules` semantics. The model axes that grow
#: with entity-transformer width ("joined_kv": the fused heads*head_dim
#: projection output of the full-emb head geometry Q1; "mlp": the
#: ff_hidden_mult*emb hidden) shard over ``model``; "embed" stays
#: replicated (it is every block's residual/LayerNorm axis — splitting
#: it would put a collective inside every residual add); "batch"
#: follows the data axis like every env-lane tensor.
LOGICAL_AXIS_RULES = (
    ("batch", "data"),
    ("heads", "model"),
    ("joined_kv", "model"),
    ("mlp", "model"),
    ("embed", None),
    ("tokens", None),
    ("kv", None),
)

#: the fixed synthetic (dp, mp) audit mesh shape — 2×2 is the smallest
#: mesh where BOTH axes are real, so the lowered program carries the
#: genuine dp psum AND mp contraction collectives
AUDIT_DPMP_MESH = (2, 2)


def make_dpmp_mesh(shape: Sequence[int] = AUDIT_DPMP_MESH) -> Mesh:
    """2-D ("data", "model") mesh over the first prod(shape) devices."""
    need = int(np.prod(shape))
    devs = jax.devices()
    if len(devs) < need:
        raise ValueError(
            f"dp x mp mesh {tuple(shape)} needs {need} devices, have "
            f"{len(devs)} (hint: XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need})")
    return Mesh(np.asarray(devs[:need]).reshape(tuple(shape)),
                ("data", "model"))


def logical_to_mesh_axes(logical_axes: Sequence[Optional[str]]) -> P:
    """Logical axis names -> PartitionSpec under ``LOGICAL_AXIS_RULES``
    (first match wins; unknown names are an error — an unmapped axis is
    a rules-table gap, not a replication decision)."""
    out = []
    for name in logical_axes:
        if name is None:
            out.append(None)
            continue
        for logical, mesh_axis in LOGICAL_AXIS_RULES:
            if logical == name:
                out.append(mesh_axis)
                break
        else:
            raise ValueError(
                f"logical axis {name!r} has no LOGICAL_AXIS_RULES entry "
                f"(parallel/mesh.py) — declare it before sharding by it")
    return P(*out)


def transformer_block_logical_axes(params) -> object:
    """Logical-axes pytree (tuples of axis names, one per leaf) for a
    ``models.transformer.TransformerBlock`` param tree — the declared
    sharding intent GP405 validates lowered programs against. Matches
    by the flax module-path names, so a renamed/added projection fails
    loudly here instead of silently replicating."""
    import jax.tree_util as jtu

    def axes_for(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        leaf_name = names[-1]
        if any(n in ("tokeys", "toqueries", "tovalues") for n in names):
            return ("embed", "joined_kv")
        if "unifyheads" in names:
            return (("joined_kv", "embed") if leaf_name == "kernel"
                    else ("embed",))
        if "ff1" in names:
            return (("embed", "mlp") if leaf_name == "kernel"
                    else ("mlp",))
        if "ff2" in names:
            return (("mlp", "embed") if leaf_name == "kernel"
                    else ("embed",))
        if any(n.startswith("norm") for n in names):
            return ("embed",)
        raise ValueError(
            f"TransformerBlock param {'/'.join(names)!r} has no logical-"
            f"axes mapping (parallel/mesh.py transformer_block_logical_"
            f"axes) — extend the table before sharding the new module")

    return jtu.tree_map_with_path(axes_for, params)


def register_audit_programs(ctx):
    """graftprog registry hook: the data-parallel superstep under a
    fixed ``AUDIT_MESH_DEVICES``-wide mesh (fingerprints must not vary
    with the host's device count), plus the population-over-dp twin
    (graftlattice — the member axis sharded over the same mesh).
    Skipped — never failed — on hosts exposing fewer CPU devices."""
    from ..analysis.registry import AuditProgram
    import jax.numpy as jnp
    if len(jax.devices()) < AUDIT_MESH_DEVICES:
        skip = AuditProgram.skipped(
            f"needs >= {AUDIT_MESH_DEVICES} devices (hint: XLA_FLAGS="
            f"--xla_force_host_platform_device_count="
            f"{AUDIT_MESH_DEVICES})")
        return {"dp_superstep": skip, "pop_dp_superstep": skip,
                **_dpmp_block_twin(ctx)}
    dp = DataParallel(ctx.exp, make_mesh(AUDIT_MESH_DEVICES))
    k = ctx.superstep_k
    sup = dp.superstep_program(k, donate=True)
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    keys = jax.ShapeDtypeStruct((k,) + key.shape, key.dtype)
    return {
        "dp_superstep": AuditProgram(
            sup, (dp.audit_avals(ctx.ts_shape), keys, jnp.asarray(0)),
            donate_argnums=(0,),
            description=f"fused K={k} superstep sharded over a "
                        f"{AUDIT_MESH_DEVICES}-device data axis"),
        **_pop_dp_twin(k, key),
        **_dpmp_block_twin(ctx),
    }


def _dpmp_block_twin(ctx):
    """The dp×mp dry-run audit entry (graftshard / ROADMAP item 3): a
    ``TransformerBlock`` at the audit model scale lowered under the
    fixed 2×2 ("data", "model") mesh with every param leaf stamped from
    ``LOGICAL_AXIS_RULES`` via ``transformer_block_logical_axes`` and
    activations on ("batch", "tokens", "embed"). The program's
    ``expected_output_shardings`` declares the same logical spec for the
    block output, so the comms audit's GP405 check IS the partitioner
    dry-run: if GSPMD stops honoring a declared rule (or the rules table
    drifts from what lowering produces) the gate fails statically. Its
    collective census (the mp all-reduces the sharded contractions
    insert) is ratcheted like every mesh program's."""
    from ..analysis.registry import AuditProgram
    from ..models.transformer import TransformerBlock
    import jax.numpy as jnp

    need = int(np.prod(AUDIT_DPMP_MESH))
    if len(jax.devices()) < need:
        return {"dpmp_block": AuditProgram.skipped(
            f"needs >= {need} devices (hint: XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need})")}
    mesh = make_dpmp_mesh()
    m = ctx.cfg.model
    dt = jnp.dtype(m.dtype)
    b, t = 4, 8                         # tiny token grid, audit-scale
    block = TransformerBlock(emb=m.emb, heads=m.heads,
                             standard_heads=m.standard_heads, dtype=dt)
    q0 = jnp.zeros((b, t, m.emb), dt)
    k0 = jnp.zeros((b, t, m.emb), dt)
    params = jax.eval_shape(lambda: block.init(
        jax.random.PRNGKey(0), q0, k0))

    logical = transformer_block_logical_axes(params)
    shardings = jax.tree.map(
        lambda ax: NamedSharding(mesh, logical_to_mesh_axes(ax)),
        logical,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(a, (str, type(None))) for a in x))
    params_aval = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        params, shardings)
    act = jax.ShapeDtypeStruct(
        (b, t, m.emb), dt,
        sharding=NamedSharding(
            mesh, logical_to_mesh_axes(("batch", "tokens", "embed"))))

    def apply(p, q, kk):
        return block.apply(p, q, kk)
    apply.__name__ = apply.__qualname__ = "_dpmp_block"
    return {"dpmp_block": AuditProgram(
        jax.jit(apply), (params_aval, act, act),
        expected_output_shardings=act.sharding,
        description=f"TransformerBlock under the fixed "
                    f"{AUDIT_DPMP_MESH[0]}x{AUDIT_DPMP_MESH[1]} "
                    f"(data, model) audit mesh, params stamped from "
                    f"LOGICAL_AXIS_RULES — the ROADMAP item 3 dry-run "
                    f"gate (GP405) plus its collective census")}


def _pop_dp_twin(k, key):
    """The population-over-dp audit entry (graftlattice): the SAME
    ``superstep_pop`` program (``run.population_superstep_program``,
    P=2 population audit scale) lowered with every state/spec leaf
    annotated with its ``population_shardings`` member-axis placement —
    the SPMD executable ``run_sequential`` dispatches when
    ``population.size`` and ``dp_devices`` are both set. Unsharded avals
    would lower the single-device ``superstep_pop`` again and the
    recorded budgets would be fiction (the ``DataParallel.audit_avals``
    rationale)."""
    from ..analysis.registry import AuditProgram, population_audit_context
    pctx = population_audit_context()
    mesh = make_mesh(AUDIT_MESH_DEVICES)
    p, kk = pctx.cfg.population.size, pctx.superstep_k
    ts_shape, spec_shape = pctx.ts_shape

    def annotate(tree):
        return jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            tree, population_shardings(mesh, tree))

    keys = jax.ShapeDtypeStruct((p, kk) + key.shape, key.dtype)
    prog = pctx.exp.population_superstep_program(kk, donate=True)
    import jax.numpy as jnp
    return {"pop_dp_superstep": AuditProgram(
        prog, (annotate(ts_shape), annotate(keys), jnp.asarray(0),
               annotate(spec_shape)),
        donate_argnums=(0,),
        description=f"fused K={kk} population superstep with the P={p} "
                    f"member axis sharded over a {AUDIT_MESH_DEVICES}-"
                    f"device data axis (population-over-dp: whole "
                    f"members per device, no cross-member collectives)")}
