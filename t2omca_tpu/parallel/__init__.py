from .mesh import (DataParallel, make_mesh, replicate, shard_episode_axis)

__all__ = ["make_mesh", "replicate", "shard_episode_axis", "DataParallel"]
