from .distributed import maybe_initialize_distributed
from .mesh import DataParallel, make_mesh

__all__ = ["make_mesh", "DataParallel", "maybe_initialize_distributed"]
