from .distributed import maybe_initialize_distributed
from .mesh import DataParallel, make_mesh, partition_devices

__all__ = ["make_mesh", "partition_devices", "DataParallel",
           "maybe_initialize_distributed"]
