from .distributed import maybe_initialize_distributed
from .mesh import (DataParallel, make_mesh, partition_devices,
                   population_shardings)

__all__ = ["make_mesh", "partition_devices", "DataParallel",
           "population_shardings", "maybe_initialize_distributed"]
