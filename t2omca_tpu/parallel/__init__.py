from .distributed import maybe_initialize_distributed
from .mesh import (DataParallel, make_mesh, replicate, shard_episode_axis)

__all__ = ["make_mesh", "replicate", "shard_episode_axis", "DataParallel",
           "maybe_initialize_distributed"]
