"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no long-context story — its attention spans ≤ n_agents+3
entity tokens on one device (SURVEY.md §5.7). This module is the first-class
scaling path for when the entity axis outgrows a chip (256+ AGVs per env,
or entity-token models with thousands of entities): shard the TOKEN axis of
attention across a mesh axis and keep compute local.

Two standard schemes, both pure collectives over ICI (no NCCL analog):

* **Ring attention** (`ring_attention`): K/V blocks rotate around the mesh
  axis via ``lax.ppermute`` while each device keeps its local Q block and
  accumulates the softmax *online* (flash-attention-style running max /
  normalizer), so the full T×T score matrix never exists anywhere. N-1
  hops overlap with compute; memory per device is O(T/N).
* **Ulysses all-to-all** (`ulysses_attention`): two ``lax.all_to_all``
  reshards — tokens→heads before attention, heads→tokens after — so each
  device computes FULL-sequence attention for a subset of heads. Cheaper
  collectives for moderate T; requires heads divisible by the axis size.

Both are exact (up to fp reassociation) equivalents of dense softmax
attention, verified against the dense reference on the virtual 8-device
mesh in tests/test_ring_attention.py.

Usage is via ``shard_map`` with the token axis sharded on ``axis_name``;
scaling (e.g. quirk Q1's ``d**-1/4`` on both q and k) is the caller's
responsibility, exactly like the dense path in ``models/transformer.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _online_block(q, k_blk, v_blk, o, l, m, mask_blk=None):
    """Accumulate one K/V block into the running (o, l, m) softmax state.

    q ``(..., Tq, D)``; k_blk/v_blk ``(..., Tk, D)``; o ``(..., Tq, D)``;
    l, m ``(..., Tq)``; ``mask_blk (..., Tk)`` marks valid key positions
    (False keys — e.g. token-axis padding — are excluded from the softmax).
    """
    logits = jnp.einsum("...qd,...kd->...qk", q, k_blk)
    if mask_blk is not None:
        logits = jnp.where(mask_blk[..., None, :], logits, -jnp.inf)
    m_blk = logits.max(axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # all-masked-so-far rows have m == m_new == -inf. Double-where: the
    # inner where keeps exp's argument finite so the UNTAKEN branch never
    # evaluates exp(-inf - -inf) = NaN — where's VJP differentiates both
    # branches, so a single outer where still back-propagates NaN.
    neg = m_new == -jnp.inf
    alpha = jnp.where(neg, 0.0,
                      jnp.exp(jnp.where(neg, 0.0, m - m_new)))
    negq = neg[..., None]
    p = jnp.where(negq, 0.0,
                  jnp.exp(jnp.where(negq, 0.0, logits - m_new[..., None])))
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum("...qk,...kd->...qd", p, v_blk)
    return o_new, l_new, m_new


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str,
                   kv_mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Exact softmax attention with the token axis sharded on ``axis_name``.

    Call inside ``shard_map``; per-device shapes ``(..., T_local, D)``.
    Returns the local block of the attention output. K/V travel the ring
    once (N-1 ``ppermute`` hops over ICI), Q never moves. ``kv_mask``
    (``(..., T_local)`` bool, sharded like K/V) excludes padded key
    positions — needed when the global token count is not a multiple of the
    axis size.
    """
    n = lax.psum(1, axis_name)
    perm = [(j, (j - 1) % n) for j in range(n)]      # pull from the right

    # inits derived from q so shard_map marks them device-varying (fresh
    # constants would be 'unvarying' and fail the fori_loop carry typecheck)
    o = q.astype(jnp.float32) * 0.0
    l = o[..., 0]
    m = l - jnp.inf

    # one loop body for both paths: an absent mask becomes all-True (the
    # extra ppermute of a bool block is negligible next to the K/V blocks,
    # and a single body keeps the NaN guard in _online_block on one path)
    if kv_mask is None:
        # unconditionally-True mask derived from k so shard_map marks it
        # device-varying; `| True` keeps it True even for non-finite k
        # (a finiteness-dependent expression would silently drop a whole
        # device's valid keys if one value overflowed)
        kv_mask = (k[..., 0] * 0 == 0) | jnp.bool_(True)

    def body(i, carry):
        o, l, m, kb, vb, mb = carry
        o, l, m = _online_block(q.astype(jnp.float32),
                                kb.astype(jnp.float32),
                                vb.astype(jnp.float32), o, l, m, mb)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        mb = lax.ppermute(mb, axis_name, perm)
        return o, l, m, kb, vb, mb

    o, l, m, *_ = lax.fori_loop(0, n, body, (o, l, m, k, v, kv_mask))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str) -> jnp.ndarray:
    """Exact softmax attention via head↔token resharding (DeepSpeed-Ulysses).

    Call inside ``shard_map``; per-device shapes ``(B, T_local, H, D)`` with
    the token axis sharded on ``axis_name`` and ``H`` divisible by the axis
    size. Internally: all_to_all → ``(B, T_full, H_local, D)`` → dense
    attention per local head → all_to_all back.
    """
    n = lax.psum(1, axis_name)

    # tokens → heads: split the head axis, gather the token axis
    reshard = lambda x: lax.all_to_all(x, axis_name, split_axis=2,
                                       concat_axis=1, tiled=True)
    qf, kf, vf = reshard(q), reshard(k), reshard(v)   # (B, T, H/n, D)

    logits = jnp.einsum("bqhd,bkhd->bhqk", qf.astype(jnp.float32),
                        kf.astype(jnp.float32))
    attn = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", attn, vf.astype(jnp.float32))

    # heads → tokens: inverse reshard
    out = lax.all_to_all(out.astype(q.dtype), axis_name, split_axis=1,
                         concat_axis=2, tiled=True)
    return out
