"""Batch preprocess transforms (M15).

The reference driver registers ``actions → actions_onehot`` as an on-insert
preprocess (``/root/reference/per_run.py:17,133``, ``components/transforms``
→ ``OneHot``). Here transforms are plain functions applied where the consumer
needs them (the learner one-hots actions on the fly — cheaper than storing
the expansion in replay HBM).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def one_hot(actions: jnp.ndarray, n_actions: int) -> jnp.ndarray:
    """``OneHot(out_dim=n_actions)``: int action indices → one-hot float rows."""
    return jax.nn.one_hot(actions, n_actions, dtype=jnp.float32)
