"""Host-RAM prioritized replay with a device-side stratified PER sample.

The reference's ``buffer_cpu_only`` flag keeps replay on CPU and moves only
sampled batches to the accelerator (``/root/reference/per_run.py:143-146``,
``:229-230``). This is that mode for the TPU framework: episode storage in
pinned host NumPy (capacity bounded by RAM, not HBM) while the PER *index*
lives on device — a mirrored ``(capacity,)`` f32 priority vector sampled by
one jitted stratified inverse-CDF program (the episode-buffer formulation,
``components/episode_buffer.PrioritizedReplayBuffer.sample``). The
pre-PR-13 implementation kept priorities in a C++ sum-tree
(``native/sumtree.cpp``) and paid a ctypes crossing per sample; the
steady-state sample path now runs ZERO sum-tree calls — priority writes are
O(batch) scatters into both mirrors, sampling is one device dispatch whose
importance weights feed the train step without ever visiting the host. The
``PySumTree``/``NativeSumTree`` classes remain as the reference formulation
the parity tests pin sampled indices and weights against
(``tests/test_host_replay.py``).

Same method surface as the device buffers (insert / can_sample / sample /
update_priorities) so the driver only branches on ``is_host`` to skip
jitting the buffer stages. Sampling semantics match the device PER:
stratified inverse-CDF over ``p^alpha``, importance weights ``(N·P)^-beta``
max-normalized, beta annealed to 1 over ``t_max`` (Q9 priorities flow back
per sampled episode).

Priorities are stored-space ``p^alpha`` at f32 — the device mirror cannot
hold the old tree's f64, and |TD|-scale priorities fit f32 with orders of
headroom; the host mirror keeps the SAME f32 values so the two can never
drift (pinned by test). ``max_priority`` tracking stays a host f64 float.
"""

from __future__ import annotations

import ctypes
import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .episode_buffer import EpisodeBatch


class PySumTree:
    """NumPy sum-tree formulation. Since PR 13 this is no longer on the
    live sample path — it survives (with ``NativeSumTree``) as the
    reference formulation the device-sample parity tests pin indices
    and weights against."""

    def __init__(self, cap: int):
        self.cap = cap
        self.leaf = np.zeros(cap, np.float64)

    def set_batch(self, idx, pri):
        self.leaf[idx] = pri

    def get(self, idx):
        return self.leaf[idx]

    def total(self):
        return float(self.leaf.sum())

    def sample(self, us: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        n = len(us)
        cdf = np.cumsum(self.leaf)
        u = (np.arange(n) + us) / n * cdf[-1]
        idx = np.minimum(np.searchsorted(cdf, u, side="right"),
                         self.cap - 1)
        return idx.astype(np.int64), self.leaf[idx]


class NativeSumTree:
    """ctypes wrapper over native/sumtree.cpp (extern "C" ABI). Parity
    reference only since PR 13 (see ``PySumTree``) — the steady-state
    sample path runs zero ctypes crossings."""

    def __init__(self, cap: int):
        from ..native import load_sumtree
        self._lib = load_sumtree()
        self.cap = 1
        while self.cap < cap:
            self.cap *= 2
        self._ptr = self._lib.sumtree_create(self.cap)
        if not self._ptr:
            raise MemoryError("sumtree_create failed")

    def __del__(self):
        lib = getattr(self, "_lib", None)
        ptr = getattr(self, "_ptr", None)
        if lib is not None and ptr:
            lib.sumtree_free(ptr)

    def set_batch(self, idx, pri):
        idx = np.ascontiguousarray(idx, np.int64)
        pri = np.ascontiguousarray(pri, np.float64)
        self._lib.sumtree_set_batch(
            self._ptr, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            pri.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), len(idx))

    def get(self, idx):
        # one batched ctypes crossing (native sumtree_get_batch) instead
        # of a per-element Python loop over sumtree_get — the FFI call
        # overhead dominated the old path at any realistic batch size
        idx = np.ascontiguousarray(np.atleast_1d(idx), np.int64)
        out = np.empty(len(idx), np.float64)
        self._lib.sumtree_get_batch(
            self._ptr, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx), out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return out

    def total(self):
        return self._lib.sumtree_total(self._ptr)

    def sample(self, us: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        n = len(us)
        us = np.ascontiguousarray(us, np.float64)
        out_idx = np.empty(n, np.int64)
        out_pri = np.empty(n, np.float64)
        self._lib.sumtree_sample(
            self._ptr, us.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            n, out_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            out_pri.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return out_idx, out_pri


# ------------------------------------------------- device sample programs
#
# Module-level jitted programs shared by every HostReplayBuffer instance
# (one compile per (capacity, batch) aval set). The priority vector is the
# only state they touch; everything else stays in host RAM.

@functools.partial(jax.jit, donate_argnums=(0,))
def _mirror_set(pri: jnp.ndarray, idx: jnp.ndarray,
                vals: jnp.ndarray) -> jnp.ndarray:
    """O(batch) scatter into the device priority mirror (donated: XLA
    updates the capacity-length vector in place instead of copying it
    per insert/priority-feedback)."""
    return pri.at[idx].set(vals)


def _valid_mass(pri: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """Zero the unfilled (and any poisoned) tail: only slots < n carry
    sampling mass, exactly like the device buffer's ``_probs`` mask —
    garbage beyond the fill line can never leak into indices or
    weights."""
    return jnp.where(jnp.arange(pri.shape[0]) < n, pri, 0.0)


def _weights_from_mass(p: jnp.ndarray, idx: jnp.ndarray, n: jnp.ndarray,
                       beta: jnp.ndarray) -> jnp.ndarray:
    """Max-normalized ``(N·P)^-beta`` over the already-masked priority
    mass — ONE definition consumed by both the live sample program and
    the sum-tree parity reference, so weight parity is pinned through
    the identical lowering rather than through cross-library float
    accidents (numpy and XLA powf differ in the last ulp). The
    ``p.sum()`` here is deliberately NOT replaced by the sampler's
    ``cdf[-1]``: the standalone ``_importance_weights`` entry point has
    no cdf, and the two reductions associate differently in f32 — one
    extra O(capacity) reduce buys the bit-identical shared form."""
    probs = p[idx] / jnp.maximum(p.sum(), 1e-12)
    nn = jnp.maximum(n, 1).astype(jnp.float32)
    w = (nn * jnp.maximum(probs, 1e-12)) ** (-beta)
    return w / jnp.maximum(w.max(), 1e-12)


@jax.jit
def _importance_weights(pri: jnp.ndarray, idx: jnp.ndarray,
                        n: jnp.ndarray, beta: jnp.ndarray) -> jnp.ndarray:
    """Standalone entry point over the RAW mirror (tests evaluate it at
    the sum-tree's own sampled indices)."""
    return _weights_from_mass(_valid_mass(pri, n), idx, n, beta)


@jax.jit
def _stratified_sample(pri: jnp.ndarray, us: jnp.ndarray, n: jnp.ndarray,
                       beta: jnp.ndarray
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The sum-tree's stratified inverse-CDF sample as one device
    program: one uniform per equal-mass stratum, ``searchsorted`` over
    the masked priority cdf (side="right", the tree-descent convention),
    clamped to the last VALID slot so the exact-right-edge float
    artifact (``u·total == total``) resolves inside the fill line — the
    ctypes path redrew there instead; with zero mass on the invalid tail
    the clamp is the only reachable difference and it is measure-zero.
    Indices are pinned bit-equal to ``NativeSumTree``/``PySumTree``
    sampling at the same uniforms (tests/test_host_replay.py)."""
    p = _valid_mass(pri, n)
    cdf = jnp.cumsum(p)
    bs = us.shape[0]
    u = (jnp.arange(bs, dtype=jnp.float32) + us) / bs * cdf[-1]
    idx = jnp.searchsorted(cdf, u, side="right")
    idx = jnp.minimum(idx, jnp.maximum(n - 1, 0))
    return idx, _weights_from_mass(p, idx, n, beta)


@dataclasses.dataclass
class HostReplayBuffer:
    """Prioritized episode replay in host RAM (reference buffer_cpu_only)."""

    capacity: int
    episode_limit: int
    n_agents: int
    n_actions: int
    obs_dim: int
    state_dim: int
    alpha: float = 0.6
    beta0: float = 0.4
    t_max: int = 1
    store_dtype: str = "float32"
    prioritized: bool = True
    is_host: bool = True

    def __post_init__(self):
        t, cap = self.episode_limit, self.capacity
        if self.store_dtype == "bfloat16":
            import ml_dtypes  # ships with jax
            sd = np.dtype(ml_dtypes.bfloat16)
        else:
            sd = np.dtype(self.store_dtype)
        self._storage = EpisodeBatch(
            obs=np.zeros((cap, t + 1, self.n_agents, self.obs_dim), sd),
            state=np.zeros((cap, t + 1, self.state_dim), sd),
            avail_actions=np.zeros((cap, t + 1, self.n_agents,
                                    self.n_actions), bool),
            actions=np.zeros((cap, t, self.n_agents), np.int32),
            reward=np.zeros((cap, t), np.float32),
            terminated=np.zeros((cap, t), bool),
            filled=np.zeros((cap, t), bool),
        )
        # stored-space p^alpha, twin-mirrored: host f32 (checkpoint /
        # introspection / parity tests) and device f32 (the sample
        # program's operand). Writes go through _set_priorities so the
        # two can never drift; the device copy is donated in place.
        self._pri = np.zeros(cap, np.float32)
        self._pri_dev = jnp.zeros(cap, jnp.float32)
        self._pos = 0
        self._count = 0
        self._max_priority = 1.0
        self._rng = np.random.default_rng(0)
        # deferred priority feedback (run.py host path): (idx, td_ref,
        # finite_ref) device refs whose host fetch is consumed at the
        # NEXT sample instead of blocking the train iteration
        self._pending_update = None

    # ------------------------------------------------------------- protocol

    def insert_episode_batch(self, batch: EpisodeBatch) -> None:
        # consume any deferred priority feedback BEFORE the insert can
        # overwrite its slots: on ring wrap-around the deferred idx may be
        # exactly the slots this batch reuses, and flushing after would
        # stamp the EVICTED episodes' |TD| onto the fresh episodes
        # (which must start at max_priority) — flushing here keeps the
        # priority mirrors byte-identical to the old synchronous order
        self.flush_priority_updates()
        host = jax.device_get(batch)
        b = host.obs.shape[0]
        idx = (self._pos + np.arange(b)) % self.capacity
        for name in ("obs", "state", "avail_actions", "actions", "reward",
                     "terminated", "filled"):
            getattr(self._storage, name)[idx] = np.asarray(
                getattr(host, name), getattr(self._storage, name).dtype)
        if self.prioritized:
            self._set_priorities(idx, np.full(
                b, self._max_priority ** self.alpha, np.float32))
        self._pos = int((self._pos + b) % self.capacity)
        self._count = int(min(self._count + b, self.capacity))

    def _set_priorities(self, idx: np.ndarray, vals: np.ndarray) -> None:
        """O(batch) priority write into BOTH mirrors (same f32 values —
        the host array is the device vector's byte-twin)."""
        self._pri[idx] = vals
        self._pri_dev = _mirror_set(self._pri_dev, jnp.asarray(idx),
                                    jnp.asarray(vals))

    def can_sample(self, batch_size: int) -> bool:
        return self._count >= batch_size

    def defer_priority_update(self, idx: np.ndarray, td_ref, finite_ref
                              ) -> None:
        """Asynchronous replacement for the post-train ``update_priorities``
        call: start the device→host copies NOW (non-blocking) and stash
        the refs; the fetch is consumed by ``flush_priority_updates`` at
        the next ``sample`` — by which point a full rollout has executed
        and the copy has long landed, so the ``np.asarray`` there is a
        wait-free read instead of the ~0.66 s blocking round-trip the
        axon tunnel charges per ``jax.device_get`` (BASELINE.md). The
        sampling distribution sees each step's |TD| one iteration late —
        the same deferral the device path's async dispatch pipeline
        already has."""
        if not self.prioritized:
            return
        self.flush_priority_updates()      # at most one in flight
        for ref in (td_ref, finite_ref):
            start = getattr(ref, "copy_to_host_async", None)
            if start is not None:
                start()
        self._pending_update = (np.asarray(idx, np.int64), td_ref,
                                finite_ref)

    def drop_pending_update(self) -> None:
        """Abandon deferred priority feedback WITHOUT consuming it. The
        driver's checkpoint restore calls this when the train step that
        produced the refs was rolled back — fetching them would stamp the
        abandoned computation's |TD| into the priority mirrors, or
        re-raise a fault from a poisoned device array outside any ladder
        routing."""
        self._pending_update = None

    def flush_priority_updates(self) -> None:
        """Consume the deferred priority feedback, if any. A tripped
        (non-finite) train step leaves the priority mirrors untouched —
        NaN mass would corrupt the sampling cdf permanently."""
        if self._pending_update is None:
            return
        idx, td_ref, finite_ref = self._pending_update
        self._pending_update = None
        if bool(np.asarray(jax.device_get(finite_ref))):
            td = np.asarray(jax.device_get(td_ref), np.float64)
            self.update_priorities(idx, td + 1e-6)             # Q9

    def _beta(self, t_env: int) -> np.float32:
        """Annealed beta, computed host-side (t_env is a host int on
        this path) and cast ONCE to the f32 the sample program runs in."""
        return np.float32(self.beta0 + (1.0 - self.beta0) * min(
            max(float(t_env) / self.t_max, 0.0), 1.0))

    def sample(self, batch_size: int, t_env: int
               ) -> Tuple[EpisodeBatch, np.ndarray, np.ndarray]:
        self.flush_priority_updates()
        n = self._count
        if self.prioritized:
            # host RNG keeps the stratum uniforms (one tiny h2d per
            # sample); index selection + importance weights run as ONE
            # device program over the mirrored priority vector — the
            # steady-state path executes zero sum-tree ctypes calls.
            # The uniforms are cast to f32 BEFORE use so the parity
            # reference (the f64 sum-tree formulation) sees the exact
            # same values under lossless promotion.
            us = self._rng.random(batch_size).astype(np.float32)
            idx_dev, w = _stratified_sample(
                self._pri_dev, jnp.asarray(us),
                jnp.asarray(n, jnp.int32), jnp.asarray(self._beta(t_env)))
            # the episode gather reads host RAM, so the indices must
            # come home — a batch_size-int fetch, the path's one
            # unavoidable d2h (the weights stay on device and feed the
            # train step directly)
            idx = np.asarray(jax.device_get(idx_dev), np.int64)
        else:
            idx = self._rng.choice(n, size=batch_size, replace=False)
            w = jax.numpy.ones(batch_size, jax.numpy.float32)
        batch = jax.tree.map(lambda s: jax.numpy.asarray(s[idx]),
                             self._storage)
        return batch, idx, w

    def sight_priority_info(self) -> dict:
        """graftsight PER health over the HOST priority mirror (pure
        numpy — the buffer_cpu_only path pays zero device traffic for
        the read; run.py's host train path appends it to train_info)."""
        from ..obs.sight import buffer_sight_info_host
        return buffer_sight_info_host(self._pri, self._count)

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        if not self.prioritized:
            return
        pri = np.asarray(jax.device_get(priorities), np.float64)
        self._max_priority = float(max(self._max_priority, pri.max()))
        self._set_priorities(np.asarray(idx, np.int64),
                             (pri ** self.alpha).astype(np.float32))
