"""Host-RAM prioritized replay with a native sum-tree index.

The reference's ``buffer_cpu_only`` flag keeps replay on CPU and moves only
sampled batches to the accelerator (``/root/reference/per_run.py:143-146``,
``:229-230``). This is that mode for the TPU framework: episode storage in
pinned host NumPy (capacity bounded by RAM, not HBM), priorities in the
C++ sum-tree (``native/sumtree.cpp``, O(log n) set/sample via ctypes), and a
pure-NumPy ``PySumTree`` fallback when no g++ toolchain exists.

Same method surface as the device buffers (insert / can_sample / sample /
update_priorities) so the driver only branches on ``is_host`` to skip
jitting the buffer stages. Sampling semantics match the device PER:
stratified inverse-CDF over ``p^alpha``, importance weights ``(N·P)^-beta``
max-normalized, beta annealed to 1 over ``t_max`` (Q9 priorities flow back
per sampled episode).
"""

from __future__ import annotations

import ctypes
import dataclasses
from typing import Tuple

import jax
import numpy as np

from .episode_buffer import EpisodeBatch


class PySumTree:
    """NumPy fallback with the same operations as the native tree."""

    def __init__(self, cap: int):
        self.cap = cap
        self.leaf = np.zeros(cap, np.float64)

    def set_batch(self, idx, pri):
        self.leaf[idx] = pri

    def get(self, idx):
        return self.leaf[idx]

    def total(self):
        return float(self.leaf.sum())

    def sample(self, us: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        n = len(us)
        cdf = np.cumsum(self.leaf)
        u = (np.arange(n) + us) / n * cdf[-1]
        idx = np.minimum(np.searchsorted(cdf, u, side="right"),
                         self.cap - 1)
        return idx.astype(np.int64), self.leaf[idx]


class NativeSumTree:
    """ctypes wrapper over native/sumtree.cpp (extern "C" ABI)."""

    def __init__(self, cap: int):
        from ..native import load_sumtree
        self._lib = load_sumtree()
        self.cap = 1
        while self.cap < cap:
            self.cap *= 2
        self._ptr = self._lib.sumtree_create(self.cap)
        if not self._ptr:
            raise MemoryError("sumtree_create failed")

    def __del__(self):
        lib = getattr(self, "_lib", None)
        ptr = getattr(self, "_ptr", None)
        if lib is not None and ptr:
            lib.sumtree_free(ptr)

    def set_batch(self, idx, pri):
        idx = np.ascontiguousarray(idx, np.int64)
        pri = np.ascontiguousarray(pri, np.float64)
        self._lib.sumtree_set_batch(
            self._ptr, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            pri.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), len(idx))

    def get(self, idx):
        # one batched ctypes crossing (native sumtree_get_batch) instead
        # of a per-element Python loop over sumtree_get — the FFI call
        # overhead dominated the old path at any realistic batch size
        idx = np.ascontiguousarray(np.atleast_1d(idx), np.int64)
        out = np.empty(len(idx), np.float64)
        self._lib.sumtree_get_batch(
            self._ptr, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx), out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return out

    def total(self):
        return self._lib.sumtree_total(self._ptr)

    def sample(self, us: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        n = len(us)
        us = np.ascontiguousarray(us, np.float64)
        out_idx = np.empty(n, np.int64)
        out_pri = np.empty(n, np.float64)
        self._lib.sumtree_sample(
            self._ptr, us.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            n, out_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            out_pri.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return out_idx, out_pri


def _make_tree(cap: int):
    try:
        return NativeSumTree(cap)
    except Exception:
        return PySumTree(cap)


@dataclasses.dataclass
class HostReplayBuffer:
    """Prioritized episode replay in host RAM (reference buffer_cpu_only)."""

    capacity: int
    episode_limit: int
    n_agents: int
    n_actions: int
    obs_dim: int
    state_dim: int
    alpha: float = 0.6
    beta0: float = 0.4
    t_max: int = 1
    store_dtype: str = "float32"
    prioritized: bool = True
    is_host: bool = True

    def __post_init__(self):
        t, cap = self.episode_limit, self.capacity
        if self.store_dtype == "bfloat16":
            import ml_dtypes  # ships with jax
            sd = np.dtype(ml_dtypes.bfloat16)
        else:
            sd = np.dtype(self.store_dtype)
        self._storage = EpisodeBatch(
            obs=np.zeros((cap, t + 1, self.n_agents, self.obs_dim), sd),
            state=np.zeros((cap, t + 1, self.state_dim), sd),
            avail_actions=np.zeros((cap, t + 1, self.n_agents,
                                    self.n_actions), bool),
            actions=np.zeros((cap, t, self.n_agents), np.int32),
            reward=np.zeros((cap, t), np.float32),
            terminated=np.zeros((cap, t), bool),
            filled=np.zeros((cap, t), bool),
        )
        self._tree = _make_tree(cap)
        self._pos = 0
        self._count = 0
        self._max_priority = 1.0
        self._rng = np.random.default_rng(0)
        # deferred priority feedback (run.py host path): (idx, td_ref,
        # finite_ref) device refs whose host fetch is consumed at the
        # NEXT sample instead of blocking the train iteration
        self._pending_update = None

    # ------------------------------------------------------------- protocol

    def insert_episode_batch(self, batch: EpisodeBatch) -> None:
        # consume any deferred priority feedback BEFORE the insert can
        # overwrite its slots: on ring wrap-around the deferred idx may be
        # exactly the slots this batch reuses, and flushing after would
        # stamp the EVICTED episodes' |TD| onto the fresh episodes
        # (which must start at max_priority) — flushing here keeps the
        # sum-tree byte-identical to the old synchronous update order
        self.flush_priority_updates()
        host = jax.device_get(batch)
        b = host.obs.shape[0]
        idx = (self._pos + np.arange(b)) % self.capacity
        for name in ("obs", "state", "avail_actions", "actions", "reward",
                     "terminated", "filled"):
            getattr(self._storage, name)[idx] = np.asarray(
                getattr(host, name), getattr(self._storage, name).dtype)
        if self.prioritized:
            self._tree.set_batch(idx, np.full(
                b, self._max_priority ** self.alpha))
        self._pos = int((self._pos + b) % self.capacity)
        self._count = int(min(self._count + b, self.capacity))

    def can_sample(self, batch_size: int) -> bool:
        return self._count >= batch_size

    def defer_priority_update(self, idx: np.ndarray, td_ref, finite_ref
                              ) -> None:
        """Asynchronous replacement for the post-train ``update_priorities``
        call: start the device→host copies NOW (non-blocking) and stash
        the refs; the fetch is consumed by ``flush_priority_updates`` at
        the next ``sample`` — by which point a full rollout has executed
        and the copy has long landed, so the ``np.asarray`` there is a
        wait-free read instead of the ~0.66 s blocking round-trip the
        axon tunnel charges per ``jax.device_get`` (BASELINE.md). The
        sampling distribution sees each step's |TD| one iteration late —
        the same deferral the device path's async dispatch pipeline
        already has."""
        if not self.prioritized:
            return
        self.flush_priority_updates()      # at most one in flight
        for ref in (td_ref, finite_ref):
            start = getattr(ref, "copy_to_host_async", None)
            if start is not None:
                start()
        self._pending_update = (np.asarray(idx, np.int64), td_ref,
                                finite_ref)

    def drop_pending_update(self) -> None:
        """Abandon deferred priority feedback WITHOUT consuming it. The
        driver's checkpoint restore calls this when the train step that
        produced the refs was rolled back — fetching them would stamp the
        abandoned computation's |TD| into the sum-tree, or re-raise a
        fault from a poisoned device array outside any ladder routing."""
        self._pending_update = None

    def flush_priority_updates(self) -> None:
        """Consume the deferred priority feedback, if any. A tripped
        (non-finite) train step leaves the sum-tree untouched — NaN
        priorities would corrupt it permanently."""
        if self._pending_update is None:
            return
        idx, td_ref, finite_ref = self._pending_update
        self._pending_update = None
        if bool(np.asarray(jax.device_get(finite_ref))):
            td = np.asarray(jax.device_get(td_ref), np.float64)
            self.update_priorities(idx, td + 1e-6)             # Q9

    def sample(self, batch_size: int, t_env: int
               ) -> Tuple[EpisodeBatch, np.ndarray, np.ndarray]:
        self.flush_priority_updates()
        n = self._count
        if self.prioritized:
            us = self._rng.random(batch_size)
            idx, pri_a = self._tree.sample(us)
            # unfilled slots carry zero priority, so a hit there can only be
            # an exact right-edge float artifact (u·total == total): redraw
            # instead of clamping, which would silently over-sample the last
            # valid episode; persistent hits mean corrupted bookkeeping
            oob = idx >= n
            tries = 0
            while oob.any():
                if tries >= 3:
                    raise RuntimeError(
                        "sum-tree repeatedly sampled unfilled slots — "
                        "priority bookkeeping is corrupted")
                ridx, rpri = self._tree.sample(
                    self._rng.random(int(oob.sum())))
                idx[oob], pri_a[oob] = ridx, rpri
                oob = idx >= n
                tries += 1
            total = self._tree.total()
            probs = pri_a / max(total, 1e-12)
            beta = self.beta0 + (1.0 - self.beta0) * min(
                max(float(t_env) / self.t_max, 0.0), 1.0)
            w = (n * np.maximum(probs, 1e-12)) ** (-beta)
            w = (w / max(w.max(), 1e-12)).astype(np.float32)
        else:
            idx = self._rng.choice(n, size=batch_size, replace=False)
            w = np.ones(batch_size, np.float32)
        batch = jax.tree.map(lambda s: jax.numpy.asarray(s[idx]),
                             self._storage)
        return batch, idx, jax.numpy.asarray(w)

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        if not self.prioritized:
            return
        pri = np.asarray(jax.device_get(priorities), np.float64)
        self._max_priority = float(max(self._max_priority, pri.max()))
        self._tree.set_batch(np.asarray(idx, np.int64), pri ** self.alpha)
