from .action_selectors import (EpsilonGreedySelector, NoisySelector,
                               SELECTOR_REGISTRY)
from .episode_buffer import (BufferState, EpisodeBatch, ReplayBuffer,
                             PrioritizedReplayBuffer)
from .schedules import DecayThenFlatSchedule
from .transforms import one_hot

__all__ = [
    "DecayThenFlatSchedule",
    "EpsilonGreedySelector",
    "NoisySelector",
    "SELECTOR_REGISTRY",
    "EpisodeBatch",
    "BufferState",
    "ReplayBuffer",
    "PrioritizedReplayBuffer",
    "one_hot",
]
