"""Episode batch + replay buffers as device-resident pytrees (M4).

Re-creates the contracts of the unreleased ``components/episode_buffer``
(``EpisodeBatch`` / ``ReplayBuffer`` / ``PrioritizedReplayBuffer``, imported
at ``/root/reference/parallel_runner.py:3`` and ``/root/reference/per_run.py:16``;
contracts pinned in SURVEY.md §2.3 M4) — but where the reference keeps a
torch-tensor dict on CPU/GPU and slices it with Python, here the whole buffer
is a fixed-shape pytree living in device HBM and every operation (insert,
sample, priority update) is a pure jittable function. Sampling never leaves
the chip, so the rollout→insert→sample→train loop compiles into a handful of
XLA programs with no host round-trips.

Scheme (reference ``per_run.py:119-133``): ``state (T+1, S)``, per-agent
``obs (T+1, A, O)``, ``avail_actions (T+1, A, n_actions)``, ``actions (T, A)``,
``reward (T,)``, ``terminated (T,)``, ``filled (T,)``. The trailing
timestep T of obs/state/avail is the bootstrap observation (the reference
stores ``episode_limit + 1`` steps per episode, ``per_run.py:143-146``).
``actions_onehot`` (M15) is materialized on demand by the consumer, not
stored.

Prioritized replay: per-*episode* priorities (the reference samples whole
episodes and feeds back one ``|TD|+1e-6`` priority per sampled episode,
``per_run.py:224-238``, Q9). Instead of a sequential sum-tree — hostile to
XLA — sampling uses stratified inverse-CDF over the normalized priority
distribution (SURVEY.md §7.4(4)): O(capacity) vectorized ops, exact for the
β-weighted expectation, fine at the reference's buffer sizes (≤ a few
thousand episodes).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class CompactEntityObs:
    """Factored entity observation (``env.compact_obs``) as episode storage:
    ~``obs_dim/(rows+stats)`` ≈ 20× smaller than the flattened ``(A, A·F)``
    obs it reconstructs exactly (same-MEC visibility × shared per-position
    normalization affine; ops/query_slice.agent_forward_qslice_entity
    consumes it directly, tests/test_entity_tables.py pins the
    reconstruction)."""

    rows: jnp.ndarray       # (B, T+1, A, F-1) — raw entity feature rows
    mec_index: jnp.ndarray  # (B, T+1, A) int8 — visibility = same-MEC
    mean: jnp.ndarray       # (B, T+1, A, F) f32 — per-position Welford mean
    std: jnp.ndarray        # (B, T+1, A, F) f32


@struct.dataclass
class EpisodeBatch:
    """One (batch of) episode(s): arrays shaped ``(B, T(+1), ...)``."""

    obs: jnp.ndarray            # (B, T+1, A, obs_dim) float32 — or a
                                # CompactEntityObs pytree (compact storage)
    state: jnp.ndarray          # (B, T+1, state_dim) float32
    avail_actions: jnp.ndarray  # (B, T+1, A, n_actions) bool (storage; a
                                # predicate — arithmetic misuse is a type
                                # error by construction)
    actions: jnp.ndarray        # (B, T, A) int32
    reward: jnp.ndarray         # (B, T) float32
    terminated: jnp.ndarray     # (B, T) bool — env-terminal, time-limit excluded (Q7)
    filled: jnp.ndarray         # (B, T) bool

    @property
    def batch_size(self) -> int:
        return jax.tree.leaves(self.obs)[0].shape[0]

    @property
    def max_seq_length(self) -> int:
        return self.actions.shape[1]

    def max_t_filled(self) -> jnp.ndarray:
        """Longest filled prefix across the batch (reference
        ``per_run.py:226-227`` truncates the sampled batch to it; with static
        shapes we keep full length and rely on the masks instead)."""
        return self.filled.sum(axis=1).max()


@struct.dataclass
class TimeMajorEpisodes:
    """Rollout-scan emission BEFORE episode-batch assembly: the ``(T, B,
    ...)`` stacked per-step outputs plus the ``(B, ...)`` bootstrap step.
    The fused superstep path (``run.Experiment.superstep_program``)
    scatters these straight into the replay ring
    (``ReplayBuffer.insert_time_major``) without ever materializing the
    concatenated ``(B, T+1, ...)`` episode batch — the batch→copy HBM
    round-trip BASELINE.md flags on the bandwidth-bound path. The
    classic path assembles the same values into an ``EpisodeBatch`` via
    ``to_batch()`` (bit-identical contents either way)."""

    obs: jnp.ndarray            # (T, B, A, obs) storage-cast — or a
                                # CompactEntityObs pytree, time-major
    state: jnp.ndarray          # (T, B, state_dim) storage-cast
    avail_actions: jnp.ndarray  # (T, B, A, n_actions) bool
    actions: jnp.ndarray        # (T, B, A) int32
    reward: jnp.ndarray         # (T, B) float32 (train-recorded reward)
    terminated: jnp.ndarray     # (T, B) bool (env-terminal, Q7)
    last_obs: jnp.ndarray       # (B, A, obs) bootstrap step — or compact
    last_state: jnp.ndarray     # (B, state_dim)
    last_avail: jnp.ndarray     # (B, A, n_actions) bool

    @property
    def batch_size(self) -> int:
        return self.actions.shape[1]

    def to_batch(self) -> EpisodeBatch:
        """Assemble the classic ``(B, T(+1), ...)`` episode batch."""
        b, t = self.actions.shape[1], self.actions.shape[0]
        bt = lambda x: jnp.swapaxes(x, 0, 1)
        cat_last = lambda seq, last: jax.tree.map(
            lambda s, l: jnp.concatenate([bt(s), l[:, None]], axis=1),
            seq, last)
        return EpisodeBatch(
            obs=cat_last(self.obs, self.last_obs),
            state=cat_last(self.state, self.last_state),
            avail_actions=cat_last(self.avail_actions, self.last_avail),
            actions=bt(self.actions),
            reward=bt(self.reward),
            terminated=bt(self.terminated),
            filled=jnp.ones((b, t), bool),
        )


@struct.dataclass
class BufferState:
    """Ring buffer over episodes + PER priorities, all device-resident."""

    storage: EpisodeBatch       # arrays (capacity, T(+1), ...)
    insert_pos: jnp.ndarray     # () int32 — next ring slot
    episodes_in_buffer: jnp.ndarray  # () int32
    # (capacity,) float32 — stored PRE-EXPONENTIATED: p^alpha for the
    # prioritized buffer (exponentiation happens once per priority WRITE
    # — O(batch) at update, O(1) at insert — instead of over the full
    # capacity on every sample; bit-identical probabilities, same op on
    # the same inputs), raw p for the uniform buffer (which never
    # samples by priority)
    priorities: jnp.ndarray
    max_priority: jnp.ndarray   # () float32 — running max of RAW priorities


def _zeros_like_episode(n_agents: int, n_actions: int, obs_dim: int,
                        state_dim: int, t: int, batch: int,
                        store_dtype=jnp.float32,
                        compact_obs: bool = False) -> EpisodeBatch:
    if compact_obs:
        f = obs_dim // n_agents        # entity feats (entity-mode layout)
        # compact leaves stay f32 regardless of store_dtype: raw features
        # + statistics, where bf16 error would be amplified by the
        # learner's re-normalization (see ParallelRunner.obs_store)
        obs = CompactEntityObs(
            rows=jnp.zeros((batch, t + 1, n_agents, f - 1), jnp.float32),
            mec_index=jnp.zeros((batch, t + 1, n_agents), jnp.int8),
            mean=jnp.zeros((batch, t + 1, n_agents, f), jnp.float32),
            std=jnp.zeros((batch, t + 1, n_agents, f), jnp.float32),
        )
    else:
        obs = jnp.zeros((batch, t + 1, n_agents, obs_dim), store_dtype)
    return EpisodeBatch(
        obs=obs,
        state=jnp.zeros((batch, t + 1, state_dim), store_dtype),
        avail_actions=jnp.zeros((batch, t + 1, n_agents, n_actions), bool),
        actions=jnp.zeros((batch, t, n_agents), jnp.int32),
        reward=jnp.zeros((batch, t), jnp.float32),
        terminated=jnp.zeros((batch, t), bool),
        filled=jnp.zeros((batch, t), bool),
    )


@dataclasses.dataclass(frozen=True)
class ReplayBuffer:
    """Uniform episode replay (the reference's commented-out default,
    ``per_run.py:135-141``). All methods are pure: ``state' = f(state, ...)``."""

    capacity: int               # episodes (reference buffer_size)
    episode_limit: int
    n_agents: int
    n_actions: int
    obs_dim: int
    state_dim: int
    store_dtype: str = "float32"   # obs/state storage dtype (HBM budget)
    compact_obs: bool = False      # CompactEntityObs storage (entity mode)

    def __post_init__(self):
        if self.capacity < 1:
            raise ValueError(f"buffer capacity must be >= 1, got "
                             f"{self.capacity}")

    def init(self) -> BufferState:
        return BufferState(
            storage=_zeros_like_episode(
                self.n_agents, self.n_actions, self.obs_dim, self.state_dim,
                self.episode_limit, self.capacity,
                jnp.dtype(self.store_dtype), compact_obs=self.compact_obs),
            insert_pos=jnp.zeros((), jnp.int32),
            episodes_in_buffer=jnp.zeros((), jnp.int32),
            priorities=jnp.zeros((self.capacity,), jnp.float32),
            max_priority=jnp.ones((), jnp.float32),
        )

    def _ring_slots(self, state: BufferState, b: int) -> jnp.ndarray:
        """Target slots for ``b`` incoming episodes, with the shared
        capacity guard — ONE source for both insert paths (their ring
        bookkeeping must stay bit-identical: superstep K=1 parity,
        docs/SPEC.md §8)."""
        if b > self.capacity:
            # ring indices would repeat within one scatter and XLA's order
            # for duplicate indices is unspecified → arbitrary contents
            raise ValueError(
                f"insert batch of {b} episodes exceeds buffer capacity "
                f"{self.capacity}; raise replay.buffer_size above "
                f"batch_size_run")
        return (state.insert_pos + jnp.arange(b)) % self.capacity

    def _insert_priority(self, state: BufferState,
                         alpha: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """STORED priority stamped on freshly inserted episodes: the raw
        running max here; the prioritized subclass pre-exponentiates
        (one scalar pow per insert — the storage convention). ``alpha``
        (a traced scalar) overrides the static exponent — the graftpop
        per-member PER-alpha seam; ``None`` (every pre-population
        caller) is byte-identical to the static path."""
        del alpha
        return state.max_priority

    def _ring_advance(self, state: BufferState, storage: EpisodeBatch,
                      idx: jnp.ndarray, b: int,
                      alpha: Optional[jnp.ndarray] = None) -> BufferState:
        """Post-insert bookkeeping shared by both insert paths: advance
        the ring cursor/fill and stamp new episodes at the running max
        priority (standard PER; reference feeds real |TD| back after the
        first sample, Q9)."""
        return state.replace(
            storage=storage,
            insert_pos=(state.insert_pos + b) % self.capacity,
            episodes_in_buffer=jnp.minimum(
                state.episodes_in_buffer + b, self.capacity),
            priorities=state.priorities.at[idx].set(
                self._insert_priority(state, alpha)),
        )

    def insert_episode_batch(self, state: BufferState,
                             batch: EpisodeBatch,
                             alpha: Optional[jnp.ndarray] = None
                             ) -> BufferState:
        """Ring-insert ``B`` episodes; overwrites oldest when full (the
        reference's EpisodeBatch ring semantics)."""
        b = batch.batch_size
        idx = self._ring_slots(state, b)
        # cast to the ring's storage dtypes (int32-avail producers stay
        # legal; scatter dtype mismatches become hard errors in newer JAX)
        storage = jax.tree.map(
            lambda s, x: s.at[idx].set(x.astype(s.dtype)), state.storage,
            batch)
        return self._ring_advance(state, storage, idx, b, alpha)

    def insert_time_major(self, state: BufferState,
                          tm: TimeMajorEpisodes,
                          alpha: Optional[jnp.ndarray] = None
                          ) -> BufferState:
        """Ring-insert straight from the rollout scan's time-major
        emission: ONE scatter per leaf via a combined ``(slot, t)``
        index map. The former path did two scatters per (T+1)-length
        leaf (steps 0..T-1 from the scan stack, step T from the
        bootstrap) and paid a ``(T, B, ...) -> (B, T, ...)`` transpose
        of every stacked leaf to line the updates up with the ring
        layout. Here the updates stay TIME-MAJOR — the scan stack and
        the bootstrap step concatenate along the existing time axis
        (no transpose, and XLA fuses the concat into the scatter's
        update operand) — and a 2-D index grid scatters row ``(t, b)``
        straight to ring element ``(slots[b], t)`` in one writeback.
        The eliminated transpose + second scatter pass are the insert
        bytes the GP302 ratchet pins DOWN on the compiled superstep
        program. Contents are bit-identical to
        ``insert_episode_batch(state, tm.to_batch())`` — the fused
        superstep relies on that for K=1 parity."""
        b = tm.batch_size
        idx = self._ring_slots(state, b)
        t1 = self.episode_limit + 1
        # combined index map shared by every (T+1)-leaf scatter: update
        # row (t, b) lands at ring element (slots[b], t)
        t_grid = jnp.broadcast_to(jnp.arange(t1)[:, None], (t1, b))
        s_grid = jnp.broadcast_to(idx[None, :], (t1, b))

        def put_tp1(s, seq, last):
            """(cap, T+1, ...) leaf ← one scatter of the time-major
            (T+1, B, ...) updates (scan stack ++ bootstrap step)."""
            upd = jnp.concatenate([seq, last[None]], axis=0)
            return s.at[s_grid, t_grid].set(upd.astype(s.dtype))

        def put_t(s, seq):
            """(cap, T, ...) leaf ← one scatter of the time-major
            (T, B, ...) scan stack (same combined index map, first T
            rows — no transpose here either)."""
            return s.at[s_grid[:-1], t_grid[:-1]].set(seq.astype(s.dtype))

        st = state.storage
        storage = st.replace(
            obs=jax.tree.map(put_tp1, st.obs, tm.obs, tm.last_obs),
            state=put_tp1(st.state, tm.state, tm.last_state),
            avail_actions=put_tp1(st.avail_actions, tm.avail_actions,
                                  tm.last_avail),
            actions=put_t(st.actions, tm.actions),
            reward=put_t(st.reward, tm.reward),
            terminated=put_t(st.terminated, tm.terminated),
            filled=st.filled.at[idx].set(True),
        )
        return self._ring_advance(state, storage, idx, b, alpha)

    def can_sample(self, state: BufferState, batch_size: int) -> jnp.ndarray:
        return state.episodes_in_buffer >= batch_size

    def _gather(self, state: BufferState, idx: jnp.ndarray) -> EpisodeBatch:
        return jax.tree.map(lambda s: s[idx], state.storage)

    def sample(self, state: BufferState, key: jax.Array, batch_size: int,
               t_env: jnp.ndarray = 0
               ) -> Tuple[EpisodeBatch, jnp.ndarray, jnp.ndarray]:
        """→ (batch, idx, weights). Uniform without replacement (weights = 1),
        same return signature as PER so the driver is agnostic
        (``per_run.py:224``)."""
        del t_env
        n = state.episodes_in_buffer
        # top-batch_size of random scores over valid slots ≡ sampling without
        # replacement with static shapes (caller gates on can_sample)
        scores = jax.random.uniform(key, (self.capacity,))
        scores = jnp.where(jnp.arange(self.capacity) < n, scores, -jnp.inf)
        _, idx = jax.lax.top_k(scores, batch_size)
        return self._gather(state, idx), idx, jnp.ones((batch_size,))

    def update_priorities(self, state: BufferState, idx: jnp.ndarray,
                          priorities: jnp.ndarray,
                          valid: Optional[jnp.ndarray] = None,
                          alpha: Optional[jnp.ndarray] = None
                          ) -> BufferState:
        del idx, priorities, valid, alpha
        return state  # uniform: no-op


@dataclasses.dataclass(frozen=True)
class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional PER over episodes (reference ``per_run.py:143-146``):
    ``P(i) ∝ p_i^alpha``, importance weights ``(N·P(i))^-β`` normalized by
    their max, β annealed linearly from ``per_beta`` to 1 over ``t_max`` env
    steps (the ctor's ``t_max`` argument)."""

    alpha: float = 0.6
    beta0: float = 0.4
    t_max: int = 1

    def _insert_priority(self, state: BufferState,
                         alpha: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        # storage convention: stored values are pre-exponentiated, so
        # the fresh-episode stamp is max^alpha (one scalar pow per
        # insert; bit-identical to exponentiating at sample time). A
        # traced `alpha` is the graftpop per-member exponent — the same
        # pow on the same values at the config default, so the
        # population path is value-identical to the static one.
        return state.max_priority ** (self.alpha if alpha is None
                                      else alpha)

    def _probs(self, state: BufferState) -> jnp.ndarray:
        # stored values are ALREADY p^alpha (pre-exponentiated at
        # insert/update — O(batch) writes), so sampling is a masked
        # normalize instead of an O(capacity) pow every draw
        valid = jnp.arange(self.capacity) < state.episodes_in_buffer
        p = jnp.where(valid, state.priorities, 0.0)
        return p / jnp.maximum(p.sum(), 1e-12)

    def sample(self, state: BufferState, key: jax.Array, batch_size: int,
               t_env: jnp.ndarray = 0
               ) -> Tuple[EpisodeBatch, jnp.ndarray, jnp.ndarray]:
        probs = self._probs(state)
        cdf = jnp.cumsum(probs)
        # stratified inverse-CDF: one uniform per equal-mass stratum
        u = (jnp.arange(batch_size)
             + jax.random.uniform(key, (batch_size,))) / batch_size
        idx = jnp.searchsorted(cdf, u * cdf[-1], side="left")
        idx = jnp.clip(idx, 0, self.capacity - 1)

        beta = self.beta0 + (1.0 - self.beta0) * jnp.clip(
            jnp.asarray(t_env, jnp.float32) / self.t_max, 0.0, 1.0)
        n = jnp.maximum(state.episodes_in_buffer, 1).astype(jnp.float32)
        w = (n * jnp.maximum(probs[idx], 1e-12)) ** (-beta)
        w = w / jnp.maximum(w.max(), 1e-12)
        return self._gather(state, idx), idx, w

    def update_priorities(self, state: BufferState, idx: jnp.ndarray,
                          priorities: jnp.ndarray,
                          valid: Optional[jnp.ndarray] = None,
                          alpha: Optional[jnp.ndarray] = None
                          ) -> BufferState:
        """Feed RAW |TD|+1e-6 back for the sampled episodes (Q9); the
        stored form is pre-exponentiated (``p^alpha``, one O(batch) pow
        here instead of O(capacity) per sample). Duplicate indices
        resolve to one of the written values (XLA scatter), matching
        the reference's last-write-wins dict update.

        ``valid`` (optional () bool) is the non-finite guard seam: when
        False the write degenerates to the episodes' EXISTING stored
        values and the running max is untouched — value-identical to
        not updating, with no host sync and no full-ring select (the
        guard the driver used to inline; it moved here when the storage
        went pre-exponentiated, so the fallback reads stored-space
        values).

        ``alpha`` (optional traced scalar) overrides the static
        exponent — the graftpop per-member PER-alpha seam (each vmapped
        member's ring then stores ``p^alpha_i`` consistently across
        insert-stamp, feedback and sample-normalize)."""
        pa = priorities ** (self.alpha if alpha is None else alpha)
        new_max = jnp.maximum(state.max_priority, priorities.max())
        if valid is not None:
            pa = jnp.where(valid, pa, state.priorities[idx])
            new_max = jnp.where(valid, new_max, state.max_priority)
        return state.replace(
            priorities=state.priorities.at[idx].set(pa),
            max_priority=new_max,
        )
