"""Action selectors (part of M7, the unreleased controllers package).

Two modes, matching the reference's flag values (SURVEY.md §5.6
``action_selector``):

* ``epsilon_greedy`` — linear-decay epsilon over ``epsilon_anneal_time`` env
  steps; with prob ε a uniformly random *available* action, else the argmax
  over available actions. Test mode forces ε = 0 (greedy), the PyMARL
  convention this codebase forks.
* ``noisy-new`` — NoisyNet exploration (``/root/reference/transf_agent.py:37-39``):
  exploration lives in the agent's noisy output layer, so selection is pure
  greedy over available actions in both train and test mode.

Everything is a pure function of ``(key, t_env)`` — no mutable selector
object; the runner logs ``epsilon(t_env)`` directly (quirk parity with
``parallel_runner.py:217-218``).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from .schedules import DecayThenFlatSchedule

_UNAVAIL = -jnp.inf


def masked_argmax(q: jnp.ndarray, avail: jnp.ndarray) -> jnp.ndarray:
    """Greedy action over available ones; unavailable Q-values are masked to
    -inf before the argmax (the MAC masking contract, SURVEY.md §2.3 M7)."""
    return jnp.argmax(jnp.where(avail > 0, q, _UNAVAIL), axis=-1)


def random_avail(key: jax.Array, avail: jnp.ndarray) -> jnp.ndarray:
    """Uniform sample over available actions via the Gumbel trick (shape-static,
    vmap-safe — replaces torch ``Categorical(avail).sample()``)."""
    g = jax.random.gumbel(key, avail.shape)
    return jnp.argmax(jnp.where(avail > 0, g, _UNAVAIL), axis=-1)


@dataclasses.dataclass(frozen=True)
class EpsilonGreedySelector:
    schedule: DecayThenFlatSchedule

    def epsilon(self, t_env: jnp.ndarray, test_mode: bool,
                eps_scale=None) -> jnp.ndarray:
        """``eps_scale`` (optional traced scalar) multiplies the
        schedule's epsilon — the graftpop per-member exploration knob
        (``population.eps_scale``). ``None`` (every pre-population
        caller) is byte-identical; 1.0 is bitwise-neutral."""
        eps = self.schedule.eval(t_env)
        if eps_scale is not None:
            eps = eps * eps_scale
        return jnp.where(jnp.asarray(test_mode), 0.0, eps)

    def select(self, key: jax.Array, q: jnp.ndarray, avail: jnp.ndarray,
               t_env: jnp.ndarray, test_mode: bool = False,
               eps_scale=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """q, avail: ``(..., n_actions)`` → (actions ``(...)``, epsilon)."""
        eps = self.epsilon(t_env, test_mode, eps_scale)
        k_coin, k_rand = jax.random.split(key)
        explore = jax.random.uniform(k_coin, q.shape[:-1]) < eps
        actions = jnp.where(explore, random_avail(k_rand, avail),
                            masked_argmax(q, avail))
        return actions, eps


@dataclasses.dataclass(frozen=True)
class NoisySelector:
    """Greedy selection; exploration comes from the agent's NoisyLinear head."""

    schedule: DecayThenFlatSchedule  # kept so `.epsilon` still logs (always 0)

    def epsilon(self, t_env: jnp.ndarray, test_mode: bool,
                eps_scale=None) -> jnp.ndarray:
        return jnp.zeros(())

    def select(self, key: jax.Array, q: jnp.ndarray, avail: jnp.ndarray,
               t_env: jnp.ndarray, test_mode: bool = False,
               eps_scale=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
        # NoisyNet exploration lives in the q-head, so the population
        # eps knob has nothing to scale here
        del key, eps_scale
        return masked_argmax(q, avail), jnp.zeros(())


SELECTOR_REGISTRY = {
    "epsilon_greedy": EpsilonGreedySelector,
    "noisy-new": NoisySelector,
}
