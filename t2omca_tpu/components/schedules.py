"""Exploration schedules.

The reference's action selector exposes an annealed ``.epsilon`` read by the
runner for logging (``/root/reference/parallel_runner.py:217-218``); the
schedule itself is part of the unreleased controllers package (M7). PyMARL's
``DecayThenFlatSchedule`` (linear decay to a floor) is the lineage standard
and is what we pin here — expressed as a pure function of ``t_env`` so it
works under ``jit``.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DecayThenFlatSchedule:
    """Linear decay from ``start`` to ``finish`` over ``time_length`` env
    steps, flat afterwards."""

    start: float
    finish: float
    time_length: int

    def eval(self, t: jnp.ndarray) -> jnp.ndarray:
        frac = jnp.clip(t / self.time_length, 0.0, 1.0)
        return self.start + frac * (self.finish - self.start)
