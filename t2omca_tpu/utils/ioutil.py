"""Atomic JSON persistence, shared by the diagnostic writers.

Three places persist post-mortem artifacts — the graftscope flight
recorder (``obs/spans.py``), the watchdog stall diagnosis
(``utils/watchdog.py``) and the device-time attribution
(``obs/device_time.py``) — and each is written on paths (stall, crash,
hard exit) where a torn or lost file defeats the artifact's purpose.
One helper so the semantics can't drift between copies:

* tmp + flush + fsync + rename: a hard process exit (or power loss)
  racing the write never publishes a truncated JSON;
* ``default=repr``: a non-JSON value smuggled into span meta or a
  diagnosis field degrades to its repr instead of a ``TypeError``
  that silently drops the one artifact the post-mortem needs.

Raises propagate (``OSError``/``TypeError``/``ValueError``) — each
call site owns its best-effort policy (warn, or return None).
stdlib-only: the jax-free report CLI imports through here.

``read_jsonl_tolerant`` is the read-side counterpart: the post-mortem
CLIs must read past the torn final line a killed run leaves.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable


def read_jsonl_tolerant(path: str,
                        on_bad: "Callable[[int, bool], None] | None" = None
                        ) -> list:
    """Parse a JSONL file, skipping unparseable lines instead of
    raising. A run killed mid-write (crash, SIGKILL, hard watchdog
    exit) leaves exactly one torn artifact: a truncated FINAL line —
    and the post-mortem readers (``obs report``, ``obs timeline``) must
    read past it, because that torn tail is precisely the file a dead
    run leaves. ``on_bad(line_no, is_last)`` is invoked per skipped
    line (1-based; ``is_last`` distinguishes the expected torn tail
    from mid-file corruption) — callers print their own warning.
    Raises ``OSError`` only when the file itself cannot be read.

    Streams with a one-line lookahead (the ``is_last`` flag needs it)
    instead of slurping: the post-mortem CLIs read long runs'
    metrics.jsonl on exactly the constrained hosts where materializing
    the raw lines alongside the parsed events would hurt."""
    out = []

    def consume(line: str, line_no: int, is_last: bool) -> None:
        line = line.strip()
        if not line:
            return
        try:
            out.append(json.loads(line))
        except ValueError:
            if on_bad is not None:
                on_bad(line_no, is_last)

    with open(path) as f:
        prev = None
        prev_no = 0
        for i, line in enumerate(f):
            if prev is not None:
                consume(prev, prev_no, False)
            prev, prev_no = line, i + 1
        if prev is not None:
            consume(prev, prev_no, True)
    return out


def write_bytes_atomic(path: str, blob: bytes) -> str:
    """tmp + flush + fsync + rename for BINARY blobs — the twin of
    :func:`write_json_atomic` for the serve artifact's msgpack param
    variants and ``jax.export`` program blobs (serve/export.py): a
    crash mid-export must never leave a half-written blob at the final
    path for ``ServeFrontend.load`` to trust. Same unique-tmp rule as
    the JSON writer (concurrent writers of one artifact must not
    interleave), same cleanup-and-propagate error policy."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def write_json_atomic(path: str, payload: Any,
                      default: Callable[[Any], str] = repr) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # unique tmp per call: concurrent writers of the same artifact
    # (two watchdog stall callbacks run on their own threads) must not
    # interleave on a shared tmp file — a fixed name would let writer
    # B truncate A's bytes mid-write and A's rename publish the torn
    # mix, the exact failure this helper exists to rule out
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f, default=default)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path
