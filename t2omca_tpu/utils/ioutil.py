"""Atomic JSON persistence, shared by the diagnostic writers.

Three places persist post-mortem artifacts — the graftscope flight
recorder (``obs/spans.py``), the watchdog stall diagnosis
(``utils/watchdog.py``) and the device-time attribution
(``obs/device_time.py``) — and each is written on paths (stall, crash,
hard exit) where a torn or lost file defeats the artifact's purpose.
One helper so the semantics can't drift between copies:

* tmp + flush + fsync + rename: a hard process exit (or power loss)
  racing the write never publishes a truncated JSON;
* ``default=repr``: a non-JSON value smuggled into span meta or a
  diagnosis field degrades to its repr instead of a ``TypeError``
  that silently drops the one artifact the post-mortem needs.

Raises propagate (``OSError``/``TypeError``/``ValueError``) — each
call site owns its best-effort policy (warn, or return None).
stdlib-only: the jax-free report CLI imports through here.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable


def write_json_atomic(path: str, payload: Any,
                      default: Callable[[Any], str] = repr) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # unique tmp per call: concurrent writers of the same artifact
    # (two watchdog stall callbacks run on their own threads) must not
    # interleave on a shared tmp file — a fixed name would let writer
    # B truncate A's bytes mid-write and A's rename publish the torn
    # mix, the exact failure this helper exists to rule out
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f, default=default)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path
