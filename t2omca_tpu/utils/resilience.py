"""Resilience primitives: graceful-shutdown guard + fault-injection hooks.

Long-lived runs die three ways the happy-path driver cannot survive:
preemption (TPU pods get SIGTERM'd mid-iteration), torn checkpoints (a
crash mid-``save_checkpoint`` leaves a truncated ``state.msgpack`` at the
HIGHEST step, which a naive resume then selects), and numeric collapse
(one NaN loss poisons params, then every checkpoint after it). Podracer
(arxiv 2104.06272) treats preemption-safe checkpointing as table stakes;
EnvPool (arxiv 2206.10558) shows a long-running vectorized loop must
survive component faults. This module holds the two process-level pieces:

* :class:`ShutdownGuard` — installs SIGTERM/SIGINT handlers that only SET A
  FLAG; the driver loop polls it once per iteration and performs an orderly
  exit (final emergency checkpoint, resume hint, exit code 0). The handler
  itself does no I/O — async-signal-safe by construction.
* fault-injection registry (``register_fault``/``fire``) — named hook
  points inside the checkpoint writer and the driver loop where tests
  deterministically inject crashes (truncate a staged file, raise
  mid-write, deliver a signal at an exact ``t_env``). Production code calls
  ``fire(...)`` unconditionally; with nothing registered it is a dict
  lookup returning immediately.

The third piece — the non-finite guard over loss/grads — lives inside the
jitted train step (``learners/qmix_learner.py``) because it must not block
the async dispatch pipeline; the driver only counts its ``all_finite``
flags at the log cadence (``run.py``). Config knobs: ``resilience.*`` in
``config.py``; contract: ``docs/RESILIENCE.md``.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------- faults

#: hook point name -> injector callables, fired in registration order.
#:
#: The device-facing point names below (``dispatch.*``, ``fetch.*``,
#: ``collective.gather``, ``backend.init``) double as graftscope span
#: phases (``obs/spans.KNOWN_PHASES``): when ``config.ObsConfig.enabled``
#: the driver records a span around the same region each hook fires in,
#: so an injected fault/hang and its telemetry trail share one name.
#: graftlint rule GL110 keeps the two sets from drifting apart.
#:
#: Known points (each passes keyword context):
#:   ``checkpoint.staged``   dirname=<staging dir>, t_env=<int>
#:       after state.msgpack is written+fsynced into the tmp.<t_env>
#:       staging directory, BEFORE the sidecar write and atomic publish —
#:       raising here simulates a crash mid-checkpoint; truncating
#:       <dirname>/state.msgpack here simulates a torn write that still
#:       gets published (the checksum must catch it on resume).
#:   ``driver.iteration``    t_env=<int>, guard=<ShutdownGuard|None>
#:       top of every run_sequential iteration — deliver a signal or trip
#:       the guard at an exact env-step.
#:   ``dispatch.superstep``  t_env=<int>, attempt=<int>, k=<int>
#:       before EACH attempt of the fused K-iteration dispatch (run.py
#:       `_dispatch`) — sleep here to simulate a hung dispatch (the
#:       watchdog must fire), raise a transient-classified error to
#:       exercise retry/backoff and the degradation ladder.
#:   ``dispatch.rollout`` / ``dispatch.train``   t_env=<int>, attempt=<int>
#:       same, for the classic three-program loop's two dispatches.
#:   ``dispatch.test``       t_env=<int>, attempt=<int>
#:       same, for each test-cadence evaluation rollout.
#:   ``dispatch.wait``       t_env=<int>
#:       before the run-ahead ``block_until_ready`` — the steady-state
#:       blocking point where async device faults surface when
#:       per-stage sync is off; transient errors route to the ladder's
#:       restore rung (no in-place retry is possible at a sync point).
#:   ``fetch.train_infos``   t_env=<int>
#:       before the log-cadence device→host fetch of the accumulated
#:       train-info rows (non-finite flags + last stats row) — same
#:       sync-point routing as ``dispatch.wait``.
#:   ``fetch.train_stats`` / ``fetch.test_stats``   t_env=<int>
#:       before each StatsAccumulator device fetch (the per-push fold
#:       and the runner-log / test-quota flushes) — same sync-point
#:       routing as ``dispatch.wait``.
#:   ``collective.gather``   t_env=<int>, multihost=<bool>
#:       inside save_checkpoint's retried gather-to-host step (before the
#:       multi-host process_allgather sequence, or before the
#:       single-process device_get) — raise to simulate a dropped/flaky
#:       collective; the driver's save cadence retries transient errors.
#:   ``backend.init``        attempt=<int>
#:       inside each retried jax.distributed.initialize attempt
#:       (parallel/distributed.py) — raise a transient error to exercise
#:       the init retry that de-flakes the gloo rendezvous.
#:   ``actor.dispatch``      t_env=<int>, attempt=<int>
#:       before EACH attempt of the sebulba actor thread's rollout
#:       dispatch (run.run_sebulba) — sleep to simulate a wedged actor
#:       mesh (the actor-side watchdog fires, trips the guard, and the
#:       learner exits resumably); raise transient to exercise the
#:       actor-side retry and the actor-failure→ladder handoff.
#:   ``learner.dispatch``    t_env=<int>, attempt=<int>
#:       same, for the sebulba learner thread's sample→train→priority
#:       dispatch — sleep here for the wedged-learner chaos scenario
#:       (watchdog fires while the actor thread exits resumably).
#:   ``queue.put`` / ``queue.get``   t_env=<int>
#:       at the trajectory queue's two ends (actor-side d2d copy + slot
#:       scatter / learner-side slot gather + ring insert) — raise to
#:       exercise the queue boundaries' failure surfacing. These wait
#:       under backpressure/starvation by design, so they carry spans
#:       but no watchdog stamp (a full/empty queue is idleness, not a
#:       stall).
#:   ``params.sync``         t_env=<int>
#:       at the learner→actor parameter publish (learner side, stamped)
#:       and the actor's staleness-bounded adopt wait (span only).
#:   ``fleet.dispatch``      engine=<int>, attempt=<int>, rid=<int>
#:       inside EACH attempt of a fleet engine's per-request dispatch
#:       (serve/fleet.py), under the engine's own watchdog stamp —
#:       sleep to simulate a wedged engine (quarantine + hedge +
#:       restart), raise transient to exercise the in-place retry,
#:       raise non-transient to kill the engine outright.
#:   ``fleet.selfcheck``     engine=<int>, stage=<str>
#:       inside the engine health-check dispatch (start / restart /
#:       degrade / refresh stages) — raise at stage="refresh" to trip
#:       the post-swap health check and force the rolling refresh's
#:       auto-rollback.
#:   ``fleet.refresh``       stage=<str>, ...
#:       at the hot-refresh fold (stage="fold", ckpt=) and per-bucket
#:       fingerprint check (stage="fingerprint", bucket=, fingerprint=)
#:       — raise at "fold" to poison a refresh (must be REFUSED while
#:       the fleet keeps serving).
#:   ``preempt.barrier``     t_env=<int>, processes=<int>
#:       inside the coordinated-preemption stop-step negotiation
#:       (parallel/distributed.negotiate_stop_step), before the bounded
#:       KV-store barrier — raise to simulate a peer dying
#:       mid-negotiation; the driver must degrade to the per-host
#:       shard save instead of attempting a collective emergency save.
#:   ``checkpoint.shard_save``   t_env=<int>, shard=<int>, shards=<int>
#:       at the top of the degraded per-host shard write
#:       (utils/checkpoint.save_checkpoint_shards) — raise to kill the
#:       fallback save itself; the driver's exit path must survive and
#:       leave the last cadence checkpoint as the resume point.
#:   ``checkpoint.elastic``  dirname=<str>, format=<int|None>
#:       inside restore_elastic after the (verified) host read, before
#:       any topology reshape or device placement — raise to fault the
#:       elastic resume boundary (docs/RESILIENCE.md §6).
_FAULTS: Dict[str, List[Callable]] = {}


def register_fault(point: str, fn: Callable) -> None:
    """Register ``fn(**context)`` to run whenever ``point`` fires.

    Test-only by intent: nothing in the production config path registers
    injectors. Injectors run inline in the faulting thread and may raise —
    that IS the fault."""
    _FAULTS.setdefault(point, []).append(fn)


def clear_faults(point: Optional[str] = None) -> None:
    """Drop all injectors (or just ``point``'s). Tests pair this with
    ``register_fault`` in a fixture finalizer so faults never leak."""
    if point is None:
        _FAULTS.clear()
    else:
        _FAULTS.pop(point, None)


def fire(point: str, **context) -> None:
    """Run every injector registered for ``point``. No-op (one dict
    lookup) when nothing is registered — safe on hot paths."""
    for fn in _FAULTS.get(point, ()):
        fn(**context)


# ---------------------------------------------------------------- shutdown

class ShutdownGuard:
    """Flag-based SIGTERM/SIGINT latch for the driver loop.

    Usage::

        with ShutdownGuard.install() as guard:
            while training:
                if guard.triggered:
                    break          # orderly: emergency checkpoint + exit 0
                ...

    The handler records WHICH signal fired (``guard.signame``) and sets a
    ``threading.Event`` — nothing else, so it is safe at any interrupt
    point. A second delivery of the same signal while shutdown is already
    in progress re-raises the default behavior (operator escalation:
    kill -TERM twice = die now), so a wedged emergency checkpoint cannot
    make the process unkillable.

    Signal handlers are process-global and main-thread-only; ``install``
    degrades gracefully (returns a guard with ``installed == False``) when
    called off the main thread, where ``triggered`` can still be tripped
    programmatically via :meth:`request` (fault injection uses this).
    """

    def __init__(self) -> None:
        self._event = threading.Event()
        self._prev: Dict[int, object] = {}
        self.signame: Optional[str] = None
        self.installed = False

    # -- construction ----------------------------------------------------

    @classmethod
    def install(cls, signals=(signal.SIGTERM, signal.SIGINT)
                ) -> "ShutdownGuard":
        guard = cls()
        for s in signals:
            try:
                guard._prev[s] = signal.signal(s, guard._handler)
            except ValueError:
                # not the main thread (or an unsupported signal on this
                # platform): signal.signal refuses — run guarded-by-flag
                # only, preemption falls back to the default disposition
                logger.warning(
                    "ShutdownGuard: cannot install handler for %s "
                    "(not the main thread?) — graceful shutdown limited "
                    "to programmatic request()", signal.Signals(s).name)
                continue
            guard.installed = True
        return guard

    def _handler(self, signum, frame) -> None:
        if self._event.is_set():
            # escalation: restore default dispositions so the NEXT signal
            # (or this one re-raised) terminates immediately
            self.uninstall()
            signal.raise_signal(signum)
            return
        self.signame = signal.Signals(signum).name
        self._event.set()

    # -- queries / control ----------------------------------------------

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def request(self, name: str = "request") -> None:
        """Trip the guard without a real signal (fault injection, tests,
        or an in-process watchdog)."""
        self.signame = self.signame or name
        self._event.set()

    def uninstall(self) -> None:
        """Restore the previous handlers (idempotent)."""
        prev, self._prev = self._prev, {}
        for s, h in prev.items():
            try:
                signal.signal(s, h)
            except (ValueError, TypeError):
                pass
        self.installed = False

    # -- context manager -------------------------------------------------

    def __enter__(self) -> "ShutdownGuard":
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()
