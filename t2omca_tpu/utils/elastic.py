"""graftmorph — topology-elastic resume routing (docs/RESILIENCE.md §6).

A checkpoint written by one topology (device count, dp split, loop shape,
population size, host count) must restore into whatever topology the
CURRENT run has: a preempted pod slice comes back smaller, a resized
reservation comes back larger, a config change flips classic↔Sebulba or
resizes the population. The on-disk state is already topology-free — a
complete save holds the GLOBAL state-dict and a partial (per-host shard)
save reassembles into one (``utils/checkpoint.py``) — so elasticity is a
ROUTING problem: read the ``meta.json`` topology stamp, compare it with
the current run's shape, and pick the restore path that reshapes what
actually differs instead of crashing deep inside ``from_state_dict``.

This module is that router. ``utils/checkpoint.py`` owns the mechanics
(:func:`~t2omca_tpu.utils.checkpoint.restore_elastic`, the shard
write/assembly, the ``_reshape_population`` shim); here lives the
driver-facing surface:

* :func:`current_topology` — the CURRENT run's stamp, the same shape
  ``save_checkpoint`` writes (so stamp comparison is symmetric);
* :func:`topology_mismatch` — the human-readable diff between a saved
  stamp and the current one (empty = same shape or unknown/pre-stamp
  checkpoint);
* :func:`resume_state` — the routing decision itself: same-shape resumes
  keep the rigid fast paths bit-for-bit (``load_checkpoint`` /
  ``load_checkpoint_sharded``); a population resize or a
  population↔classic flip routes through ``restore_elastic``; a
  stampless checkpoint that fails the rigid path structurally falls back
  to the elastic path once before giving up.

Device-count and loop-shape changes need no data movement at all — the
driver builds its templates/shardings for the CURRENT mesh and the
restore places each leaf under them (leaf-streamed, ADVICE r5) — so
those mismatches are logged, not special-cased.
"""

from __future__ import annotations

import logging
from typing import Any, List, Optional, Sequence, Tuple

from . import checkpoint as _ckpt

logger = logging.getLogger(__name__)

#: stamp keys compared host-side; everything else in the stamp is
#: informational (mesh/sebulba details vary freely under placement)
_COMPARED_KEYS = ("population", "device_count", "process_count", "loop")


def current_topology(state: Any, loop: Optional[str] = None,
                     mesh_shape: Optional[Sequence[int]] = None,
                     sebulba: Optional[dict] = None,
                     member_ranking: Optional[Sequence[int]] = None
                     ) -> dict:
    """The CURRENT run's topology stamp — the dict ``save_checkpoint``
    writes into ``meta.json`` (``topology=``) and ``resume_state``
    compares against. ``state`` may be concrete or an eval_shape
    template (only shapes are read). ``loop`` names the driver shape
    (``"classic"`` / ``"sebulba"``); ``member_ranking`` (best member
    first, from the host EMA return stats when they exist) is what a
    later shrink keeps."""
    extra: dict = {}
    if loop is not None:
        extra["loop"] = loop
    if mesh_shape is not None:
        extra["mesh_shape"] = [int(x) for x in mesh_shape]
    if sebulba is not None:
        extra["sebulba"] = sebulba
    if member_ranking is not None:
        extra["member_ranking"] = [int(m) for m in member_ranking]
    return _ckpt._topology_stamp(state, extra)


def topology_mismatch(saved: Optional[dict],
                      current: dict) -> List[str]:
    """Human-readable differences between a checkpoint's stamp and the
    current run's — empty when the shapes agree OR the checkpoint
    predates the stamp (pre-graftmorph saves carry none; unknown is NOT
    a mismatch, the rigid path must keep working on old checkpoints).
    Only keys present in BOTH stamps compare — a stamp written without a
    ``loop`` entry says nothing about loop shape."""
    if not saved:
        return []
    diffs = []
    for key in _COMPARED_KEYS:
        if key in saved and key in current and saved[key] != current[key]:
            diffs.append(f"{key}: saved {saved[key]!r} -> "
                         f"current {current[key]!r}")
    return diffs


def _needs_elastic(saved: Optional[dict], current: dict) -> bool:
    """True when the RAW STATE itself must be reshaped — today that is
    exactly a population mismatch (P resize, or population↔classic).
    Device/process/loop changes are placement-only: the rigid sharded
    path already streams leaves onto the current mesh."""
    if not saved or "population" not in saved:
        return False
    return saved["population"] != current.get("population")


def resume_state(dirname: str, template: Any, shardings: Any = None,
                 verify: bool = True,
                 topology: Optional[dict] = None,
                 member_ranking: Optional[Sequence[int]] = None
                 ) -> Tuple[Any, bool]:
    """Restore ``dirname`` into the CURRENT topology → ``(state,
    used_elastic)`` — the driver's one resume entry point.

    Same-shape resumes take the EXACT rigid paths that existed before
    graftmorph (``load_checkpoint_sharded`` when ``shardings`` is given,
    else ``load_checkpoint``) — bit-for-bit unchanged behavior, no
    elastic hook fired. A stamped population mismatch routes through
    :func:`~t2omca_tpu.utils.checkpoint.restore_elastic`; any other
    stamped difference (device count, host count, loop shape) is logged
    and handled by placement alone. A STAMPLESS checkpoint that fails
    the rigid path with a structural error gets one elastic retry — the
    pre-stamp analog of detection — before the original error
    semantics apply."""
    meta = _ckpt._read_meta(dirname)
    saved = (meta or {}).get("topology")
    current = _ckpt._topology_stamp(template, topology)
    diffs = topology_mismatch(saved, current)
    if _needs_elastic(saved, current):
        logger.warning(
            "resume_state: topology changed since %s was written (%s) — "
            "routing through restore_elastic (docs/RESILIENCE.md §6)",
            dirname, "; ".join(diffs))
        return _ckpt.restore_elastic(
            dirname, template, shardings=shardings, verify=verify,
            member_ranking=member_ranking), True
    if diffs:
        logger.info(
            "resume_state: placement-only topology change for %s (%s) — "
            "leaves stream onto the current mesh, no reshape needed",
            dirname, "; ".join(diffs))
    try:
        if shardings is not None:
            return _ckpt.load_checkpoint_sharded(
                dirname, template, shardings, verify=verify), False
        return _ckpt.load_checkpoint(dirname, template,
                                     verify=verify), False
    except ValueError as e:
        if saved is not None:
            raise                    # stamped + same shape: a real
            #                          config mismatch, not elasticity
        logger.warning(
            "resume_state: rigid restore of stampless checkpoint %s "
            "failed structurally (%s) — retrying through "
            "restore_elastic once", dirname, e)
        return _ckpt.restore_elastic(
            dirname, template, shardings=shardings, verify=verify,
            member_ranking=member_ranking), True
