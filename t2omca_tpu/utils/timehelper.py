"""ETA estimation and human-readable durations (M10).

Contract from the call sites (``/root/reference/per_run.py:9,207-208,246-251``):
``time_left(last_time, last_T, t_current, t_max)`` extrapolates remaining
wall-clock from the recent rate; ``time_str(seconds)`` renders a duration.
"""

from __future__ import annotations

import time


def time_str(s: float) -> str:
    """Seconds → ``Xd Xh Xm Xs`` (largest nonzero units)."""
    s = int(s)
    days, s = divmod(s, 86400)
    hours, s = divmod(s, 3600)
    minutes, s = divmod(s, 60)
    out = []
    if days:
        out.append(f"{days}d")
    if hours or days:
        out.append(f"{hours}h")
    if minutes or hours or days:
        out.append(f"{minutes}m")
    out.append(f"{s}s")
    return " ".join(out)


def time_left(start_time: float, t_start: int, t_current: int,
              t_max: int) -> str:
    """Extrapolated remaining time from the rate since ``start_time``."""
    if t_current >= t_max:
        return "-"
    elapsed = time.time() - start_time
    if t_current <= t_start or elapsed <= 0:
        return "?"
    rate = (t_current - t_start) / elapsed
    return time_str((t_max - t_current) / rate)
