"""Dispatch watchdog, retry/backoff, and the degradation ladder.

The fused superstep (docs/SPEC.md §8) concentrates all progress into one
long XLA dispatch per K iterations, and under a remote-tunnel backend
that dispatch can *hang* rather than fail: a wedged tunnel blocks the
dispatching thread inside C++ for tens of minutes (BASELINE.md measured
~25 min inside backend init alone) — longer than any scheduler's
preemption grace, so the run dies with nothing on disk and no diagnosis.
Podracer-style loops (arxiv 2104.06272) assume the driver can detect a
starved accelerator; this module supplies the three host-side pieces the
driver (``run.run_sequential``) composes around every device-facing
boundary:

* :class:`Watchdog` — a heartbeat monitor. The driver stamps a phase
  before each dispatch / collective / checkpoint gather and clears it
  when the call returns; a daemon thread fires once per armed stamp that
  outlives ``timeout_s``, capturing a :class:`StallDiagnosis` (phase,
  t_env, elapsed, backend) and invoking ``on_stall`` — the driver's
  callback writes an emergency checkpoint from the stamped (pre-dispatch,
  still-consistent) state, persists the diagnosis, and trips the
  ShutdownGuard so the loop exits orderly if the stalled call ever
  returns. If it never does, an optional hard-exit stage terminates the
  process after ``grace_s`` with a distinctive exit code — the supervisor
  restarts and resume picks the emergency checkpoint.
* :func:`retry_call` — bounded attempts with exponential backoff +
  jitter, gated on :func:`is_transient` error classification (gloo
  ``EnforceNotMet``, connection resets, rendezvous timeouts, ...).
  Deterministic errors (shape bugs, config mistakes) propagate on the
  first attempt — retrying those only delays the real diagnosis.
* :class:`DegradationLadder` — the escalation policy for dispatch
  failures that survive in-place retries: shrink the blast radius
  (superstep K→1, so a preemption or the next failure loses ≤1
  iteration), then restore the last good checkpoint, then abort with the
  captured diagnosis. Config knobs: ``resilience.*`` (config.py);
  contract: docs/RESILIENCE.md §5.

Everything here is host-side and jit-free; tests drive it with
millisecond timeouts on CPU (tests/test_watchdog.py, tests/test_chaos.py).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import threading
import time
from typing import Any, Callable, Optional

from .ioutil import write_json_atomic

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------- errors


class DispatchFailed(RuntimeError):
    """A device-facing dispatch failed and exhausted its in-place retries
    (or could not be retried because its donated inputs were already
    consumed). Carries what the degradation ladder needs to pick a rung
    and what the final abort diagnosis reports."""

    def __init__(self, phase: str, attempts: int, cause: BaseException):
        super().__init__(
            f"dispatch {phase!r} failed after {attempts} attempt(s): "
            f"{type(cause).__name__}: {cause}")
        self.phase = phase
        self.attempts = attempts
        self.cause = cause


# ---------------------------------------------------------------- retry

#: substrings (lowercased, matched against ``TypeName: message``) that mark
#: an error as plausibly transient — worth a bounded retry. Collected from
#: the failure modes this repo has actually hit (CHANGES.md): the gloo
#: ``EnforceNotMet`` preamble-size crash on the 2-process CPU transport,
#: coordinator rendezvous races, dropped remote-tunnel connections.
TRANSIENT_PATTERNS = (
    "enforcenotmet",            # gloo transport assertion (jaxlib CPU collectives)
    "gloo",
    "connection",               # reset / refused / aborted
    "broken pipe",
    "reset by peer",
    "socket",
    "timed out",
    "timeout",
    "deadline",
    "unavailable",
    "temporarily",
    "rendezvous",
    "barrier",
    "preempt",
    "resource exhausted",
    "too many open files",
)


def is_transient(exc: BaseException) -> bool:
    """Heuristic retriable-error classification. Connection/timeout OS
    errors are transient by type; everything else by message substring
    (XLA surfaces backend faults as ``XlaRuntimeError`` with the
    transport's text inside). Interrupts/exits are never transient —
    callers only catch ``Exception``, but guard anyway."""
    if isinstance(exc, (KeyboardInterrupt, SystemExit)):
        return False
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError,
                        BrokenPipeError)):
        return True
    msg = f"{type(exc).__name__}: {exc}".lower()
    return any(p in msg for p in TRANSIENT_PATTERNS)


def backoff_delay(attempt: int, base_s: float, mult: float = 2.0,
                  max_s: float = 30.0, jitter: float = 0.25,
                  _random: Callable[[], float] = random.random) -> float:
    """Exponential backoff for 1-based ``attempt`` with multiplicative
    jitter in ``[0, jitter]`` — the jitter decorrelates peers retrying the
    same shared resource (coordinator, tunnel, filesystem) in lockstep."""
    delay = min(base_s * (mult ** max(attempt - 1, 0)), max_s)
    return delay * (1.0 + jitter * _random())


def retry_call(fn: Callable[[], Any], *, attempts: int = 3,
               backoff_s: float = 0.5, backoff_mult: float = 2.0,
               max_backoff_s: float = 30.0, jitter: float = 0.25,
               retriable: Callable[[BaseException], bool] = is_transient,
               label: str = "", sleep: Callable[[float], None] = time.sleep
               ) -> Any:
    """Call ``fn()`` with up to ``attempts`` tries. Non-retriable errors
    (per ``retriable``) and the final failure propagate unmodified —
    callers keep their existing except clauses. ``sleep`` is injectable so
    tests assert the backoff sequence without waiting it out."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except Exception as e:          # noqa: BLE001 — classified below
            if attempt >= attempts or not retriable(e):
                raise
            delay = backoff_delay(attempt, backoff_s, backoff_mult,
                                  max_backoff_s, jitter)
            logger.warning(
                "%s: transient failure (attempt %d/%d), retrying in "
                "%.2fs: %s: %s", label or getattr(fn, "__name__", "call"),
                attempt, attempts, delay, type(e).__name__, e)
            sleep(delay)


def state_intact(state: Any) -> bool:
    """True iff no jax.Array leaf of ``state`` has been deleted. A failed
    dispatch whose donated inputs were already consumed cannot be retried
    in place — the ladder must go straight to the restore rung."""
    import jax
    return not any(x.is_deleted() for x in jax.tree.leaves(state)
                   if isinstance(x, jax.Array))


# ---------------------------------------------------------------- watchdog


@dataclasses.dataclass
class StallDiagnosis:
    """What the watchdog knew when it fired — everything a post-mortem (or
    the abort message) needs to locate the hang without a debugger."""

    phase: str                  # the stamped boundary (e.g. dispatch.superstep)
    t_env: int                  # driver env-step cursor at the stamp
    elapsed_s: float            # how long the call had been in flight
    timeout_s: float            # the configured resilience.dispatch_timeout
    backend: str                # jax.default_backend() ("cpu"/"tpu"/...)
    state: Any = None           # pre-dispatch TrainState snapshot (not serialized)

    def to_dict(self) -> dict:
        return {"phase": self.phase, "t_env": self.t_env,
                "elapsed_s": round(self.elapsed_s, 3),
                "timeout_s": self.timeout_s, "backend": self.backend}

    def message(self) -> str:
        return (f"stalled dispatch: phase={self.phase} t_env={self.t_env} "
                f"elapsed={self.elapsed_s:.1f}s "
                f"(resilience.dispatch_timeout={self.timeout_s}s, "
                f"backend={self.backend})")


def write_diagnosis(diag: StallDiagnosis, dirname: str,
                    extra: Optional[dict] = None) -> Optional[str]:
    """Persist ``dirname/stall_diagnosis.json`` (best-effort: diagnosis
    must never be the thing that crashes the diagnostic path).
    ``extra`` is merged into the payload — the driver passes the
    graftscope flight-recorder tail as ``recent_spans`` (the hanging
    span last, docs/OBSERVABILITY.md), so a wedged run's causal trail
    lands in the same file as its diagnosis. Written via
    ``write_json_atomic`` (tmp + fsync + rename, ``default=repr``): a
    hard exit racing the write must not publish a torn JSON, and a
    non-JSON span-meta value must not cost the whole diagnosis."""
    try:
        payload = diag.to_dict()
        if extra:
            payload.update(extra)
        return write_json_atomic(
            os.path.join(dirname, "stall_diagnosis.json"), payload)
    except (OSError, TypeError, ValueError) as e:  # pragma: no cover
        logger.warning("could not persist stall diagnosis: %s", e)
        return None


class Watchdog:
    """Heartbeat monitor for device-facing calls.

    Usage (the driver's shape)::

        wd = Watchdog(timeout_s=cfg.resilience.dispatch_timeout,
                      on_stall=_emergency_exit)
        wd.start()
        ...
        with wd.watch("dispatch.superstep", t_env=t_env, state=ts):
            ts, stats, infos = superstep(ts, keys, t0)
        ...
        wd.stop()

    ``stamp`` arms a deadline; ``clear`` disarms it — while no stamp is
    armed (host-side bookkeeping between dispatches) the watchdog never
    fires, so a slow *host* (logging to a wedged NFS, say) is not
    misdiagnosed as a stalled *device*. The monitor thread fires **once
    per armed stamp**: it records the :class:`StallDiagnosis` and runs
    ``on_stall(diag)`` on a dedicated daemon thread (the stalled main
    thread cannot run anything, and the monitor itself must keep
    watching — a callback wedged inside the stalled backend must not
    blind it to later stalls). If ``grace_s > 0`` and the main thread still
    has not progressed past the stamped call ``grace_s`` seconds after
    the fire, ``_exit(exit_code)`` terminates the process — the escape
    hatch for a dispatch that never returns, sized so a supervisor
    restart + checkpoint resume beats waiting out the hang. ``_exit`` is
    injectable for tests (default ``os._exit``: a wedged C++ call ignores
    normal interpreter shutdown).

    **Compile exemption.** The FIRST occurrence of each phase includes
    the XLA compile — tens of seconds on CPU tests, minutes at
    production shapes — so ``timeout_s`` only applies to a phase once a
    previous occurrence has completed cleanly (its warm steady-state is
    then the thing being bounded). Until that first completion the
    deadline is ``first_timeout_s`` (0 = unbounded: compile times are
    config-dependent and an operator who wants startup hangs bounded —
    the wedged-tunnel-at-init shape — sets
    ``resilience.first_dispatch_timeout`` explicitly).
    """

    def __init__(self, timeout_s: float,
                 on_stall: Optional[Callable[[StallDiagnosis], None]] = None,
                 poll_s: Optional[float] = None, grace_s: float = 0.0,
                 exit_code: int = 17, first_timeout_s: float = 0.0,
                 _exit: Callable[[int], None] = os._exit) -> None:
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0 (0 disables the "
                             f"watchdog at the config layer), got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.first_timeout_s = float(first_timeout_s)
        self.grace_s = float(grace_s)
        self.exit_code = int(exit_code)
        self.on_stall = on_stall
        # poll fast enough that 'fires within the configured timeout'
        # means within ~1.25x of it even at millisecond test timeouts
        self.poll_s = poll_s if poll_s else min(max(timeout_s / 4.0, 0.005),
                                                1.0)
        self._exit = _exit
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # armed stamp: (generation, phase, t_env, state, monotonic since)
        self._gen = 0
        self._beat = time.monotonic()   # last stamp/clear (pulse telemetry)
        self._armed: Optional[tuple] = None
        self._fired_gen = -1
        self._completed: set = set()    # phases with ≥1 clean completion
        self.diagnosis: Optional[StallDiagnosis] = None
        self.stall_count = 0

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Watchdog":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="t2omca-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Disarm and stop the monitor (also cancels a pending hard
        exit). Idempotent; safe from any thread."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2 * self.poll_s + 1.0)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- heartbeat -------------------------------------------------------

    def stamp(self, phase: str, t_env: int = 0, state: Any = None) -> None:
        """Arm the deadline for one device-facing call. ``state`` is the
        pre-call train state — what the emergency checkpoint saves if this
        call stalls (pass None when no consistent state exists)."""
        with self._lock:
            self._gen += 1
            self._beat = time.monotonic()
            self._armed = (self._gen, phase, int(t_env), state,
                           self._beat)

    def clear(self, completed: bool = True) -> None:
        """Disarm (the call returned). Drops the state reference.
        ``completed=True`` (a clean return, not an exception) marks the
        phase warm: ``timeout_s`` applies to its next occurrences instead
        of the compile-exempt ``first_timeout_s``."""
        with self._lock:
            if completed and self._armed is not None:
                self._completed.add(self._armed[1])
            self._gen += 1
            self._beat = time.monotonic()
            self._armed = None

    def watch(self, phase: str, t_env: int = 0, state: Any = None):
        """Context manager: ``stamp`` on entry, ``clear`` on exit."""
        return _Watch(self, phase, t_env, state)

    def heartbeat(self) -> dict:
        """Live telemetry snapshot for the pulse plane (obs/pulse.py,
        docs/OBSERVABILITY.md §pulse): the armed phase and how long its
        call has been in flight, the age of the last heartbeat (any
        stamp or clear), and the cumulative stall count. Read-only and
        lock-bounded — safe from the HTTP scrape thread while the main
        thread is wedged inside the armed call (that is the read the
        endpoint exists for)."""
        now = time.monotonic()
        with self._lock:
            armed = self._armed
            out = {"armed_phase": armed[1] if armed is not None else None,
                   "armed_s": (round(now - armed[4], 3)
                               if armed is not None else 0.0),
                   "beat_age_s": round(now - self._beat, 3),
                   "stall_count": self.stall_count}
        return out

    def take_diagnosis(self) -> Optional[StallDiagnosis]:
        """Consume the latest stall diagnosis (None if none fired).
        Called by the driver loop once it regains control."""
        with self._lock:
            d, self.diagnosis = self.diagnosis, None
            return d

    # -- monitor thread --------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                armed = self._armed
                if armed is None:
                    continue
                gen, phase, t_env, state, since = armed
                # compile exemption: a phase that has never completed is
                # (probably) compiling — bound it by first_timeout_s only
                limit = (self.timeout_s if phase in self._completed
                         else self.first_timeout_s)
                elapsed = time.monotonic() - since
                if limit <= 0 or elapsed < limit or gen == self._fired_gen:
                    continue
                self._fired_gen = gen
                timeout_used = limit
            # build + publish outside the lock: on_stall may checkpoint
            import jax
            diag = StallDiagnosis(phase=phase, t_env=t_env,
                                  elapsed_s=elapsed,
                                  timeout_s=timeout_used,
                                  backend=jax.default_backend(),
                                  state=state)
            with self._lock:
                self.diagnosis = diag
                self.stall_count += 1
            logger.error("watchdog: %s", diag.message())
            # arm the hard-exit timer BEFORE the callback: on_stall's
            # emergency checkpoint reads device state over the possibly
            # wedged backend and can itself hang without raising — a
            # sequential grace timer would then never start and the
            # process would stall unbounded, the exact failure this
            # watchdog exists to bound
            if self.grace_s > 0:
                threading.Thread(target=self._maybe_hard_exit,
                                 args=(gen,), daemon=True,
                                 name="t2omca-watchdog-grace").start()
            if self.on_stall is None:
                diag.state = None       # nothing will consume it
            else:
                # the callback runs on its OWN daemon thread: its
                # emergency checkpoint reads device state over the
                # possibly wedged backend and can block indefinitely
                # without raising — run inline it would blind this
                # monitor to every later stall in the run (the stalled
                # call can return after ~25 min, the main thread wedge
                # again at the next stamp, and nothing would fire: no
                # diagnosis, no guard trip, no grace timer)
                threading.Thread(target=self._run_on_stall, args=(diag,),
                                 daemon=True,
                                 name="t2omca-watchdog-stall").start()

    def _run_on_stall(self, diag: StallDiagnosis) -> None:
        try:
            self.on_stall(diag)
        except Exception:               # noqa: BLE001 — diagnostics only
            logger.exception("watchdog on_stall callback failed")
        finally:
            # only the callback (the emergency save) needs the stamped
            # state; the retained diagnosis serves to_dict()/message()
            # consumers — keeping the reference would pin the
            # pre-stall TrainState (device ring included) through the
            # recovery and exit paths
            diag.state = None

    def _maybe_hard_exit(self, fired_gen: int) -> None:
        """Stage 2 (own thread, armed before ``on_stall`` runs): the
        stalled call never returned. Wait ``grace_s`` for the main thread
        to progress (any stamp/clear bumps the generation); if it never
        does, terminate the process so the supervisor can restart into a
        checkpoint resume."""
        if self.grace_s <= 0:
            return
        deadline = time.monotonic() + self.grace_s
        step = min(self.poll_s, 0.05)
        while time.monotonic() < deadline:
            if self._stop.wait(step):
                return                  # orderly exit reached wd.stop()
            with self._lock:
                if self._gen != fired_gen:
                    return              # main thread progressed
        # final re-check: the loop can expire on the clock before its
        # next poll observes a recovery that landed in the last window —
        # killing a run mid-orderly-exit would abandon the in-progress
        # exit checkpoint as a staged tmp dir
        if self._stop.is_set():
            return
        with self._lock:
            if self._gen != fired_gen:
                return
        logger.critical(
            "watchdog: stalled call never returned within the %.1fs grace "
            "after diagnosis — hard process exit (%d); resume from the "
            "emergency checkpoint", self.grace_s, self.exit_code)
        self._exit(self.exit_code)


class _Watch:
    """Re-entrant-free stamp/clear pair (plain class: contextmanager
    generators hold frames the watchdog thread would race)."""

    __slots__ = ("_wd", "_phase", "_t_env", "_state")

    def __init__(self, wd: Watchdog, phase: str, t_env: int, state: Any):
        self._wd, self._phase, self._t_env, self._state = (wd, phase,
                                                           t_env, state)

    def __enter__(self) -> None:
        self._wd.stamp(self._phase, self._t_env, self._state)

    def __exit__(self, exc_type, *exc) -> None:
        # an exception is not a completion: the phase stays compile-exempt
        # until one occurrence actually returns (an injected failure on
        # attempt 1 must not arm the warm timeout over attempt 2's compile)
        self._wd.clear(completed=exc_type is None)
        self._state = None


class ExitDeadline:
    """Hard wall-clock bound over a region of the EXIT path (plain class,
    same reason as :class:`_Watch`). The preemption/stall exit runs after
    ``wd.stop()`` — no stamp, no grace timer — yet its emergency save
    still reads device state over the possibly-wedged backend and can
    block without raising; with nothing left to bound it, the run would
    hang inside its own exit path, the exact failure this module exists
    to bound. A daemon timer terminates the process with the stall exit
    code if the region has not completed within ``bound_s`` — resume
    falls back to the newest published checkpoint."""

    __slots__ = ("_bound_s", "_exit_code", "_label", "_exit_fn", "_done")

    def __init__(self, bound_s: float, exit_code: int, *,
                 label: str = "exit path",
                 _exit: Callable[[int], None] = os._exit) -> None:
        self._bound_s = float(bound_s)
        self._exit_code = int(exit_code)
        self._label = label
        self._exit_fn = _exit
        self._done = threading.Event()

    def _run(self) -> None:
        if self._done.wait(self._bound_s):
            return
        logger.critical(
            "%s did not complete within its %.1fs bound (wedged "
            "backend?) — hard process exit (%d); resume falls back to "
            "the newest published checkpoint", self._label,
            self._bound_s, self._exit_code)
        self._exit_fn(self._exit_code)

    def __enter__(self) -> "ExitDeadline":
        threading.Thread(target=self._run, daemon=True,
                         name="t2omca-exit-deadline").start()
        return self

    def __exit__(self, *exc) -> None:
        self._done.set()


# ---------------------------------------------------------------- ladder


class DegradationLadder:
    """Escalation policy for dispatches that exhausted in-place retries.

    Rung order (docs/RESILIENCE.md §5): **degrade** — drop superstep K→1
    so each dispatch risks one iteration instead of K (only once, and only
    when the fused path is active); **restore** — reload the last good
    checkpoint (up to ``max_restores`` times); **abort** — surface the
    captured diagnosis. Counters are cumulative for the life of the run
    (matching the non-finite escalation's ``max_restores`` semantics):
    intervening successful dispatches do NOT refund restores, and a run
    that had to degrade stays degraded (the fused program is the thing
    that keeps failing) — tune ``max_restores`` against lifetime budget,
    not per-incident streaks.
    """

    def __init__(self, max_restores: int) -> None:
        self.max_restores = max(int(max_restores), 0)
        self.degraded = False
        self.restores = 0
        self.failures = 0               # exhausted-retry episodes, total

    def next_action(self, can_degrade: bool) -> str:
        """→ ``'degrade' | 'restore' | 'abort'`` for one exhausted
        dispatch. The caller maps 'restore' to 'abort' itself when no
        valid checkpoint exists."""
        self.failures += 1
        if can_degrade and not self.degraded:
            self.degraded = True
            return "degrade"
        if self.restores < self.max_restores:
            self.restores += 1
            return "restore"
        return "abort"

    def describe(self) -> str:
        return (f"failures={self.failures} degraded={self.degraded} "
                f"restores={self.restores}/{self.max_restores}")
