"""Tracing / profiling (SURVEY.md §5(1) — absent in the reference).

The reference's only timing is wall-clock ETA logging
(``/root/reference/per_run.py:207-208,246-251``). Here:

* ``StageTimer`` — per-stage wall-clock accumulation (rollout / train /
  test) logged with the metrics, so throughput regressions show up in the
  same TensorBoard/JSONL stream as reward curves;
* ``TraceWindow`` — a ``jax.profiler`` trace capture over a configured
  ``t_env`` window, viewable in TensorBoard's profile tab or Perfetto.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Optional

import jax


class StageTimer:
    def __init__(self):
        self._acc: Dict[str, float] = defaultdict(float)
        self._n: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._acc[name] += time.perf_counter() - t0
            self._n[name] += 1

    def log_and_reset(self, logger, t_env: int) -> None:
        for name, total in self._acc.items():
            n = max(self._n[name], 1)
            logger.log_stat(f"time_{name}_ms", 1000.0 * total / n, t_env)
        self._acc.clear()
        self._n.clear()


class TraceWindow:
    """Start a jax profiler trace when ``t_env`` enters
    [start, start+duration_steps-ish]; stop after ``n_iterations`` driver
    iterations. No-op when ``trace_dir`` is empty.

    Subclass hook: ``_on_stop(logger, t_env)`` runs once, right after
    ``jax.profiler.stop_trace()`` — ``obs.device_time.ProgramTraceWindow``
    overrides it to attribute the captured device time back to the
    registry's named programs (docs/OBSERVABILITY.md)."""

    def __init__(self, trace_dir: str, start_t_env: int = 0,
                 n_iterations: int = 3):
        self.trace_dir = trace_dir
        self.start_t_env = start_t_env
        self.n_iterations = n_iterations
        self._active: Optional[int] = None   # iterations remaining
        self._done = False

    def maybe_start(self, t_env: int) -> None:
        if (not self.trace_dir or self._done or self._active is not None
                or t_env < self.start_t_env):
            return
        jax.profiler.start_trace(self.trace_dir)
        self._active = self.n_iterations

    def tick(self, logger=None, t_env: int = 0) -> None:
        if self._active is None:
            return
        self._active -= 1
        if self._active <= 0:
            jax.profiler.stop_trace()
            self._active = None
            self._done = True
            self._on_stop(logger, t_env)

    def _on_stop(self, logger, t_env: int) -> None:
        if logger is not None:
            logger.console_logger.info(
                f"profiler trace written to {self.trace_dir}")
