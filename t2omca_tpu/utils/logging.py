"""Logger facade (M9).

Contract from the call sites (``/root/reference/per_run.py:8,29,45-53``):
wraps a console logger; ``setup_tb(dir)``; ``log_stat(key, value, t)``;
``print_recent_stats()``; exposes ``.console_logger``. The sacred observer
(``setup_sacred``) has no equivalent here — the experiment registry is the
run directory plus TensorBoard; a ``log_json`` sink writes the same scalars
as JSONL for offline analysis (replacing sacred's FileStorageObserver).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import defaultdict
from typing import Optional


def get_console_logger(name: str = "t2omca") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "[%(levelname)s %(asctime)s] %(name)s %(message)s", "%H:%M:%S"))
        logger.addHandler(h)
    logger.setLevel(logging.INFO)
    logger.propagate = False
    return logger


class Logger:
    #: default per-key in-memory history cap. ``self.stats`` used to
    #: keep every (t, value) pair for the life of the run — unbounded
    #: host-RAM growth on long runs, for a structure whose only reader
    #: (``print_recent_stats``) looks at the last 5 entries. The JSONL
    #: sink is the durable record; this cap bounds the live mirror.
    #: Overridable per instance (``config.ObsConfig.stats_history``
    #: threads through ``run.run``); 0 = unbounded (the old behavior).
    DEFAULT_MAX_HISTORY = 1024

    def __init__(self, console_logger: Optional[logging.Logger] = None,
                 max_history: Optional[int] = None):
        self.console_logger = console_logger or get_console_logger()
        self.stats = defaultdict(list)       # key -> [(t, value)]
        self.max_history = (self.DEFAULT_MAX_HISTORY
                            if max_history is None else int(max_history))
        self._tb = None
        self._jsonl = None
        # the sebulba driver logs from two threads (the actor thread's
        # runner-log/test cadences, the learner's log cadence): a key
        # inserted into self.stats while print_recent_stats iterates it
        # is a RuntimeError out of the diagnostics layer, and two
        # unsynchronized _jsonl writes can interleave mid-line — one
        # uncontended lock covers both (single-thread drivers pay an
        # uncontended acquire per cadence, not per step)
        self._lock = threading.Lock()

    # ---- sinks -----------------------------------------------------------
    def setup_tb(self, dirname: str) -> None:
        """TensorBoard via torch's bundled writer (the image has torch;
        gated so a torch-free install still runs)."""
        try:
            from torch.utils.tensorboard import SummaryWriter
        except Exception:
            self.console_logger.warning(
                "tensorboard writer unavailable; TB logging disabled")
            return
        os.makedirs(dirname, exist_ok=True)
        self._tb = SummaryWriter(log_dir=dirname)

    def setup_json(self, dirname: str) -> None:
        os.makedirs(dirname, exist_ok=True)
        self._jsonl = open(os.path.join(dirname, "metrics.jsonl"), "a")

    # ---- scalar API ------------------------------------------------------
    def log_stat(self, key: str, value, t: int) -> None:
        """Log one stat. Scalars are the contract; a VECTOR value (the
        graftsight fixed-bin histograms, per-layer attention entropies)
        degrades gracefully instead of crashing the diagnostics layer:
        ``metrics.jsonl`` keeps the full-fidelity list, while the
        in-memory history (the ``print_recent_stats`` console path) and
        TensorBoard get the mean as a scalar summary."""
        vector = None
        nd = getattr(value, "ndim", None)
        if isinstance(value, (list, tuple)) or (nd is not None and nd > 0):
            import numpy as _np
            arr = _np.asarray(value, dtype=_np.float64).reshape(-1)
            vector = [float(v) for v in arr]
            # console/TB summary: the mean (NaN-safe — a poisoned bin
            # must not blank the whole console line). Size-1 vectors
            # stay vectors deliberately: a (1,)-shaped stat is schema,
            # not a scalar that happens to be boxed.
            value = float(_np.nanmean(arr)) if arr.size else 0.0
        else:
            value = float(value)
        with self._lock:
            hist = self.stats[key]
            hist.append((t, value))
            if self.max_history and len(hist) > self.max_history:
                # amortized trim: drop down to half the cap so the
                # O(cap) del runs once per cap/2 appends, not on every
                # append — but never below the 5 entries
                # print_recent_stats reads (a cap of 5-9 must stay
                # observationally identical to the unbounded behavior),
                # and never above the cap itself
                keep = min(max(self.max_history // 2, 5),
                           self.max_history)
                del hist[:len(hist) - keep]
            if self._tb is not None:
                self._tb.add_scalar(key, value, t)
            if self._jsonl is not None:
                self._jsonl.write(json.dumps(
                    {"key": key,
                     "value": value if vector is None else vector,
                     "t": t}) + "\n")
                self._jsonl.flush()

    def print_recent_stats(self) -> None:
        """Mirrors the reference's periodic stat dump
        (``per_run.py:283-286``): latest value per key at the newest t."""
        with self._lock:
            if not self.stats:
                return
            t = max(ts[-1][0] for ts in self.stats.values())
            items = [f"t_env: {t}"]
            for k in sorted(self.stats):
                window = self.stats[k][-5:]
                mean = sum(v for _, v in window) / len(window)
                items.append(f"{k}: {mean:.4f}")
            line = "Recent stats | " + " | ".join(items)
        self.console_logger.info(line)

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
        if self._jsonl is not None:
            self._jsonl.close()
