"""Terminal-info stat aggregation — the reference runner's contract.

Re-creates ``cur_stats`` / ``cur_returns`` semantics of
``/root/reference/parallel_runner.py:193-231`` exactly:

* only the info dict of the TERMINAL step of each episode enters the stats
  (the reference appends ``data["info"]`` to ``final_env_infos`` when an env
  reports ``terminated``, ``:168-170``);
* values are summed across envs AND across rollouts until a flush, with
  ``n_episodes`` accumulating ``batch_size`` per rollout (``:226-228``);
* a flush logs ``<k>_mean = Σv / n_episodes`` plus ``return_mean`` over the
  accumulated per-episode returns, then clears (``:222-231``);
* test stats flush only when exactly the rounded ``test_nepisode`` quota of
  returns has accumulated (quirk Q10, ``:212-214``); train stats flush on the
  ``runner_log_interval`` cadence with ``epsilon`` logged alongside
  (``:215-219``).
"""

from __future__ import annotations

from collections import defaultdict
from typing import List

import jax
import numpy as np

#: info keys present in the reference env's terminal-step info dict
#: (``/root/reference/environment_multi_mec.py:343-364``)
TERMINAL_INFO_KEYS = (
    "reward", "delay_reward", "overtime_penalty",
    "channel_utilization_rate", "conflict_ratio", "episode_limit",
    "task_completion_rate", "task_completion_delay",
)


class StatsAccumulator:
    """Accumulates RolloutStats across rollouts; flush = reference ``_log``."""

    def __init__(self):
        self.stats = defaultdict(float)
        self.n_episodes = 0
        self.returns: List[float] = []
        self.epsilon = 0.0

    def push(self, rollout_stats) -> None:
        s = jax.device_get(rollout_stats)
        ret = np.atleast_1d(np.asarray(s.episode_return))
        self.returns.extend(float(x) for x in ret)
        self.n_episodes += len(ret)
        for k in TERMINAL_INFO_KEYS:
            self.stats[k] += float(np.sum(getattr(s, k)))
        self.epsilon = float(np.mean(np.asarray(s.epsilon)))

    def flush(self, logger, t_env: int, prefix: str = "") -> None:
        """Log ``return_mean`` + every ``<k>_mean`` and clear
        (``/root/reference/parallel_runner.py:222-231``)."""
        if self.returns:
            logger.log_stat(prefix + "return_mean",
                            float(np.mean(self.returns)), t_env)
        n = max(self.n_episodes, 1)
        for k, v in self.stats.items():
            logger.log_stat(prefix + k + "_mean", v / n, t_env)
        self.stats.clear()
        self.returns.clear()
        self.n_episodes = 0
