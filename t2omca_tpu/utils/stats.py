"""Terminal-info stat aggregation — the reference runner's contract.

Re-creates ``cur_stats`` / ``cur_returns`` semantics of
``/root/reference/parallel_runner.py:193-231`` exactly:

* only the info dict of the TERMINAL step of each episode enters the stats
  (the reference appends ``data["info"]`` to ``final_env_infos`` when an env
  reports ``terminated``, ``:168-170``);
* values are summed across envs AND across rollouts until a flush, with
  ``n_episodes`` accumulating ``batch_size`` per rollout (``:226-228``);
* a flush logs ``<k>_mean = Σv / n_episodes`` plus ``return_mean`` over the
  accumulated per-episode returns, then clears (``:222-231``);
* test stats flush only when exactly the rounded ``test_nepisode`` quota of
  returns has accumulated (quirk Q10, ``:212-214``); train stats flush on the
  ``runner_log_interval`` cadence with ``epsilon`` logged alongside
  (``:215-219``).
"""

from __future__ import annotations

from collections import defaultdict
from typing import List

import jax
import numpy as np

#: info keys present in the reference env's terminal-step info dict
#: (``/root/reference/environment_multi_mec.py:343-364``), plus the
#: graftworld deadline-miss rate (envs/mec_offload.StepInfo — the
#: per-slice generalization metric, docs/ENVS.md)
TERMINAL_INFO_KEYS = (
    "reward", "delay_reward", "overtime_penalty",
    "channel_utilization_rate", "conflict_ratio", "episode_limit",
    "task_completion_rate", "task_completion_delay",
    "deadline_miss_rate",
)

#: per-slice keys worth a slice breakdown (graftworld per-scenario
#: eval): return + the generalization-relevant rates — the full
#: TERMINAL set per slice would triple the metric stream for keys
#: (epsilon-like constants, episode_limit) that cannot differ by slice
SLICE_KEYS = ("conflict_ratio", "task_completion_rate",
              "deadline_miss_rate")

#: per-member keys emitted under the graftpop ``pop<i>_*`` rows
#: (docs/POPULATION.md): the experiment-comparison metrics — per-member
#: return rides separately as ``pop<i>_return_mean``. Same restraint as
#: SLICE_KEYS: the full TERMINAL set × P would flood the stream with
#: rows that cannot differ usefully by member.
POP_MEMBER_KEYS = ("task_completion_rate", "conflict_ratio",
                   "deadline_miss_rate")


class StatsAccumulator:
    """Accumulates RolloutStats across rollouts; flush = reference ``_log``.

    ``push`` only *references* the device arrays (shape-derived episode
    count, no transfer); the device→host fetch happens once per ``flush``.
    Under the remote-tunnel backend a fetch is a blocking ~0.66 s round
    trip (BASELINE.md), so per-rollout fetching would serialize the driver
    loop on the slowest link; deferring it lets dispatch run ahead between
    log cadences. Aggregation semantics are unchanged."""

    #: fold threshold: each un-fetched RolloutStats ref pins its device
    #: buffers alive, so when ``runner_log_interval`` spans many rollouts
    #: ``_pending`` would grow without bound; past this many pushes the
    #: partial results are folded into host-side sums (one extra fetch per
    #: FOLD_EVERY rollouts — negligible against the interval it bounds)
    FOLD_EVERY = 64

    def __init__(self, population: int = 0):
        self.n_episodes = 0
        #: device→host round-trips this accumulator has performed
        #: (folds + mid-interval epsilon reads) — graftscope surfaces it
        #: as ``stat_fetches`` so sync-point cost is attributable from
        #: telemetry alone (each fetch is ~0.66 s under the axon tunnel)
        self.fetches = 0
        #: graftpop population axis (docs/POPULATION.md): P > 0 means
        #: every pushed stats leaf carries a LEADING (P,) member axis
        #: (the population superstep's vmapped output). The fold then
        #: ALSO aggregates per member — riding the same single fetch,
        #: zero extra dispatches — and flush emits ``pop<i>_*`` rows
        #: next to the aggregate stream when P > 1. ``n_episodes``
        #: counts TOTAL episodes across members (P·K·B per push).
        self.population = population
        #: per-member return EMA surviving across flushes — the PBT
        #: ranking signal (population.pbt_step member_perf); None until
        #: a member has flushed at least once
        self.member_return_ema: List = [None] * max(population, 0)
        self._pending = []          # un-fetched RolloutStats device refs
        self._eps_ref = None        # epsilon pushed since the last fetch
        self._eps_val = 0.0         # cached host value
        self._returns: List[float] = []   # folded per-episode returns
        self._stats = defaultdict(float)  # folded terminal-info sums
        # member id -> {n, return_sum, <TERMINAL_INFO_KEYS sums>}
        self._members = defaultdict(lambda: defaultdict(float))
        # graftworld per-scenario-slice aggregation (docs/ENVS.md):
        # family id -> {n, return_sum, <SLICE_KEYS sums>}; fed by the
        # SAME fold fetch as the overall sums — a stats object without a
        # ``scenario`` field (older tests, fakes) skips slice tracking
        self._slices = defaultdict(lambda: defaultdict(float))

    def push(self, rollout_stats) -> None:
        self._pending.append(rollout_stats)
        self._eps_ref = rollout_stats.epsilon
        # episode count is static shape info — reading it syncs nothing
        self.n_episodes += int(
            np.prod(rollout_stats.episode_return.shape) or 1)
        if len(self._pending) >= self.FOLD_EVERY:
            self._fold()

    def _fold(self) -> None:
        """Fetch every pending device ref (ONE host round-trip) and fold
        it into the host-side sums; clears ``_pending``. A pushed stats
        object may be one rollout's ``(B,)`` arrays or a fused
        superstep's stacked ``(K, B)`` — flattening makes both the same
        per-episode stream (the episode count in ``push`` already used
        the full shape product)."""
        if not self._pending:
            return
        self.fetches += 1
        fetched = jax.device_get(self._pending)
        for s in fetched:
            ret = np.asarray(s.episode_return).reshape(-1)
            self._returns.extend(float(x) for x in ret)
            for k in TERMINAL_INFO_KEYS:
                # absent keys (older fakes without the graftworld
                # fields) simply don't aggregate
                v = getattr(s, k, None)
                if v is not None:
                    self._stats[k] += float(np.sum(v))
            if self.population:
                # per-member aggregation off the SAME fetched arrays:
                # leaf layout (P, ...) — member i is row i
                for m in range(self.population):
                    mem = self._members[m]
                    r_m = np.asarray(s.episode_return)[m].reshape(-1)
                    mem["n"] += float(r_m.size)
                    mem["return"] += float(r_m.sum())
                    for k in TERMINAL_INFO_KEYS:
                        v = getattr(s, k, None)
                        if v is not None:
                            mem[k] += float(np.sum(np.asarray(v)[m]))
            scenario = getattr(s, "scenario", None)
            if scenario is not None:
                fam = np.asarray(scenario).reshape(-1).astype(np.int64)
                for f in np.unique(fam):
                    sel = fam == f
                    sl = self._slices[int(f)]
                    sl["n"] += float(sel.sum())
                    sl["return"] += float(ret[sel].sum())
                    for k in SLICE_KEYS:
                        v = getattr(s, k, None)
                        if v is not None:
                            sl[k] += float(
                                np.asarray(v).reshape(-1)[sel].sum())
        # the last pending entry owns the epsilon ref — same fetch; a
        # stacked push's most recent value is its LAST row. Under a
        # population the logged aggregate `epsilon` is MEMBER 0's (the
        # un-scaled schedule — the solo run's value); pop<i> epsilons
        # differ only by the static eps_scale grid, not worth P rows
        eps = np.asarray(fetched[-1].epsilon)
        if self.population:
            eps = eps[0]
        self._eps_val = float(np.mean(eps.reshape(-1)[-1:]))
        self._eps_ref = None
        self._pending.clear()

    @property
    def epsilon(self) -> float:
        """Exploration rate of the most recent rollout (reference logs it
        alongside each train-stat flush, ``parallel_runner.py:217-218``).

        NOTE: when pushes happened since the last fetch, reading this
        property performs a BLOCKING device→host fetch (~0.66 s per read
        under the axon tunnel) — treat mid-interval reads as costly.
        ``flush`` refreshes the cached value inside its own single fetch,
        which is where cadenced callers should get it."""
        if self._eps_ref is not None:
            # a stacked (K,) superstep push reports its LAST sub-iteration
            # (member 0's under a population — see _fold)
            self.fetches += 1
            eps = np.asarray(jax.device_get(self._eps_ref))
            if self.population:
                eps = eps[0]
            self._eps_val = float(eps.reshape(-1)[-1])
            self._eps_ref = None
        return self._eps_val

    def flush(self, logger, t_env: int, prefix: str = "") -> None:
        """Log ``return_mean`` + every ``<k>_mean`` and clear
        (``/root/reference/parallel_runner.py:222-231``). When the
        accumulated episodes span MORE than one scenario-family slice
        (a graftworld distribution), per-slice rows follow under
        ``<prefix>slice<fam>_*`` keys — single-scenario runs keep the
        exact pre-graftworld metric stream. A graftpop population
        (P > 1) additionally emits per-member ``<prefix>pop<i>_*`` rows
        and refreshes :attr:`member_return_ema` (the PBT ranking
        signal) — same fetch, zero extra dispatches; P <= 1 keeps the
        exact single-experiment stream (the P=1 parity contract)."""
        self._fold()                              # ONE host round-trip
        if self._returns:
            logger.log_stat(prefix + "return_mean",
                            float(np.mean(self._returns)), t_env)
        n = max(self.n_episodes, 1)
        for k, v in self._stats.items():
            logger.log_stat(prefix + k + "_mean", v / n, t_env)
        if self.population:
            for m in sorted(self._members):
                mem = self._members[m]
                if not mem.get("n"):
                    continue
                mn = max(mem["n"], 1.0)
                r = mem["return"] / mn
                ema = self.member_return_ema[m]
                self.member_return_ema[m] = (
                    r if ema is None else 0.7 * ema + 0.3 * r)
                if self.population > 1:
                    tag = f"{prefix}pop{m}_"
                    logger.log_stat(tag + "return_mean", r, t_env)
                    for k in POP_MEMBER_KEYS:
                        if k in mem:
                            logger.log_stat(tag + k + "_mean",
                                            mem[k] / mn, t_env)
        if len(self._slices) > 1:
            for fam in sorted(self._slices):
                sl = self._slices[fam]
                sn = max(sl["n"], 1.0)
                tag = f"{prefix}slice{fam}_"
                logger.log_stat(tag + "n", sl["n"], t_env)
                logger.log_stat(tag + "return_mean", sl["return"] / sn,
                                t_env)
                for k in SLICE_KEYS:
                    logger.log_stat(tag + k + "_mean", sl[k] / sn, t_env)
        self._returns.clear()
        self._stats.clear()
        self._members.clear()
        self._slices.clear()
        self.n_episodes = 0
