from .logging import Logger
from .timehelper import time_left, time_str

__all__ = ["Logger", "time_left", "time_str"]
