"""Checkpoint save/resume of the full train state.

The reference checkpoints only model+optimizer tensors into
``<results>/models/<token>/<t_env>/`` and resumes by numeric-directory scan
with ``load_step`` nearest-match, restoring the env-step cursor
(``/root/reference/per_run.py:159-189,265-279``, Q13). What it does NOT
checkpoint — replay contents, normalizer statistics, RNG state — makes its
resume approximate (SURVEY.md §5(4)).

Here the checkpoint is the *entire* train-state pytree (learner params +
target + optimizer, runner state incl. per-env Welford stats and PRNG keys,
and optionally the replay buffer), serialized with flax msgpack — resume is
exact, an intentional capability upgrade flagged in SURVEY.md §5(4).
Directory layout and nearest-``load_step`` selection mirror the reference.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Tuple

import jax
from flax import serialization

#: bump when the checkpointed pytree layout changes incompatibly
#: (v2: bool avail storage + meta sidecar; v3: RunnerState carries the
#: per-lane reward-scale state)
FORMAT_VERSION = 3


class CheckpointFormatError(ValueError):
    """The checkpoint's on-disk format is not readable by this build
    (newer FORMAT_VERSION). NOT a config mismatch — no fallback applies."""


def _obs_layout(state: Any) -> Optional[str]:
    """'compact' | 'dense' | None (host buffer keeps state outside the tree)."""
    from ..components.episode_buffer import CompactEntityObs
    buf = getattr(state, "buffer", None)
    if buf is None:
        return None
    return ("compact" if isinstance(buf.storage.obs, CompactEntityObs)
            else "dense")


def save_checkpoint(path: str, t_env: int, state: Any) -> str:
    """Write ``<path>/<t_env>/state.msgpack`` + a ``meta.json`` sidecar
    recording the format version and replay obs layout, so a restore with
    a mismatched ``replay.compact_entity_store`` fails with the exact flag
    to toggle instead of a deep msgpack structure error.

    Multi-host (``jax.process_count() > 1``): leaves sharded over the
    global mesh are not host-addressable, so every process joins a
    ``process_allgather`` (a collective — ALL processes must call this
    function in lockstep) to assemble them, and only process 0 writes the
    file. Replicated leaves (params, optimizer — already host-local) skip
    the gather entirely; only data-sharded leaves (the replay ring,
    runner lanes) ride the collective. The checkpoint on disk is always
    the complete global state, restorable on any topology (exact-resume
    re-shards; model-only fallback via ``load_learner_state``). Known
    cost at production ring sizes: the allgather materializes the ring on
    EVERY host (~GiBs over DCN); a per-shard on-disk format (one file per
    process, orbax-style) is the escape hatch if that ever dominates."""
    d = os.path.join(path, str(int(t_env)))
    if jax.process_count() > 1:
        import numpy as _np
        from jax.experimental import multihost_utils

        def _host_local(x):
            if not isinstance(x, jax.Array):
                return x
            if x.is_fully_addressable:
                return jax.device_get(x)
            if x.is_fully_replicated:
                return _np.asarray(x)      # local shard already holds it
            return multihost_utils.process_allgather(x, tiled=True)

        # branch choice depends only on shardings — identical on every
        # process, so the collectives stay in lockstep
        state = jax.tree.map(_host_local, state)
        if jax.process_index() != 0:
            return d
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "state.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(jax.device_get(state)))
    with open(os.path.join(d, "meta.json"), "w") as f:
        json.dump({"format": FORMAT_VERSION, "obs_layout": _obs_layout(state),
                   "t_env": int(t_env)}, f)
    return d


def find_checkpoint(path: str, load_step: int = 0) -> Optional[Tuple[str, int]]:
    """Scan numeric subdirs; pick max ``t_env`` when ``load_step == 0`` else
    the nearest to ``load_step`` (reference ``per_run.py:171-182``)."""
    if not os.path.isdir(path):
        return None
    steps = [int(name) for name in os.listdir(path)
             if name.isdigit()
             and os.path.isdir(os.path.join(path, name))]
    if not steps:
        return None
    if load_step == 0:
        step = max(steps)
    else:
        step = min(steps, key=lambda s: abs(s - load_step))
    return os.path.join(path, str(step)), step


def load_checkpoint(dirname: str, target: Any) -> Any:
    """Restore into a template pytree of the same structure. The
    ``meta.json`` sidecar (when present) turns a replay-layout mismatch
    into a precise config instruction before any deserialization."""
    meta_path = os.path.join(dirname, "meta.json")
    meta = None
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        fmt = meta.get("format", 0)
        if fmt > FORMAT_VERSION:
            raise CheckpointFormatError(
                f"checkpoint {dirname} has format v{fmt}, newer than this "
                f"build's v{FORMAT_VERSION} — upgrade the framework to "
                f"restore it")
        saved = meta.get("obs_layout")
        configured = _obs_layout(target)
        if saved and configured and saved != configured:
            want = "true" if saved == "compact" else "false"
            raise ValueError(
                f"checkpoint {dirname} stores the replay ring with "
                f"'{saved}' obs layout but the config builds '{configured}' "
                f"storage — set replay.compact_entity_store={want} (and for "
                f"'compact' keep env_args.fast_norm=true) to resume this "
                f"checkpoint (docs/SPEC.md perf modes)")
    with open(os.path.join(dirname, "state.msgpack"), "rb") as f:
        data = f.read()
    try:
        if meta is None or meta.get("format", 0) < 3:
            # v2 → v3 migration: v3 added RunnerState.rscale. No v2 run
            # could have had reward_scaling on (the field did not exist),
            # so injecting the template's fresh (all-zero) reward-scale
            # state-dict is lossless — replay contents, normalizer stats,
            # and RNG state all restore exactly. Meta-less checkpoints
            # (pre-v2, before the sidecar existed — or a deleted sidecar)
            # take the same path: injection is conditional on the field
            # actually being absent, so a v3 tree without its meta.json
            # still restores unmodified.
            raw = serialization.msgpack_restore(data)
            if (isinstance(raw, dict) and "runner" in raw
                    and "rscale" not in raw["runner"]):
                raw["runner"]["rscale"] = serialization.to_state_dict(
                    jax.device_get(target.runner.rscale))
            restored = serialization.from_state_dict(target, raw)
        else:
            restored = serialization.from_bytes(target, data)
    except (KeyError, ValueError) as e:
        raise ValueError(
            f"checkpoint {dirname} does not match the configured train-state "
            f"structure: {e}. A common cause is the replay storage layout — "
            f"checkpoints written before/after the compact entity storage "
            f"default need replay.compact_entity_store toggled to match "
            f"(docs/SPEC.md perf modes)") from e
    # flax does not shape-validate on restore: a checkpoint from a
    # different config (env lanes, replay capacity, DP shapes) would
    # silently land wrong-shaped arrays that only explode later inside
    # jit — reject it here so callers can fall back to the model-only
    # restore (run.evaluate_sequential does)
    t_leaves = jax.tree_util.tree_leaves_with_path(target)
    r_leaves = jax.tree_util.tree_leaves_with_path(restored)
    bad = [
        (jax.tree_util.keystr(kp), getattr(lt, "shape", None),
         getattr(lr, "shape", None))
        for (kp, lt), (_, lr) in zip(t_leaves, r_leaves)
        if getattr(lt, "shape", None) != getattr(lr, "shape", None)]
    if bad:
        k, st, sr = bad[0]
        raise ValueError(
            f"checkpoint {dirname} was written under a different config: "
            f"{len(bad)} leaves mismatch the template (first: {k} stored "
            f"{sr} vs configured {st}). Use load_learner_state for "
            f"model-only restore (reference semantics).")
    return restored


def load_learner_state(dirname: str, target: Any) -> Any:
    """Restore ONLY the learner subtree (params/target/optimizer) into a
    full train-state template — shape-independent of the runner/replay
    config, so a model trained at one scale (or on a DP mesh) evaluates
    under any other. Matches the reference's model-only checkpoint
    semantics (``/root/reference/per_run.py:185-187``): runner-side
    normalizer statistics start fresh."""
    with open(os.path.join(dirname, "state.msgpack"), "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    learner = serialization.from_state_dict(target.learner, raw["learner"])
    # same silent-wrong-shape hazard as the full restore: a model-config
    # mismatch (e.g. different emb) must fail HERE with the leaf named,
    # not later inside jit — and for params there is no further fallback
    t_leaves = jax.tree_util.tree_leaves_with_path(target.learner)
    r_leaves = jax.tree_util.tree_leaves_with_path(learner)
    bad = [
        (jax.tree_util.keystr(kp), getattr(lt, "shape", None),
         getattr(lr, "shape", None))
        for (kp, lt), (_, lr) in zip(t_leaves, r_leaves)
        if getattr(lt, "shape", None) != getattr(lr, "shape", None)]
    if bad:
        k, st, sr = bad[0]
        raise ValueError(
            f"checkpoint {dirname} holds a different MODEL than the "
            f"configured one: {len(bad)} learner leaves mismatch (first: "
            f"{k} stored {sr} vs configured {st}); fix the model config "
            f"to match the checkpoint")
    return target.replace(learner=learner)
