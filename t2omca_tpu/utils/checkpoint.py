"""Checkpoint save/resume of the full train state — crash-safe.

The reference checkpoints only model+optimizer tensors into
``<results>/models/<token>/<t_env>/`` and resumes by numeric-directory scan
with ``load_step`` nearest-match, restoring the env-step cursor
(``/root/reference/per_run.py:159-189,265-279``, Q13). What it does NOT
checkpoint — replay contents, normalizer statistics, RNG state — makes its
resume approximate (SURVEY.md §5(4)).

Here the checkpoint is the *entire* train-state pytree (learner params +
target + optimizer, runner state incl. per-env Welford stats and PRNG keys,
and optionally the replay buffer), serialized with flax msgpack — resume is
exact, an intentional capability upgrade flagged in SURVEY.md §5(4).
Directory layout and nearest-``load_step`` selection mirror the reference.

Crash safety (docs/RESILIENCE.md): a write lands in a ``tmp.<t_env>``
staging directory, is fsynced, and is published by one atomic ``rename`` —
a crash at ANY point leaves either the previous checkpoint set intact or a
``tmp.*`` leftover that the numeric scan never selects. ``meta.json``
records a SHA-256 of ``state.msgpack``; ``find_checkpoint`` verifies each
candidate and *skips back* to the newest VALID step instead of handing a
torn or bit-flipped file to resume. ``prune_checkpoints`` bounds disk on
long runs (keep newest K + every Nth step). Single writer per checkpoint
directory assumed (the driver owns its token-unique ``models/<token>/``).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import shutil
from typing import Any, List, Optional, Sequence, Tuple

import jax
from flax import serialization

from . import resilience

logger = logging.getLogger(__name__)

#: bump when the checkpointed pytree layout changes incompatibly
#: (v2: bool avail storage + meta sidecar; v3: RunnerState carries the
#: per-lane reward-scale state; v4: RunnerState carries the per-lane
#: graftworld scenario params, envs/mec_offload.EnvParams; v5: graftpop
#: population runs checkpoint a ``population.PopState`` — the
#: (P,)-stacked TrainState plus the PBT-mutable PopulationSpec —
#: instead of the bare TrainState; classic runs keep the bare-TrainState
#: layout AND keep stamping v4 (``_state_format``) so a pre-population
#: build can still restore them after a rollback, and a single-member
#: checkpoint restores into a population
#: template via the ``_lift_population`` shim). The staged/atomic write
#: and the sidecar's ``sha256``/``bytes`` keys are ADDITIVE — the tree
#: layout is unchanged and old readers ignore unknown sidecar keys, so
#: they do not bump this.
FORMAT_VERSION = 5


class CheckpointFormatError(ValueError):
    """The checkpoint's on-disk format is not readable by this build
    (newer FORMAT_VERSION). NOT a config mismatch — no fallback applies."""


class CheckpointIntegrityError(RuntimeError):
    """The checkpoint bytes on disk do not match their recorded checksum
    (torn write published by an old build, bit rot, manual tampering).
    Deliberately NOT a ValueError: the model-only restore fallback that
    callers apply to config mismatches is hopeless here — the bytes
    themselves are bad."""


def _state_format(state: Any) -> int:
    """The format version THIS state's layout needs: v5 only for a
    graftpop ``PopState`` (the new-in-v5 layout); classic bare-TrainState
    checkpoints keep stamping v4 — their on-disk layout is unchanged, so
    a pre-population build (whose ``FORMAT_VERSION`` is 4) must keep
    restoring them after a rollback."""
    return (FORMAT_VERSION
            if hasattr(state, "ts") and hasattr(state, "spec") else 4)


def _obs_layout(state: Any) -> Optional[str]:
    """'compact' | 'dense' | None (host buffer keeps state outside the tree)."""
    from ..components.episode_buffer import CompactEntityObs
    # a graftpop PopState wraps the (stacked) TrainState in `.ts`; the
    # storage layout is a per-leaf property, unchanged by the stack
    state = getattr(state, "ts", state)
    buf = getattr(state, "buffer", None)
    if buf is None:
        return None
    return ("compact" if isinstance(buf.storage.obs, CompactEntityObs)
            else "dense")


def _population_size(state: Any) -> Optional[int]:
    """Leading (P,) member count of a graftpop ``PopState`` (None for a
    bare TrainState). Works on concrete, host-numpy and eval_shape trees
    AND on the raw state-dict form (``{"ts": ..., "spec": ...}``)."""
    spec = (state.get("spec") if isinstance(state, dict)
            else getattr(state, "spec", None))
    if spec is None or not (isinstance(state, dict)
                            or hasattr(state, "ts")):
        return None
    leaves = jax.tree_util.tree_leaves(spec)
    if not leaves:
        return None
    shape = getattr(leaves[0], "shape", None)
    return int(shape[0]) if shape else None


def _topology_stamp(state: Any, extra: Optional[dict] = None) -> dict:
    """The ``meta.json`` topology stamp (docs/RESILIENCE.md §6): enough
    about the WRITING run's shape that a resume under a different shape is
    detected and routed through :func:`restore_elastic` instead of
    crashing deep inside ``from_state_dict``. The driver threads loop
    shape / mesh shape / sebulba split through ``extra``; the base facts
    are derivable from the state + runtime here. Absent on pre-graftmorph
    checkpoints — readers must treat a missing stamp as "unknown", not as
    a mismatch."""
    stamp = {"device_count": jax.device_count(),
             "process_count": jax.process_count(),
             "population": _population_size(state),
             "format": _state_format(state)}
    if extra:
        stamp.update(extra)
    return stamp


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _fsync_path(path: str) -> None:
    """fsync a file OR directory entry so the rename-based publish is
    durable, not merely atomic-in-page-cache."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def save_checkpoint(path: str, t_env: int, state: Any,
                    gather_retries: int = 2,
                    gather_backoff_s: float = 0.5,
                    topology: Optional[dict] = None) -> str:
    """Write ``<path>/<t_env>/{state.msgpack, meta.json}`` crash-safely.

    ``gather_retries``/``gather_backoff_s`` bound the per-leaf allgather
    retry on the multi-host path (defaults mirror
    ``resilience.dispatch_retries``/``retry_backoff_s`` — the driver
    threads its configured values through).

    The write is staged in ``<path>/tmp.<t_env>``: state bytes + fsync,
    sidecar (format version, replay obs layout, sha256 + byte count of the
    state blob) + fsync, then ONE ``os.rename`` publishes the directory
    and the parent is fsynced. Readers therefore only ever see complete
    checkpoints; a crash leaves a ``tmp.*`` directory the numeric scan in
    ``find_checkpoint`` ignores (and ``prune_checkpoints`` sweeps). The
    sidecar lets a restore with a mismatched ``replay.compact_entity_store``
    fail with the exact flag to toggle instead of a deep msgpack error.

    Re-saving an existing step (the preemption path's emergency checkpoint
    can land on the save cadence's step) replaces the published directory.

    Multi-host (``jax.process_count() > 1``): leaves sharded over the
    global mesh are not host-addressable, so every process joins a
    ``process_allgather`` (a collective — ALL processes must call this
    function in lockstep) to assemble them, and only process 0 writes the
    file. Replicated leaves (params, optimizer — already host-local) skip
    the gather entirely; only data-sharded leaves (the replay ring, runner
    lanes) ride the collective. Non-zero processes drop each gathered leaf
    immediately instead of holding the assembled tree until the file write
    — peak extra host RAM off process 0 is ONE leaf's gather, not the full
    ring (ADVICE r5); process 0 logs the gathered byte count so the DCN
    cost of the collective is visible in the run log. The checkpoint on
    disk is always the complete global state, restorable on any topology
    (exact-resume re-shards; model-only fallback via
    ``load_learner_state``). A per-shard on-disk format (one file per
    process, orbax-style) exists as :func:`save_checkpoint_shards` — the
    degraded path for a preemption with dead peers, where this function's
    collectives would hang.

    ``topology`` merges driver-side facts (loop shape, mesh shape,
    sebulba split, member ranking) into the ``meta.json`` topology stamp
    (docs/RESILIENCE.md §6)."""
    d = os.path.join(path, str(int(t_env)))
    # stamped BEFORE the multi-host gather: the global device/process
    # counts are the WRITING topology by definition — capture them while
    # the state still carries its device placement
    stamp = _topology_stamp(state, topology)
    # fault-injection point (docs/RESILIENCE.md §4): the gather-to-host
    # step — the multi-host allgather sequence below, or the plain
    # device_get serialize on one process. Raising a transient error here
    # simulates a dropped/flaky collective; the driver's save cadence
    # wraps this whole function in utils.watchdog.retry_call, so the save
    # is retried with backoff instead of killing the run.
    resilience.fire("collective.gather", t_env=int(t_env),
                    multihost=jax.process_count() > 1)
    if jax.process_count() > 1:
        import numpy as _np
        from jax.experimental import multihost_utils

        from .watchdog import retry_call

        # quiesce + align before the host-driven collective sequence: the
        # driver dispatches asynchronously, so train-step collectives
        # (grad psums) can still be in flight when save is called.
        # Draining the device queue and barriering all processes first
        # makes the gather sequence the only live collective traffic —
        # cheap at save cadence, and it keeps a slow host from skewing
        # the processes into interleaved collective orders.
        jax.block_until_ready(state)
        multihost_utils.sync_global_devices("save_checkpoint:begin")

        writer = jax.process_index() == 0
        gathered_bytes = [0]

        def _host_local(x):
            if not isinstance(x, jax.Array):
                return x
            if x.is_fully_addressable:
                return jax.device_get(x) if writer else None
            if x.is_fully_replicated:
                # local shard already holds the value — no collective
                return _np.asarray(x) if writer else None
            # branch choice depends only on shardings — identical on every
            # process, so the collectives stay in lockstep. Transient
            # transport faults (the gloo EnforceNotMet class) retry with
            # the same deterministic policy on every process: the error is
            # symmetric (the collective fails on all participants), so the
            # peers re-enter the retried gather in lockstep too — a
            # one-sided loss would desync and is exactly what the driver's
            # watchdog (stamped around this save) then catches as a stall.
            g = retry_call(
                lambda: multihost_utils.process_allgather(x, tiled=True),
                attempts=1 + max(int(gather_retries), 0),
                backoff_s=gather_backoff_s,
                label="checkpoint.process_allgather")
            if not writer:
                return None          # freed now, not at function exit
            gathered_bytes[0] += g.nbytes
            return g

        state = jax.tree.map(_host_local, state)
        # trailing barrier: non-writers must not run ahead into the next
        # collective phase (or interpreter shutdown) while the writer is
        # mid-sequence — same transport race as above, from the other side
        multihost_utils.sync_global_devices("save_checkpoint:end")
        if not writer:
            return d
        if gathered_bytes[0]:
            logger.info(
                "save_checkpoint t_env=%d: allgathered %.1f MiB of "
                "data-sharded leaves over DCN", int(t_env),
                gathered_bytes[0] / (1 << 20))

    os.makedirs(path, exist_ok=True)
    staging = os.path.join(path, f"tmp.{int(t_env)}")
    if os.path.isdir(staging):
        shutil.rmtree(staging)       # leftover from a crashed writer
    os.makedirs(staging)

    blob = serialization.to_bytes(jax.device_get(state))
    state_path = os.path.join(staging, "state.msgpack")
    with open(state_path, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    digest = hashlib.sha256(blob).hexdigest()
    del blob
    # fault-injection hook (tests): crash / truncate between the state
    # write and the publish — the whole point of the staged layout
    resilience.fire("checkpoint.staged", dirname=staging, t_env=int(t_env))
    with open(os.path.join(staging, "meta.json"), "w") as f:
        json.dump({"format": _state_format(state),
                   "obs_layout": _obs_layout(state),
                   "t_env": int(t_env), "sha256": digest,
                   "bytes": os.path.getsize(state_path),
                   "topology": stamp}, f)
        f.flush()
        os.fsync(f.fileno())

    displaced = None
    if os.path.isdir(d):
        # replacing an already-published step (emergency save landing on
        # the save cadence's step): move the old version ASIDE instead of
        # deleting it before the publish — with keep_last=1 retention
        # there may be no older step to skip back to, and an rmtree here
        # would leave a crash window with NOTHING on disk. Now the only
        # exposure is the instant between the two renames, and even a
        # crash there leaves this complete copy on disk (hand-recoverable
        # by renaming it back; prune sweeps it otherwise).
        displaced = os.path.join(path, f"tmp.{int(t_env)}.replaced")
        if os.path.isdir(displaced):
            shutil.rmtree(displaced)
        os.rename(d, displaced)
    os.rename(staging, d)            # the atomic publish
    _fsync_path(path)                # make the rename itself durable
    if displaced is not None:
        shutil.rmtree(displaced)
    return d


#: ``shard.<i>-of-<n>.msgpack`` — one host's slice of a degraded save
_SHARD_RE = re.compile(r"^shard\.(\d+)-of-(\d+)\.msgpack$")


def _shard_file(i: int, n: int) -> Tuple[str, str]:
    return f"shard.{i}-of-{n}.msgpack", f"shard.{i}-of-{n}.json"


def _write_file_atomic(dirname: str, name: str, blob: bytes) -> None:
    """tmp-write + fsync + rename INSIDE an already-visible directory —
    per-file atomicity for the shard path, where no host owns the
    directory and the staged-directory publish of the complete path is
    impossible (peers write into the same step dir concurrently)."""
    tmp = os.path.join(dirname, f".tmp.{name}")
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, os.path.join(dirname, name))


def write_shard(path: str, t_env: int, shard_index: int, num_shards: int,
                host_state: Any, sharded_paths: Sequence[str] = (),
                topology: Optional[dict] = None) -> str:
    """Write ONE host's shard of a degraded (``partial``) checkpoint into
    ``<path>/<t_env>/`` — no collectives, no cross-host coordination, so
    it cannot hang on a dead peer.

    ``host_state`` is this host's LOCAL view of the train state: sharded
    leaves hold only the local axis-0 block, replicated leaves the full
    value. ``sharded_paths`` names (``jax.tree_util.keystr`` over the
    state-dict) the leaves that are axis-0 blocks — the assembly rule in
    :func:`_assemble_shards` concatenates exactly those in shard order
    and takes shard 0's copy of everything else. All repo shardings are
    ``P("data")`` on the leading axis or fully replicated
    (``parallel/mesh.py``), so axis-0 concat is the only assembly rule.

    Layout per shard: ``shard.<i>-of-<n>.msgpack`` (the state-dict) +
    ``shard.<i>-of-<n>.json`` (its sha256/bytes + ``sharded_paths``),
    both tmp-written + renamed for per-file atomicity. Every surviving
    host also writes an identical, deterministic ``meta.json`` stamped
    ``partial`` (sorted keys; last-writer-wins is byte-idempotent), so
    the step dir is self-describing even when only some shards landed —
    :func:`verify_checkpoint` treats it as valid only when ALL ``n``
    shards are present and intact."""
    d = os.path.join(path, str(int(t_env)))
    os.makedirs(d, exist_ok=True)
    sd = serialization.to_state_dict(host_state)
    blob = serialization.to_bytes(sd)
    sname, jname = _shard_file(int(shard_index), int(num_shards))
    _write_file_atomic(d, sname, blob)
    side = {"shard": int(shard_index), "shards": int(num_shards),
            "t_env": int(t_env), "sha256": hashlib.sha256(blob).hexdigest(),
            "bytes": len(blob), "sharded_paths": sorted(sharded_paths)}
    del blob
    _write_file_atomic(d, jname,
                       json.dumps(side, sort_keys=True).encode())
    meta = {"format": _state_format(host_state),
            "obs_layout": _obs_layout(host_state),
            "t_env": int(t_env), "partial": True,
            "shards": int(num_shards),
            "topology": _topology_stamp(host_state, topology)}
    _write_file_atomic(d, "meta.json",
                       json.dumps(meta, sort_keys=True).encode())
    _fsync_path(d)
    os.makedirs(path, exist_ok=True)
    _fsync_path(path)
    return d


def save_checkpoint_shards(path: str, t_env: int, state: Any,
                           topology: Optional[dict] = None) -> str:
    """Degraded emergency save: each process writes ONLY its local shard
    via :func:`write_shard` — the fallback when the coordinated
    preemption barrier fails or :func:`save_checkpoint`'s gather dies
    mid-collective (a peer is gone, so any collective would hang). The
    resulting save is stamped ``partial`` and is valid only once all
    shards landed; :func:`restore_host_state` reassembles it into the
    ordinary global state-dict on ANY later host count
    (docs/RESILIENCE.md §6)."""
    import numpy as _np
    idx, n = jax.process_index(), jax.process_count()
    # fault-injection point (docs/RESILIENCE.md §4): the degraded
    # shard write itself — the driver's exit path catches a failure
    # here and leaves the last cadence save as the resume point
    resilience.fire("checkpoint.shard_save", t_env=int(t_env),
                    shard=idx, shards=n)
    sd = serialization.to_state_dict(state)
    kp_leaves, treedef = jax.tree_util.tree_flatten_with_path(sd)
    sharded_paths, host_leaves = [], []
    for kp, x in kp_leaves:
        if not isinstance(x, jax.Array):
            host_leaves.append(x)
            continue
        if x.is_fully_replicated:
            host_leaves.append(_np.asarray(x))
            continue
        if x.is_fully_addressable:
            host_leaves.append(jax.device_get(x))
            continue
        # data-sharded leaf: this host's axis-0 block, deduped (a
        # device may hold a replica of another's block under dp) and
        # ordered by global offset
        blocks = {}
        for s in x.addressable_shards:
            start = s.index[0].start or 0 if s.index else 0
            blocks.setdefault(start, s.data)
        block = _np.concatenate(
            [_np.asarray(blocks[k]) for k in sorted(blocks)], axis=0)
        sharded_paths.append(jax.tree_util.keystr(kp))
        host_leaves.append(block)
    host_sd = jax.tree_util.tree_unflatten(treedef, host_leaves)
    del kp_leaves, host_leaves
    d = write_shard(path, t_env, idx, n, host_sd,
                    sharded_paths=sharded_paths, topology=topology)
    logger.warning(
        "save_checkpoint_shards t_env=%d: wrote degraded shard %d/%d "
        "under %s (valid for resume only once all shards land)",
        int(t_env), idx, n, d)
    return d


def _shard_groups(dirname: str) -> dict:
    """``{n: {i: filename}}`` for the shard msgpacks present in a dir."""
    groups: dict = {}
    try:
        names = os.listdir(dirname)
    except OSError:
        return groups
    for name in names:
        m = _SHARD_RE.match(name)
        if m:
            groups.setdefault(int(m.group(2)), {})[int(m.group(1))] = name
    return groups


def _complete_shard_group(dirname: str, verify: bool = True
                          ) -> Optional[int]:
    """The shard count ``n`` of a COMPLETE, intact shard set under
    ``dirname`` (all ``n`` msgpacks + sidecars present, byte counts and
    — when ``verify`` — SHA-256 digests matching), else None. Multiple
    ``n`` groups can coexist if saves from different host counts landed
    on the same step; any complete group qualifies, largest first."""
    for n, idxs in sorted(_shard_groups(dirname).items(), reverse=True):
        if set(idxs) != set(range(n)):
            continue
        ok = True
        for i in range(n):
            sname, jname = _shard_file(i, n)
            spath = os.path.join(dirname, sname)
            jpath = os.path.join(dirname, jname)
            try:
                with open(jpath) as f:
                    side = json.load(f)
            except (OSError, json.JSONDecodeError):
                ok = False
                break
            nbytes = side.get("bytes")
            if nbytes is not None and os.path.getsize(spath) != nbytes:
                ok = False
                break
            if verify and side.get("sha256") is not None \
                    and _sha256_file(spath) != side["sha256"]:
                ok = False
                break
        if ok:
            return n
    return None


def _assemble_shards(dirname: str, n: int) -> Any:
    """Reassemble a complete ``partial`` save into the ordinary global
    state-dict — pure host numpy, works on ANY current host count (the
    shard count ``n`` is a property of the save, not the reader).
    Leaves named in the sidecars' ``sharded_paths`` concatenate along
    axis 0 in shard order; everything else (replicated leaves) takes
    shard 0's copy. Peak host RAM is the assembled state plus ONE
    leaf's source blocks — each leaf's slots across the shard list are
    dropped as its concat completes, so there is never a 2x-state
    transient."""
    import numpy as _np
    sharded: set = set()
    flats, treedef0 = [], None
    for i in range(n):
        sname, jname = _shard_file(i, n)
        with open(os.path.join(dirname, jname)) as f:
            sharded.update(json.load(f).get("sharded_paths") or [])
        with open(os.path.join(dirname, sname), "rb") as f:
            sd = serialization.msgpack_restore(f.read())
        kp_leaves, treedef = jax.tree_util.tree_flatten_with_path(sd)
        if treedef0 is None:
            treedef0 = treedef
        elif treedef != treedef0:
            raise CheckpointIntegrityError(
                f"partial checkpoint {dirname}: shard {i} has a "
                f"different tree structure than shard 0 — the shards "
                f"were written by incompatible runs; resume from an "
                f"older complete step")
        flats.append([list(p) for p in kp_leaves])
    out = []
    for col in range(len(flats[0])):
        kp = flats[0][col][0]
        if jax.tree_util.keystr(kp) in sharded:
            parts = [flats[i][col][1] for i in range(n)]
            for i in range(n):
                flats[i][col][1] = None      # free source before concat
            out.append(_np.concatenate(
                [_np.asarray(p) for p in parts], axis=0))
            del parts
        else:
            out.append(flats[0][col][1])
    return jax.tree_util.tree_unflatten(treedef0, out)


def verify_checkpoint(dirname: str) -> bool:
    """True iff ``dirname`` holds a restorable checkpoint.

    New-format checkpoints (sidecar carries ``sha256``) verify by content
    digest — catches truncation AND bit flips. Legacy sidecars without a
    checksum are trusted on presence (their write order put ``meta.json``
    last, so a sidecar implies the state blob completed). Sidecar-less
    directories (pre-v2, or a torn legacy write that died mid-state) fall
    back to a full msgpack parse — expensive, but only ever paid for
    legacy candidates actually under consideration.

    ``partial`` (per-host shard) saves are valid ONLY when every one of
    their ``n`` shards is present and intact — completeness is a gate,
    not a preference: a multi-host emergency save interrupted after some
    shards landed must NOT look newest-valid on the host whose shard
    completed, or resume diverges per host. An incomplete shard set
    returns False and :func:`find_checkpoint` skips back to the newest
    complete step."""
    state_path = os.path.join(dirname, "state.msgpack")
    if not os.path.isfile(state_path):
        return _complete_shard_group(dirname, verify=True) is not None
    meta_path = os.path.join(dirname, "meta.json")
    if os.path.isfile(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, json.JSONDecodeError):
            return False
        want = meta.get("sha256")
        if want is not None:
            nbytes = meta.get("bytes")
            if nbytes is not None and os.path.getsize(state_path) != nbytes:
                return False         # cheap reject before hashing
            return _sha256_file(state_path) == want
        return True                  # legacy sidecar: meta written last
    try:                             # sidecar-less legacy: parse or reject
        with open(state_path, "rb") as f:
            serialization.msgpack_restore(f.read())
        return True
    except Exception:                # truncated/garbled msgpack
        return False


def find_checkpoint(path: str, load_step: int = 0,
                    verify: bool = True) -> Optional[Tuple[str, int]]:
    """Scan numeric subdirs; pick max ``t_env`` when ``load_step == 0`` else
    the nearest to ``load_step`` (reference ``per_run.py:171-182``; ties
    resolve to the SMALLER step, deterministically). Candidates failing
    :func:`verify_checkpoint` are skipped — selection falls back to the
    next-best valid step, so one torn top checkpoint no longer kills
    resume. ``tmp.*`` staging leftovers are never candidates (non-numeric
    names)."""
    if not os.path.isdir(path):
        return None
    steps = [int(name) for name in os.listdir(path)
             if name.isdigit()
             and os.path.isdir(os.path.join(path, name))]
    if not steps:
        return None
    if load_step == 0:
        ordered = sorted(steps, reverse=True)                  # newest first
    else:
        ordered = sorted(steps, key=lambda s: (abs(s - load_step), s))
    for step in ordered:
        d = os.path.join(path, str(step))
        if not verify or verify_checkpoint(d):
            return d, step
        logger.warning(
            "find_checkpoint: skipping corrupt/torn checkpoint %s "
            "(integrity check failed) — falling back to the next valid "
            "step", d)
    logger.warning("find_checkpoint: no valid checkpoint under %s "
                   "(%d candidates, all failed verification)", path,
                   len(steps))
    return None


def prune_checkpoints(path: str, keep_last: int = 5,
                      keep_every: int = 0) -> List[int]:
    """Retention for long runs: keep the newest ``keep_last`` steps plus —
    when ``keep_every > 0`` — every step divisible by ``keep_every``
    (coarse history for post-hoc analysis); delete the rest. Also sweeps
    ``tmp.*`` staging leftovers from crashed writers. Returns the deleted
    steps. Safe to call after every save; single writer assumed.

    Multi-host: a no-op off process 0 — only the checkpoint writer prunes.
    On a shared filesystem a non-writer sweeping ``tmp.*`` would race the
    writer's in-flight staging directory (every process runs the driver's
    save cadence, but only process 0 owns the files)."""
    if jax.process_index() != 0:
        return []
    if not os.path.isdir(path):
        return []
    steps = sorted(int(n) for n in os.listdir(path)
                   if n.isdigit() and os.path.isdir(os.path.join(path, n)))
    keep = set(steps[-max(keep_last, 1):])
    if keep_every > 0:
        keep.update(s for s in steps if s % keep_every == 0)
    removed = []
    for s in steps:
        if s not in keep:
            shutil.rmtree(os.path.join(path, str(s)), ignore_errors=True)
            removed.append(s)
    for n in os.listdir(path):
        if n.startswith("tmp.") and os.path.isdir(os.path.join(path, n)):
            shutil.rmtree(os.path.join(path, n), ignore_errors=True)
    if removed:
        logger.info("prune_checkpoints: removed %d old checkpoints under "
                    "%s (kept %d)", len(removed), path, len(keep))
    return removed


def _read_meta(dirname: str) -> Optional[dict]:
    """The ``meta.json`` sidecar (None when absent) + format check."""
    meta_path = os.path.join(dirname, "meta.json")
    if not os.path.exists(meta_path):
        return None
    with open(meta_path) as f:
        meta = json.load(f)
    fmt = meta.get("format", 0)
    if fmt > FORMAT_VERSION:
        raise CheckpointFormatError(
            f"checkpoint {dirname} has format v{fmt}, newer than this "
            f"build's v{FORMAT_VERSION} — upgrade the framework to "
            f"restore it")
    return meta


def restore_host_state(dirname: str, verify: bool = True,
                       layout_target: Any = None
                       ) -> Tuple[Optional[dict], Any]:
    """Read one checkpoint to HOST memory: → ``(meta, raw_state_dict)``.

    The shared bottom half of every restore path — format/integrity
    checks plus one ``msgpack_restore`` into plain numpy leaves, with
    **no device allocation**. :func:`load_checkpoint` builds the
    classic single-placement restore on top; :func:`load_checkpoint_sharded`
    places each leaf straight onto a mesh (the DP resume path); the
    serve exporter (``serve/export.py``) picks the learner subtree out
    of ``raw`` and never touches the ring. ``verify=False`` skips the
    SHA-256 re-hash for callers that just paid it in
    :func:`find_checkpoint`. ``layout_target`` (a TrainState-like tree,
    concrete or eval_shape) opts into the replay obs-layout check
    BEFORE the multi-GiB state read — a layout mismatch then costs a
    meta.json read, not a full decode."""
    meta = _read_meta(dirname)
    if layout_target is not None:
        _check_obs_layout(meta, layout_target, dirname)
    state_path = os.path.join(dirname, "state.msgpack")
    if not os.path.isfile(state_path):
        # degraded per-host shard save (docs/RESILIENCE.md §6): valid
        # only when complete; reassembles into the ordinary global
        # state-dict on ANY current host count, so every caller above
        # this point (load_checkpoint, the sharded/elastic restores,
        # the serve exporter) reads partial saves transparently
        n = _complete_shard_group(dirname, verify=verify)
        if n is None:
            raise CheckpointIntegrityError(
                f"checkpoint {dirname} has neither state.msgpack nor a "
                f"complete shard set — an interrupted partial save; "
                f"resume from an older step (find_checkpoint skips "
                f"incomplete partial saves automatically)")
        return meta, _assemble_shards(dirname, n)
    with open(state_path, "rb") as f:
        data = f.read()
    if verify and meta is not None and meta.get("sha256") is not None:
        digest = hashlib.sha256(data).hexdigest()
        if digest != meta["sha256"]:
            raise CheckpointIntegrityError(
                f"checkpoint {dirname} fails its integrity check: "
                f"state.msgpack hashes to {digest[:12]}… but meta.json "
                f"recorded {meta['sha256'][:12]}… — the file is torn or "
                f"corrupted; resume from an older step "
                f"(find_checkpoint skips invalid checkpoints "
                f"automatically)")
    return meta, serialization.msgpack_restore(data)


def _check_obs_layout(meta: Optional[dict], target: Any,
                      dirname: str) -> None:
    """Replay-layout mismatch → a precise config instruction before any
    deserialization (works on concrete AND eval_shape templates)."""
    saved = meta.get("obs_layout") if meta else None
    configured = _obs_layout(target)
    if saved and configured and saved != configured:
        want = "true" if saved == "compact" else "false"
        raise ValueError(
            f"checkpoint {dirname} stores the replay ring with "
            f"'{saved}' obs layout but the config builds '{configured}' "
            f"storage — set replay.compact_entity_store={want} (and for "
            f"'compact' keep env_args.fast_norm=true) to resume this "
            f"checkpoint (docs/SPEC.md perf modes)")


def _inject_runner_field(raw: Any, target: Any, name: str) -> None:
    """Inject the template's ``runner.<name>`` state-dict into a raw
    tree missing the field (stepwise format migration). Abstract
    template leaves (eval_shape restore) inject fresh zeros."""
    if not (isinstance(raw, dict) and "runner" in raw
            and name not in raw["runner"]):
        return
    import numpy as _np
    host = jax.tree.map(
        lambda x: (_np.zeros(x.shape, x.dtype)
                   if isinstance(x, jax.ShapeDtypeStruct)
                   else jax.device_get(x)),
        getattr(target.runner, name))
    raw["runner"][name] = serialization.to_state_dict(host)


def _lift_population(raw: Any, target: Any) -> Any:
    """v4 → v5 graftpop shim: lift a SINGLE-MEMBER checkpoint (the bare
    TrainState state-dict every pre-population run wrote) into a
    population template — every member starts from the same restored
    state, replicated along the new leading ``(P,)`` axis, and the spec
    comes from the template (the caller's config-built grids; zeros on
    an eval_shape template). Lossless: member 0 IS the restored run.
    Keyed on STRUCTURE, not version: any single-member tree (missing
    the ``spec`` key) restoring into a ``PopState`` template lifts.

    Members 1..P-1 get their replicated ROLLOUT key (``runner.key``)
    re-salted with a per-member ``fold_in`` — the self-evolving env/
    exploration/scenario stream lives in that leaf, and a verbatim
    replica would make every member draw the SAME trajectories for the
    rest of the run, silently defeating the population's diversity
    (the same defect class pbt_step re-salts exploited members for).
    Member 0's key is untouched: member 0 IS the restored run."""
    import numpy as _np
    p = int(jax.tree_util.tree_leaves(target.spec)[0].shape[0])

    def _stack(a):
        # read-only stride-0 broadcast VIEW, deliberately not .copy():
        # the lift runs on the full host state-dict (replay ring
        # included), and P materialized host copies of a multi-GiB ring
        # would OOM the resume this shim exists to enable — the P-times
        # footprint is inherent on DEVICE, the host transient is not
        # (from_state_dict/device_put copy per leaf on transfer anyway)
        a = _np.asarray(a)
        return _np.broadcast_to(a, (p,) + a.shape)

    spec_host = jax.tree.map(
        lambda x: (_np.zeros(x.shape, x.dtype)
                   if isinstance(x, jax.ShapeDtypeStruct)
                   else _np.asarray(jax.device_get(x))), target.spec)
    stacked = jax.tree.map(_stack, raw)
    runner = stacked.get("runner") if isinstance(stacked, dict) else None
    if isinstance(runner, dict) and "key" in runner:
        k = _np.asarray(runner["key"])
        runner["key"] = _np.stack(
            [k[0]] + [_np.asarray(jax.device_get(
                jax.random.fold_in(jax.numpy.asarray(k[m]), m)))
                for m in range(1, p)])
    return {"ts": stacked, "spec": serialization.to_state_dict(spec_host)}


def _resalt_member_keys(runner: Any, members: Sequence[int]) -> None:
    """Re-salt the listed members' ROLLOUT keys (``runner.key`` in a
    stacked state-dict) with a per-member ``fold_in`` — shared logic of
    :func:`_lift_population` and :func:`_reshape_population`: any member
    whose key was REPLICATED from another's must diverge or both draw
    identical trajectories forever (the diversity defect pbt_step's
    exploit re-salt exists for)."""
    import numpy as _np
    if not (isinstance(runner, dict) and "key" in runner and members):
        return
    k = _np.array(runner["key"])          # owned copy: rows mutate below
    for m in members:
        k[m] = _np.asarray(jax.device_get(
            jax.random.fold_in(jax.numpy.asarray(k[m]), m)))
    runner["key"] = k


def _reshape_population(raw: Any, target: Any,
                        member_ranking: Optional[Sequence[int]] = None
                        ) -> Any:
    """Elastic v5 → v5 shim (generalizes :func:`_lift_population`, which
    only covers P=1 → P): resize a population state-dict's leading
    ``(P_src,)`` member axis to the template's ``P_dst``.

    Shrink keeps ``member_ranking[:P_dst]`` when a ranking is given (the
    save-side stamp records one from the host EMA return stats when they
    exist — docs/RESILIENCE.md §6) else the member prefix; the prefix
    path slices views, no host copy. Grow keeps all ``P_src`` members
    and replicates member ``m % P_src`` into each new slot ``m``, with
    the new members' rollout keys ``fold_in``-re-salted so no two
    members share streams. Both ``ts`` and ``spec`` rows move together —
    a surviving member keeps its own hyperparameters."""
    import numpy as _np
    p_dst = int(jax.tree_util.tree_leaves(target.spec)[0].shape[0])
    p_src = int(_np.asarray(
        jax.tree_util.tree_leaves(raw["spec"])[0]).shape[0])
    if p_src == p_dst:
        return raw
    if p_dst < p_src:
        if member_ranking is not None:
            idx = [int(i) for i in list(member_ranking)[:p_dst]]
            if sorted(set(idx)) != sorted(idx) or not all(
                    0 <= i < p_src for i in idx):
                raise ValueError(
                    f"member_ranking {list(member_ranking)!r} is not a "
                    f"permutation prefix of range({p_src}) — cannot "
                    f"shrink the population to P={p_dst}")
        else:
            idx = list(range(p_dst))
        salted: List[int] = []       # survivors keep their own streams
    else:
        idx = list(range(p_src)) + [m % p_src for m in range(p_src, p_dst)]
        salted = list(range(p_src, p_dst))

    prefix = idx == list(range(p_dst))

    def _take(a):
        a = _np.asarray(a)
        if prefix:
            return a[:p_dst]         # stride view — no host copy
        return _np.take(a, _np.asarray(idx), axis=0)

    out = {"ts": jax.tree.map(_take, raw["ts"]),
           "spec": jax.tree.map(_take, raw["spec"])}
    _resalt_member_keys(out["ts"].get("runner")
                        if isinstance(out["ts"], dict) else None, salted)
    logger.info(
        "_reshape_population: %s P=%d -> P=%d (members %s%s)",
        "shrank" if p_dst < p_src else "grew", p_src, p_dst, idx,
        f", re-salted {salted}" if salted else "")
    return out


def _extract_member(raw: Any,
                    member_ranking: Optional[Sequence[int]] = None) -> Any:
    """Elastic v5 → v4 shim: pull ONE member (the ranking's best when
    given, else member 0) out of a population state-dict so a population
    run restores into a bare-TrainState template — the P → classic leg
    of the elastic matrix. Per-leaf axis-0 indexing returns views; the
    spec rows are dropped (a classic run has no PBT grids)."""
    import numpy as _np
    m = int(member_ranking[0]) if member_ranking else 0
    return jax.tree.map(lambda a: _np.asarray(a)[m], raw["ts"])


def _migrate_raw(meta: Optional[dict], raw: Any, target: Any) -> Any:
    """Stepwise format migrations, each lossless:

    * v2 → v3 added ``RunnerState.rscale``. No v2 run could have had
      reward_scaling on (the field did not exist), so injecting the
      template's reward-scale state-dict restores replay contents,
      normalizer stats, and RNG state exactly.
    * v3 → v4 added ``RunnerState.env_params`` (graftworld scenario
      instances, envs/mec_offload.EnvParams). The rollout RESAMPLES
      env_params at every episode start, so the injected template
      values (the caller's freshly-initialized scenario draw; zeros on
      an eval_shape template) are consumed by nothing — a v3 run
      restores into the v4 tree with identical training behavior.
    * v4 → v5 wrapped population runs' state in ``population.PopState``
      (``_lift_population`` above): a single-member checkpoint restores
      into a population template with every member replicated from it.

    Meta-less checkpoints (pre-v2, or a deleted sidecar) take the same
    path: injection is conditional on the field actually being absent,
    so a current-format tree without its meta.json still restores
    unmodified."""
    fmt = meta.get("format", 0) if meta is not None else 0
    pop_target = (hasattr(target, "ts") and hasattr(target, "spec")
                  and isinstance(raw, dict) and "spec" not in raw)
    if pop_target:
        # the earlier stepwise injections below run against a
        # SINGLE-member view of the stacked template (strip the (P,)
        # axis): the raw tree is still single-member at this point
        inject_target = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype),
            target.ts)
    else:
        inject_target = target
    if fmt < 3:
        _inject_runner_field(raw, inject_target, "rscale")
    if fmt < 4:
        _inject_runner_field(raw, inject_target, "env_params")
    if pop_target:
        raw = _lift_population(raw, target)
    return raw


def _check_leaf_shapes(target: Any, restored: Any, dirname: str) -> None:
    """flax does not shape-validate on restore: a checkpoint from a
    different config (env lanes, replay capacity, DP shapes) would
    silently land wrong-shaped arrays that only explode later inside
    jit — reject it here so callers can fall back to the model-only
    restore (run.evaluate_sequential does)."""
    t_leaves = jax.tree_util.tree_leaves_with_path(target)
    r_leaves = jax.tree_util.tree_leaves_with_path(restored)
    bad = [
        (jax.tree_util.keystr(kp), getattr(lt, "shape", None),
         getattr(lr, "shape", None))
        for (kp, lt), (_, lr) in zip(t_leaves, r_leaves)
        if getattr(lt, "shape", None) != getattr(lr, "shape", None)]
    if bad:
        k, st, sr = bad[0]
        raise ValueError(
            f"checkpoint {dirname} was written under a different config: "
            f"{len(bad)} leaves mismatch the template (first: {k} stored "
            f"{sr} vs configured {st}). Use load_learner_state for "
            f"model-only restore (reference semantics).")


def _restore_into(dirname: str, target: Any, verify: bool) -> Any:
    """Shared restore core: host read (obs-layout checked from the
    sidecar BEFORE the state decode) → migration → structure match →
    per-leaf shape validation. ``target`` may be concrete
    (:func:`load_checkpoint`) or an eval_shape template
    (:func:`load_checkpoint_sharded`) — either way the returned leaves
    are the stored host numpy arrays."""
    meta, raw = restore_host_state(dirname, verify=verify,
                                   layout_target=target)
    try:
        restored = serialization.from_state_dict(
            target, _migrate_raw(meta, raw, target))
    except (KeyError, ValueError) as e:
        raise ValueError(
            f"checkpoint {dirname} does not match the configured train-state "
            f"structure: {e}. A common cause is the replay storage layout — "
            f"checkpoints written before/after the compact entity storage "
            f"default need replay.compact_entity_store toggled to match "
            f"(docs/SPEC.md perf modes)") from e
    _check_leaf_shapes(target, restored, dirname)
    return restored


def load_checkpoint(dirname: str, target: Any, verify: bool = True) -> Any:
    """Restore into a template pytree of the same structure. The
    ``meta.json`` sidecar (when present) turns a replay-layout mismatch
    into a precise config instruction before the state blob is even
    read, and its checksum (when present) turns silent corruption into
    :class:`CheckpointIntegrityError` before flax sees a single byte.
    Callers that just selected ``dirname`` via :func:`find_checkpoint`
    already paid the SHA-256 pass there and may set ``verify=False`` to
    skip re-hashing (one full read of a multi-GiB ring is real time)."""
    return _restore_into(dirname, target, verify)


def load_checkpoint_sharded(dirname: str, template: Any, shardings: Any,
                            verify: bool = True) -> Any:
    """Restore into an ABSTRACT template (a ``jax.eval_shape`` pytree),
    placing each leaf directly under its sharding — the resume-side
    analog of ``DataParallel.init_sharded`` (ADVICE r5): the classic
    ``init → load_checkpoint → dp.shard`` sequence materializes the
    full TrainState (notably the replay ring) on ONE device before the
    mesh placement, which is an OOM at config-5 ring sizes. Here the
    state exists host-side as numpy only, and each leaf is
    ``device_put`` under its ``shardings`` entry one at a time — the
    host copy of every placed leaf is dropped immediately, so peak
    device memory is the sharded state plus one leaf, never 1 + 1/N
    rings. ``template`` and ``shardings`` must be structure-identical
    (``DataParallel.state_shardings(template)`` builds the latter)."""
    return _place_streamed(_restore_into(dirname, template, verify),
                           shardings)


def _place_streamed(restored: Any, shardings: Any) -> Any:
    """The leaf-streaming placement core (ADVICE r5) shared by
    :func:`load_checkpoint_sharded` and :func:`restore_elastic`: each
    host leaf is ``device_put`` under its sharding one at a time and its
    host copy dropped immediately — peak device memory is the sharded
    state plus ONE leaf, never a full single-device materialization."""
    flat, treedef = jax.tree_util.tree_flatten(restored)
    # the flat list is now the ONLY holder of the host leaves — without
    # this, `restored` would pin every leaf and the per-leaf free below
    # would free nothing
    del restored
    flat_sh = jax.tree_util.tree_flatten(shardings)[0]
    if len(flat_sh) != len(flat):
        raise ValueError(
            f"shardings tree has {len(flat_sh)} leaves but the template "
            f"has {len(flat)} — build it with state_shardings(template)")
    placed = []
    for i, sh in enumerate(flat_sh):
        placed.append(jax.device_put(flat[i], sh))
        flat[i] = None               # leaf streaming: free the host copy
    return jax.tree_util.tree_unflatten(treedef, placed)


def restore_elastic(dirname: str, template: Any, shardings: Any = None,
                    verify: bool = True,
                    member_ranking: Optional[Sequence[int]] = None) -> Any:
    """Restore ANY v3–v5 checkpoint into the CURRENT run's topology
    (docs/RESILIENCE.md §6) — the elastic superset of
    :func:`load_checkpoint` / :func:`load_checkpoint_sharded`:

    * **format**: the stepwise v2→v3→v4→v5 shims of :func:`_migrate_raw`
      run first, exactly as on the rigid paths;
    * **population**: a ``(P_src,)`` checkpoint resizes into a
      ``(P_dst,)`` template via :func:`_reshape_population` (shrink
      keeps the stamped best-ranked members else the prefix; grow
      replicates with ``fold_in``-re-salted rollout keys), and a
      population checkpoint restores into a BARE TrainState template via
      :func:`_extract_member`;
    * **devices / loop shape**: the state-dict is topology-free (a
      complete save holds the global state; a partial save reassembles
      in :func:`restore_host_state`), so dp N↔M and classic↔Sebulba are
      pure placement — pass the CURRENT mesh's ``shardings`` and each
      leaf streams straight to its new placement
      (:func:`_place_streamed`, no full-tree single-device transient);
      with ``shardings=None`` leaves restore host-side as numpy exactly
      like :func:`load_checkpoint`.

    ``member_ranking`` (best first) overrides the ranking stamped into
    ``meta.json`` by the save side; when neither exists a shrink keeps
    the member prefix. Fires the ``checkpoint.elastic`` resilience hook
    after the host read so chaos tests can fault the routing boundary
    itself."""
    meta, raw = restore_host_state(dirname, verify=verify,
                                   layout_target=template)
    # fault-injection point (docs/RESILIENCE.md §4): the elastic
    # restore/reshape boundary — after the (verified) host read, before
    # any reshaping or device placement
    resilience.fire("checkpoint.elastic", dirname=dirname,
                    format=(meta or {}).get("format"))
    if member_ranking is None and meta is not None:
        member_ranking = (meta.get("topology") or {}).get("member_ranking")
    pop_target = hasattr(template, "ts") and hasattr(template, "spec")
    if not pop_target and isinstance(raw, dict) and "spec" in raw:
        raw = _extract_member(raw, member_ranking)
    raw = _migrate_raw(meta, raw, template)
    if pop_target and isinstance(raw, dict) and "spec" in raw:
        raw = _reshape_population(raw, template, member_ranking)
    try:
        restored = serialization.from_state_dict(template, raw)
    except (KeyError, ValueError) as e:
        raise ValueError(
            f"checkpoint {dirname} does not match the configured "
            f"train-state structure even after elastic reshaping: {e} "
            f"(docs/RESILIENCE.md §6)") from e
    _check_leaf_shapes(template, restored, dirname)
    if shardings is None:
        return restored
    return _place_streamed(restored, shardings)


def load_learner_state(dirname: str, target: Any) -> Any:
    """Restore ONLY the learner subtree (params/target/optimizer) into a
    full train-state template — shape-independent of the runner/replay
    config, so a model trained at one scale (or on a DP mesh) evaluates
    under any other. Matches the reference's model-only checkpoint
    semantics (``/root/reference/per_run.py:185-187``): runner-side
    normalizer statistics start fresh. Reads through
    :func:`restore_host_state` so partial (per-host shard) saves
    reassemble transparently; the integrity re-hash is skipped — the
    caller just paid it in :func:`find_checkpoint`."""
    _, raw = restore_host_state(dirname, verify=False)
    if isinstance(raw, dict) and "spec" in raw:
        raw = _extract_member(raw)   # population save: member 0's model
    learner = serialization.from_state_dict(target.learner, raw["learner"])
    # same silent-wrong-shape hazard as the full restore: a model-config
    # mismatch (e.g. different emb) must fail HERE with the leaf named,
    # not later inside jit — and for params there is no further fallback
    t_leaves = jax.tree_util.tree_leaves_with_path(target.learner)
    r_leaves = jax.tree_util.tree_leaves_with_path(learner)
    bad = [
        (jax.tree_util.keystr(kp), getattr(lt, "shape", None),
         getattr(lr, "shape", None))
        for (kp, lt), (_, lr) in zip(t_leaves, r_leaves)
        if getattr(lt, "shape", None) != getattr(lr, "shape", None)]
    if bad:
        k, st, sr = bad[0]
        raise ValueError(
            f"checkpoint {dirname} holds a different MODEL than the "
            f"configured one: {len(bad)} learner leaves mismatch (first: "
            f"{k} stored {sr} vs configured {st}); fix the model config "
            f"to match the checkpoint")
    return target.replace(learner=learner)
