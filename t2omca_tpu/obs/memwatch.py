"""HBM memwatch: per-device memory snapshots with phase attribution.

The repo's memory story so far is all *predictive*: ``bench.py --hbm``
sizes residents from shapes, graftprog's GP303 ratchets the compiled
programs' peak (temp + output-alias) at the frozen audit config. What
dies on a chip is the *live* number — and when it does, nothing says
what held HBM at the time. This module closes that gap:

* :class:`MemWatch` — reads ``device.memory_stats()`` (the PJRT
  allocator counters: ``bytes_in_use``, ``peak_bytes_in_use``, ...)
  at PHASE BOUNDARIES the driver already owns (startup, log cadence,
  checkpoint save), tracking per-device high water **attributed to the
  phase that first reached it** — so an OOM or wedge post-mortem says
  "the high water was N GiB, first seen at the ``checkpoint.save``
  boundary at t_env=M", not just a number.
* the report rides the existing artifacts: the driver merges
  ``report()`` into ``flight_recorder.json`` and
  ``stall_diagnosis.json`` (``spans.SpanRecorder.persist(extra=)`` /
  ``watchdog.write_diagnosis(extra=)``). During a stall only the
  CACHED high water is reported — a snapshot would touch the wedged
  backend from the diagnostic path.
* :func:`audit_peak_budgets` — the graftprog GP303 peaks
  (``analysis/programs.json``, jax-free read) ride along in the report
  as ``budgets_audit_peak_bytes`` so the post-mortem can line the live
  number up against what the *compiled programs* claim to need.
  Honesty: the budgets are measured at the frozen tiny audit config —
  they anchor "which program is the HBM hog", not an absolute bound at
  run scale.

Allocator support varies: TPU/GPU PJRT clients report real counters,
the CPU client usually returns ``None`` — every read degrades to
"unsupported" (``supported: false`` in the report), never a crash.
jax is imported lazily inside ``snapshot`` so importing this module
stays free for the jax-free CLIs.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from .spans import NULL_RECORDER

#: memory_stats keys copied into each snapshot when present (allocator
#: dialects differ; absent keys are simply omitted)
_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
              "largest_free_block_bytes", "num_allocs")


def audit_peak_budgets(programs_json: Optional[str] = None
                       ) -> Dict[str, float]:
    """→ ``{program: peak_bytes}`` for every compiled-level entry in
    graftprog's baseline (jax-free; empty on any read problem — the
    budgets decorate the report, they are not load-bearing)."""
    try:
        from ..analysis.baseline import DEFAULT_PROGRAMS, load_programs
        base = load_programs(programs_json or DEFAULT_PROGRAMS)
        return {name: float(entry["peak_bytes"])
                for name, entry in base.get("programs", {}).items()
                if isinstance(entry, dict) and "peak_bytes" in entry}
    except Exception:  # noqa: BLE001 — decoration only
        return {}


class MemWatch:
    """Phase-boundary HBM snapshots + high-water attribution. Thread-
    safe: the driver snapshots from the main thread while the stall
    path reads ``report()`` from the watchdog thread."""

    enabled = True

    def __init__(self, rec=NULL_RECORDER,
                 budgets: Optional[Dict[str, float]] = None,
                 _devices: Optional[Callable[[], list]] = None) -> None:
        self._rec = rec
        self._budgets = dict(budgets or {})
        self._devices_fn = _devices          # test hook (fake devices)
        self._lock = threading.Lock()
        # device id -> {"bytes_in_use", ..., "high_water_bytes",
        #               "high_water_phase", "high_water_t_env"}
        self._dev: Dict[str, Dict[str, Any]] = {}
        self.snapshots = 0
        #: None until the first snapshot; False when no device reports
        #: allocator stats (CPU client) — the report states it instead
        #: of showing an empty table with no explanation
        self.supported: Optional[bool] = None

    def _devices(self) -> list:
        if self._devices_fn is not None:
            return self._devices_fn()
        import jax
        return jax.local_devices()

    def snapshot(self, phase: str, t_env: int = 0
                 ) -> Optional[Dict[str, Dict[str, int]]]:
        """One per-device read at a phase boundary. Returns the raw
        per-device stats (None when unsupported) and folds the high
        water — attributed to ``phase``/``t_env`` when this read is the
        new maximum. Spanned (``memwatch.snapshot``) so its cost shows
        up in the phase table like any other boundary."""
        with self._rec.span("memwatch.snapshot", t_env=t_env, at=phase):
            try:
                devices = self._devices()
            except Exception:  # noqa: BLE001 — telemetry only
                with self._lock:
                    # a transient device-list failure (backend teardown
                    # racing the final snapshot) must not erase the
                    # verdict earlier successful reads earned — the
                    # report would say "unsupported" over populated rows
                    if not self._dev:
                        self.supported = False
                return None
            out: Dict[str, Dict[str, int]] = {}
            for i, d in enumerate(devices):
                try:
                    ms = d.memory_stats()
                except Exception:  # noqa: BLE001 — per-device degrade
                    ms = None
                if not ms:
                    continue
                did = str(getattr(d, "id", i))
                snap = {k: int(ms[k]) for k in _STAT_KEYS if k in ms}
                out[did] = snap
            with self._lock:
                self.snapshots += 1
                self.supported = bool(out) or bool(self._dev)
                for did, snap in out.items():
                    rec = self._dev.setdefault(did, {
                        "high_water_bytes": -1,
                        "high_water_phase": None,
                        "high_water_t_env": 0})
                    rec.update(snap)
                    # prefer the allocator's own peak counter (it sees
                    # between-boundary spikes); fall back to in-use
                    hw = snap.get("peak_bytes_in_use",
                                  snap.get("bytes_in_use", 0))
                    if hw > rec["high_water_bytes"]:
                        rec["high_water_bytes"] = hw
                        rec["high_water_phase"] = phase
                        rec["high_water_t_env"] = int(t_env)
            return out or None

    def report(self) -> Dict[str, Any]:
        """The post-mortem block merged into flight/stall artifacts.
        Pure cached state — safe to call from the stall path over a
        wedged backend (no device reads)."""
        with self._lock:
            devices = {did: dict(rec) for did, rec in self._dev.items()}
            return {"supported": self.supported,
                    "snapshots": self.snapshots,
                    "devices": devices,
                    # graftprog GP303 peaks at the frozen AUDIT config —
                    # a which-program anchor, not a run-scale bound
                    "budgets_audit_peak_bytes": dict(self._budgets)}


class NullMemWatch:
    """The disabled memwatch: every operation a no-op, so call sites
    stay unconditional (the NullRecorder pattern)."""

    enabled = False
    supported = None
    snapshots = 0

    def snapshot(self, phase: str, t_env: int = 0):
        return None

    def report(self) -> Dict[str, Any]:
        return {}


NULL_MEMWATCH = NullMemWatch()


def make_memwatch(obs_cfg, rec=NULL_RECORDER):
    """:data:`NULL_MEMWATCH` unless ``obs.enabled`` AND
    ``obs.memwatch`` (sanity_check enforces the pairing)."""
    if obs_cfg is None or not getattr(obs_cfg, "enabled", False) \
            or not getattr(obs_cfg, "memwatch", False):
        return NULL_MEMWATCH
    return MemWatch(rec=rec, budgets=audit_peak_budgets())
