"""graftpulse timeline: the longitudinal perf-trajectory table.

The repo carries seven BENCH_r*.json records spanning every perf PR,
in three historical shapes (bare ``{metric, value, unit,
vs_baseline}`` lines in r01/r02, error-only partials in r03, schema'd
partials with span summaries in r06/r07), plus per-run
``metrics.jsonl`` streams — and nothing that reads them TOGETHER. The
question ROADMAP open item 1 keeps asking ("what is the trajectory,
and which rounds are real numbers vs wedged partials?") has been
answered by hand every round. This CLI answers it mechanically:

    python -m t2omca_tpu.obs timeline [BENCH_r*.json ...] \
        [--runs <run_dir> ...] [--json]

One row per BENCH record (wrapper ``{n, cmd, rc, tail, parsed}`` or a
bare record line — every historical shape tolerated), one row per run
directory (newest ``env_steps_per_sec`` from its ``metrics.jsonl``),
rendered measured-vs-wedged so a partial can never masquerade as a
number. Torn final JSONL lines (the artifact a killed run leaves) are
skipped with a warning, never raised on.

Deliberately **jax-free** (pinned by a subprocess test, like the
report CLI): the trajectory question gets asked from hosts that cannot
initialize a backend — that is what most of the table's rows died of.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

from ..utils.ioutil import read_jsonl_tolerant

#: record keys surfaced in the note column when present — the leg
#: identity that distinguishes one matrix record from another
_CONTEXT_KEYS = ("config", "superstep", "kernels", "acting", "dp",
                 "population", "sebulba", "leg", "n_envs")


def _warn(msg: str) -> None:
    print(f"graftpulse: warning: {msg}", file=sys.stderr)


def _extract_record(data: Any) -> Optional[dict]:
    """The measurement record inside one BENCH_r*.json: the round
    driver's wrapper carries it under ``parsed`` (possibly null —
    fall back to the last JSON-looking stdout line in ``tail``); a
    bare record file IS the record."""
    if not isinstance(data, dict):
        return None
    if "parsed" in data or "tail" in data or "cmd" in data:
        rec = data.get("parsed")
        if isinstance(rec, dict):
            return rec
        tail = data.get("tail")
        if isinstance(tail, str):
            for line in reversed(tail.strip().splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        return json.loads(line)
                    except ValueError:
                        continue
        return None
    if "metric" in data or "value" in data:
        return data
    return None


def bench_row(path: str) -> Dict[str, Any]:
    """→ one timeline row for a BENCH record file (never raises: an
    unreadable file becomes an ``unreadable`` row — the table must
    render the whole series even when one round's artifact is junk)."""
    name = os.path.basename(path)
    if name.endswith(".json"):
        name = name[:-5]
    row: Dict[str, Any] = {"kind": "bench", "name": name, "n": None,
                           "status": "unreadable", "metric": None,
                           "value": None, "unit": None,
                           "vs_baseline": None, "platform": None,
                           "schema": None, "note": ""}
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        row["note"] = f"unreadable: {e}"
        return row
    if isinstance(data, dict):
        row["n"] = data.get("n")
    rec = _extract_record(data)
    if rec is None:
        row["status"] = "no-record"
        rc = data.get("rc") if isinstance(data, dict) else None
        row["note"] = f"no parseable record (rc={rc})"
        return row
    row["metric"] = rec.get("metric")
    row["value"] = rec.get("value")
    row["unit"] = rec.get("unit")
    row["vs_baseline"] = rec.get("vs_baseline")
    row["schema"] = rec.get("schema")
    row["platform"] = rec.get("platform") or rec.get("backend")
    if row["value"] is None:
        # the wedged-partial class (r03–r07): value never landed — the
        # note says which phase died, which is the record's whole point
        row["status"] = "wedged"
        note = []
        if rec.get("phase"):
            note.append(f"phase={rec['phase']}")
        if rec.get("error"):
            note.append(str(rec["error"])[:80])
        row["note"] = " ".join(note) or "no value recorded"
    else:
        row["status"] = "measured"
        ctx = [f"{k}={rec[k]}" for k in _CONTEXT_KEYS
               if rec.get(k) not in (None, False)]
        row["note"] = " ".join(ctx)
    return row


def run_rows(run_dir: str) -> List[Dict[str, Any]]:
    """→ timeline rows for one recorded run directory: the newest
    ``env_steps_per_sec`` from its ``metrics.jsonl`` — torn-tolerant,
    jax-free. (Serving latency lives in BENCH ``--serve`` records, not
    in run-dir metrics — those join the table as bench rows.)"""
    path = os.path.join(run_dir, "metrics.jsonl")
    name = os.path.basename(os.path.normpath(run_dir))
    base = {"kind": "run", "name": name, "n": None, "metric": None,
            "value": None, "unit": None, "vs_baseline": None,
            "platform": None, "schema": None, "note": ""}
    if not os.path.exists(path):
        return [dict(base, status="no-metrics",
                     note="no metrics.jsonl in run dir")]
    try:
        events = read_jsonl_tolerant(
            path, on_bad=lambda ln, last: _warn(
                f"{path} line {ln} unparseable"
                f"{' (torn tail from a killed run?)' if last else ''}"
                f" — skipped"))
    except OSError as e:
        return [dict(base, status="unreadable", note=str(e))]
    newest: Dict[str, Any] = {}
    t_max = 0
    for ev in events:
        if not isinstance(ev, dict):
            continue        # a corrupt line can parse to a bare scalar
        key = ev.get("key")
        if isinstance(key, str):
            newest[key] = ev.get("value")
            t = ev.get("t")
            if isinstance(t, (int, float)):
                t_max = max(t_max, int(t))
    if "env_steps_per_sec" not in newest:
        return [dict(base, status="no-rate",
                     note=f"{len(events)} metric events, no "
                          f"env_steps_per_sec (run died before the "
                          f"second log cadence?)")]
    return [dict(base, status="run", metric="env_steps_per_sec",
                 value=newest["env_steps_per_sec"],
                 unit="env-steps/s (live)",
                 note=f"newest log cadence at t_env={t_max}")]


def _fmt(v, nd=1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.{nd}f}"
    return str(v)


def render(rows: List[Dict[str, Any]]) -> str:
    lines: List[str] = []
    lines.append("graftpulse timeline — perf trajectory "
                 "(BENCH records + run metrics)")
    hdr = (f"{'record':<22}{'status':<11}{'metric':<26}{'value':>12}"
           f"{'vs_base':>9}  {'platform':<9}{'note'}")
    lines.append(hdr)
    lines.append("-" * max(len(hdr), 100))
    for r in rows:
        lines.append(
            f"{r['name']:<22}{r['status']:<11}"
            f"{(r['metric'] or '-'):<26}{_fmt(r['value']):>12}"
            f"{_fmt(r['vs_baseline'], 3):>9}  "
            f"{(r['platform'] or '-'):<9}{r['note']}")
    measured = sum(1 for r in rows if r["status"] == "measured")
    wedged = sum(1 for r in rows if r["status"] == "wedged")
    bench_n = sum(1 for r in rows if r["kind"] == "bench")
    lines.append("")
    lines.append(f"{measured}/{bench_n} bench records carry a measured "
                 f"value; {wedged} wedged partial(s)"
                 + (" — the r03+ backend-init class, ROADMAP open "
                    "item 1 (bench.py --daemon waits those out)"
                    if wedged else ""))
    return "\n".join(lines)


def timeline_main(paths: List[str], runs: List[str],
                  as_json: bool = False) -> int:
    """The ``timeline`` subcommand body. Exit 0 = table printed
    (wedged rows are CONTENT, not errors), 2 = nothing to read."""
    if not paths and not runs:
        # bare invocation: the repo-root default. With --runs alone the
        # caller asked about runs, not the cwd's records
        paths = sorted(_glob.glob("BENCH_r*.json"))
    rows: List[Dict[str, Any]] = []
    bench = sorted(paths, key=lambda p: (os.path.basename(p), p))
    for p in bench:
        rows.append(bench_row(p))
    # stable longitudinal order: the round counter when present wins
    # over filename (BENCH_r10 must sort after BENCH_r9)
    rows.sort(key=lambda r: (r["n"] if isinstance(r["n"], int)
                             else 10**9, r["name"]))
    for rd in runs:
        rows.extend(run_rows(rd))
    if not rows:
        print("graftpulse: error: no BENCH_r*.json found and no --runs "
              "given — pass record paths or run from the repo root",
              file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps({"version": 1, "rows": rows}))
    else:
        print(render(rows))
    return 0
