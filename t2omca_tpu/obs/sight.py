"""graftsight: in-graph learning-dynamics telemetry + RL-health detectors.

graftscope (spans) and graftpulse (live endpoint) made the *systems*
layer observable; the *learning* layer still emitted five scalars
(``learners/qmix_learner.py``) — nobody could tell a healthy campaign
from one whose PER priorities collapsed or whose mixer attention
saturated until the return curve flatlined hours later. This module is
the learning half (docs/OBSERVABILITY.md §6):

* **in-graph diagnostics** — helpers the train step calls when
  ``obs.sight.enabled`` (a STATIC config gate: off means byte-identical
  programs, pinned by graftprog's fingerprints). Everything reduces ON
  DEVICE into ``train_info`` — per-module gradient/param-update norms
  (agent transformer vs mixer vs embeddings), fixed-bin masked
  histograms of TD error / chosen Q / targets, PER importance-weight
  effective sample size + priority-distribution entropy, per-layer
  attention entropy (one probe timestep through the folded qslice
  blocks), and target-network drift — and rides the driver's EXISTING
  log-cadence ``fetch.train_infos`` round trip: the Podracer/Anakin
  cost profile (fold diagnostics into the already-donated program so
  they ride the existing dispatch for free), zero extra dispatches and
  zero extra device→host syncs (pinned by compile-budget/no-transfer
  tests).
* **:class:`SightMonitor`** — host-side windowed detectors over the
  fetched stream: loss plateau, Q-value divergence, PER priority
  collapse, attention collapse, per-module gradient starvation. Each
  registers a pulse ``/healthz`` check (the endpoint flips 503 naming
  the verdict), emits a flight-recorder mark on trip, and folds its
  verdict into ``stall_diagnosis.json`` via the driver's stall extras.
* **learning CLI** — ``python -m t2omca_tpu.obs learning <run_dir>``:
  JAX-FREE post-mortem renderer of the learning-health table, detector
  verdicts and per-scenario-slice learning curves from the run's
  ``metrics.jsonl`` (via the tolerant reader — killed runs leave torn
  tails).

Import contract: this module is stdlib+numpy at import time (the
jax-free CLI path); every in-graph helper pulls jax/optax lazily inside
its body, the ``analysis/guards.py`` pattern.
"""

from __future__ import annotations

import os
import sys
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

#: tiny epsilon for entropy/ratio denominators (f32-safe)
_EPS = 1e-12

#: detector names — the ``/healthz`` check ids are ``sight-<name>``,
#: the logged alert keys ``sight_alert_<name>`` (docs/OBSERVABILITY.md
#: §6 healthz table)
DETECTORS = ("loss_plateau", "q_divergence", "priority_collapse",
             "attention_collapse", "grad_starvation")


def enabled(cfg) -> bool:
    """The static gate every call site checks (TrainConfig in)."""
    return bool(cfg.obs.sight.enabled)


def module_group_names(cfg) -> Tuple[str, ...]:
    """Static per-config grouping of the param tree for the per-module
    norm breakdown: the agent transformer stack, everything else in the
    agent (feat embedding + q head + rnn cells = ``embed``), and the
    mixer. Derived from the CONFIG, not the tree, so
    ``train_info_zeros`` can mirror the emitted keys aval-exactly
    (VDN is parameterless — no mixer group to starve)."""
    names = []
    if cfg.agent == "transformer":
        names.append("agent_tf")
    names.append("embed")
    if cfg.mixer != "vdn":
        names.append("mixer")
    return tuple(names)


def module_groups(cfg, tree) -> Dict[str, list]:
    """Split a ``{"agent": variables, "mixer": variables}`` tree (params
    / grads / optax updates — same structure) into the
    ``module_group_names`` leaf lists."""
    import jax
    agent = tree["agent"]
    agent = agent.get("params", agent) if isinstance(agent, dict) else agent
    groups: Dict[str, list] = {}
    if cfg.agent == "transformer":
        groups["agent_tf"] = jax.tree.leaves(agent["transformer"])
        rest = {k: v for k, v in agent.items() if k != "transformer"}
    else:
        rest = agent
    groups["embed"] = jax.tree.leaves(rest)
    if cfg.mixer != "vdn":
        groups["mixer"] = jax.tree.leaves(tree["mixer"])
    return groups


def _global_norm(leaves) -> "object":
    """f32 global L2 norm over a leaf list (optax.global_norm accepts
    any pytree; the f32 lift keeps bf16 configs from squashing tiny
    gradients to zero inside the reduction)."""
    import jax.numpy as jnp
    import optax
    return optax.global_norm([x.astype(jnp.float32) for x in leaves])


def masked_histogram(x, mask, lo: float, hi: float, bins: int):
    """Fixed-bin masked histogram as one scatter-add: ``x`` and
    ``mask`` broadcast-compatible, result a ``(bins,)`` f32 FRACTION
    vector (sums to 1 over the masked mass; outliers clip into the edge
    bins — an edge pileup is the divergence signal, never silently
    dropped)."""
    import jax.numpy as jnp
    x = jnp.asarray(x, jnp.float32)
    m = jnp.broadcast_to(jnp.asarray(mask, jnp.float32), x.shape)
    idx = jnp.clip(((x - lo) / (hi - lo) * bins).astype(jnp.int32),
                   0, bins - 1)
    counts = jnp.zeros((bins,), jnp.float32).at[
        idx.reshape(-1)].add(m.reshape(-1))
    return counts / jnp.maximum(counts.sum(), 1.0)


def learner_train_info(cfg, grads, updates, params, target_params,
                       weights) -> dict:
    """The train-step tail's sight block (``QMixLearner.train``):
    per-module gradient and update norms, importance-weight effective
    sample size (fraction of batch), and target-network drift
    (relative param distance to the target copy)."""
    import jax.numpy as jnp
    info = {}
    for name, leaves in module_groups(cfg, grads).items():
        info[f"sight_grad_norm_{name}"] = _global_norm(leaves)
    for name, leaves in module_groups(cfg, updates).items():
        info[f"sight_update_norm_{name}"] = _global_norm(leaves)
    w = jnp.asarray(weights, jnp.float32)
    s1, s2 = w.sum(), (w * w).sum()
    info["sight_per_ess"] = (s1 * s1) / (w.shape[0]
                                         * jnp.maximum(s2, _EPS))
    import jax
    diff = jax.tree.map(lambda p, t: p.astype(jnp.float32)
                        - t.astype(jnp.float32), params, target_params)
    info["sight_target_drift"] = (
        _global_norm(jax.tree.leaves(diff))
        / jnp.maximum(_global_norm(jax.tree.leaves(target_params)), _EPS))
    return info


def loss_sight_info(sight_cfg, td, chosen, targets, mask) -> dict:
    """The loss body's sight block (``QMixLearner._loss``): fixed-bin
    masked histograms of the TD error, the chosen (taken) Qs and the
    bootstrap targets — the value-scale fingerprints a blow-up or a
    dead-value collapse shows up in first. All inputs pre-detached by
    the caller (``stop_gradient``) so the probe never touches the
    backward pass."""
    b, q = float(sight_cfg.td_range), float(sight_cfg.q_range)
    n = int(sight_cfg.bins)
    return {
        "sight_td_hist": masked_histogram(td, mask, -b, b, n),
        "sight_q_taken_hist": masked_histogram(
            chosen, mask[..., None], -q, q, n),
        "sight_target_hist": masked_histogram(targets, mask, -q, q, n),
    }


def attention_entropies(folded_tf: dict, k0, x0, *, emb: int, heads: int,
                        depth: int, dtype):
    """Per-layer mean attention entropy of ``x0``'s query rows against
    the pinned layer-0 keys ``k0`` — the ``transformer_rows`` math
    (``ops/query_slice.py``) with the softmax distribution kept long
    enough to reduce its entropy. Returns ``(depth,)`` f32 entropies
    NORMALIZED by ``log(n_keys)`` (1 = uniform attention, 0 = every
    head a delta function — the collapse the detector watches).
    Costs one probe's worth of attention per layer; callers feed ONE
    timestep, so this is ~1/T of a single unroll layer."""
    import jax
    import jax.numpy as jnp

    from ..ops.query_slice import _block_tail
    s, r, _ = x0.shape
    t_k = k0.shape[1]
    ents = []
    for i in range(depth):
        bp = folded_tf["blocks"][i]
        qp = jnp.dot(x0.reshape(s * r, emb), bp["wqk"],
                     preferred_element_type=jnp.float32)
        qp = qp.reshape(s, r * heads, emb)
        logits = jax.lax.dot_general(
            qp, k0.astype(jnp.float32), (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)          # (S, R·H, T)
        p = jax.nn.softmax(logits, axis=-1)
        ent = -(p * jnp.log(p + _EPS)).sum(axis=-1).mean()
        ents.append(ent / np.log(max(t_k, 2)))
        # advance the query rows through the block tail so layer i+1
        # measures the entropy of the attention it actually computes
        ctx = jax.lax.dot_general(
            p.astype(dtype), k0, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        ctx = ctx.astype(dtype).reshape(s * r, heads * emb)
        attended = (jnp.dot(ctx, bp["wvu"],
                            preferred_element_type=jnp.float32)
                    + bp["u_bias"].astype(jnp.float32))
        x0 = _block_tail(bp, attended, x0.reshape(s * r, emb),
                         dtype).reshape(s, r, emb)
    return jnp.stack(ents).astype(jnp.float32)


def agent_attention_entropy(learner, agent_params, obs_t0, compact_t0):
    """Agent-side probe (transformer agents only): episode-start hidden
    + the first timestep's entity tokens through the folded blocks.
    ``obs_t0 (B, A, O)`` for dense storage, or ``compact_t0 = (rows,
    same_mec, mean, std)`` for compact entity storage (the tokens are
    reconstructed per ``agent_forward_qslice_entity``'s factoring — a
    one-timestep materialization, (B, A, A+1, E), is probe-cheap)."""
    import jax
    import jax.numpy as jnp

    from ..ops.query_slice import fold_agent_params
    a = learner.mac.agent
    f = fold_agent_params(jax.lax.stop_gradient(agent_params),
                          emb=a.emb, heads=a.heads, depth=a.depth,
                          standard_heads=a.standard_heads, dtype=a.dtype)
    if compact_t0 is not None:
        rows, same_mec, mean, std = [jax.lax.stop_gradient(x)
                                     for x in compact_t0]
        b, n_ag, _ = rows.shape
        denom = std.astype(jnp.float32) + 1e-8
        rows9 = jnp.concatenate(
            [rows.astype(jnp.float32), jnp.zeros((b, n_ag, 1))], axis=-1)
        we = f["fe"]["kernel"].astype(a.dtype)
        be = f["fe"]["bias"].astype(jnp.float32)
        e_vis = (jnp.dot(((rows9 - mean) / denom).astype(a.dtype), we,
                         preferred_element_type=jnp.float32) + be)
        e_hid = (jnp.dot(((-mean) / denom).astype(a.dtype), we,
                         preferred_element_type=jnp.float32) + be)
        self_corr = (we[8][None, None, :].astype(jnp.float32)
                     / denom[..., 8:9])
        # observer i's entity token j: visible ? e_vis[j] : e_hid[j],
        # plus the is-self correction on the diagonal (j == i)
        vis = same_mec[:, :, :, None]                    # (B, A_i, A_j, 1)
        ent_tok = jnp.where(vis, e_vis[:, None, :, :], e_hid[:, None, :, :])
        eye = jnp.eye(n_ag, dtype=jnp.float32)[None, :, :, None]
        ent_tok = ent_tok + eye * self_corr[:, None, :, :]
        h0 = learner.mac.init_hidden(b).astype(jnp.float32)  # (B, A, E)
        k0 = jnp.concatenate([h0[:, :, None, :], ent_tok], axis=2)
        k0 = k0.reshape(b * n_ag, n_ag + 1, a.emb).astype(a.dtype)
    else:
        obs_t0 = jax.lax.stop_gradient(obs_t0)
        b, n_ag, _ = obs_t0.shape
        s = b * n_ag
        x = obs_t0.reshape(s, a.n_entities, a.feat_dim).astype(a.dtype)
        fe = f["fe"]
        embs = (jnp.dot(x, fe["kernel"].astype(a.dtype),
                        preferred_element_type=jnp.float32)
                + fe["bias"].astype(jnp.float32)).astype(a.dtype)
        h0 = learner.mac.init_hidden(b).reshape(s, a.emb).astype(a.dtype)
        k0 = jnp.concatenate([h0[:, None, :], embs], axis=1)
    x0 = k0[:, :1, :]                                    # the hidden row
    return attention_entropies(f["tf"], k0, x0, emb=a.emb, heads=a.heads,
                               depth=a.depth, dtype=a.dtype)


def mixer_attention_entropy(learner, mixer_params, state_t0, obs_t0,
                            hid_t0):
    """Mixer-side probe (transformer mixers only): the t=0 mixer token
    sequence — state-entity embeddings ++ post-step-0 agent hiddens ++
    the initial hyper tokens — with the consumed (last ``A+3``) rows as
    queries, exactly the rows ``mixer_forward_qslice`` carries."""
    import jax
    import jax.numpy as jnp

    from ..ops.query_slice import fold_mixer_params
    mx = learner.mixer
    f = fold_mixer_params(jax.lax.stop_gradient(mixer_params),
                          emb=mx.emb, heads=mx.heads, depth=mx.depth,
                          standard_heads=mx.standard_heads, dtype=mx.dtype)
    b = hid_t0.shape[0]
    if mx.state_entity_mode:
        inputs = state_t0.reshape(b, mx.n_entities, mx.feat_dim)
    else:                       # Q12: all agents' obs entities
        inputs = obs_t0.reshape(b, mx.n_agents * mx.n_entities,
                                mx.feat_dim)
    inputs = jax.lax.stop_gradient(inputs).astype(mx.dtype)
    fe = f["fe"]
    embs = (jnp.dot(inputs, fe["kernel"].astype(mx.dtype),
                    preferred_element_type=jnp.float32)
            + fe["bias"].astype(jnp.float32)).astype(mx.dtype)
    k0 = jnp.concatenate(
        [embs, jax.lax.stop_gradient(hid_t0).astype(mx.dtype),
         mx.initial_hyper(b).astype(mx.dtype)], axis=1)
    r = mx.n_agents + 3
    return attention_entropies(f["tf"], k0, k0[:, -r:, :], emb=mx.emb,
                               heads=mx.heads, depth=mx.depth,
                               dtype=mx.dtype)


def buffer_sight_info(priorities, episodes_in_buffer) -> dict:
    """PER priority-distribution health from the ring's stored (already
    ``p^alpha``) priority vector: Shannon entropy of the sampling
    distribution over the valid slots, normalized by ``log(n)`` —
    collapse (a handful of episodes soaking all sampling mass) reads
    as norm → 0. In-graph: one masked reduce over the ``(capacity,)``
    vector inside the already-dispatched train program."""
    import jax.numpy as jnp
    pri = jnp.asarray(priorities, jnp.float32)
    n = jnp.asarray(episodes_in_buffer, jnp.int32)
    valid = jnp.arange(pri.shape[0]) < n
    p = jnp.where(valid, pri, 0.0)
    probs = p / jnp.maximum(p.sum(), _EPS)
    ent = -(probs * jnp.log(probs + _EPS)).sum()
    norm = ent / jnp.log(jnp.maximum(n, 2).astype(jnp.float32))
    return {"sight_priority_entropy": ent,
            "sight_priority_entropy_norm": norm}


def maybe_buffer_info(cfg, info: dict, buf) -> dict:
    """Merge the in-graph PER-health read into a train-info dict when
    the static gate + prioritized replay apply — the ONE definition all
    three device train-program shapes share (classic ``_train_iter``,
    BOTH superstep cond branches, sebulba ``learner_step``), so their
    emitted pytrees can never desynchronize. ``cfg`` is the full
    TrainConfig; ``buf`` the (post-update or untouched) BufferState."""
    if not (cfg.obs.sight.enabled and cfg.replay.prioritized):
        return info
    return dict(info, **buffer_sight_info(buf.priorities,
                                          buf.episodes_in_buffer))


def buffer_sight_info_host(pri: np.ndarray, count: int) -> dict:
    """Host-replay twin of :func:`buffer_sight_info` over the numpy
    priority mirror — pure host math, zero dispatches on the
    ``buffer_cpu_only`` path."""
    p = np.asarray(pri[:max(count, 0)], np.float64)
    z = max(float(p.sum()), _EPS)
    probs = p / z
    ent = float(-(probs * np.log(probs + _EPS)).sum()) if count else 0.0
    norm = ent / np.log(max(count, 2))
    return {"sight_priority_entropy": np.float32(ent),
            "sight_priority_entropy_norm": np.float32(norm)}


def train_info_extras_zeros(cfg) -> dict:
    """Aval-matched zeros for every sight key the learner emits — the
    superstep's skipped-iteration branch (``train_info_zeros``) must
    mirror ``train``'s pytree exactly. The key set is a STATIC function
    of the config (``module_group_names`` + the family gates), never of
    runtime values."""
    import jax.numpy as jnp
    z = jnp.zeros((), jnp.float32)
    sg = cfg.obs.sight
    info = {}
    for name in module_group_names(cfg):
        info[f"sight_grad_norm_{name}"] = z
        info[f"sight_update_norm_{name}"] = z
    info["sight_per_ess"] = z
    info["sight_target_drift"] = z
    for k in ("sight_td_hist", "sight_q_taken_hist", "sight_target_hist"):
        info[k] = jnp.zeros((sg.bins,), jnp.float32)
    if cfg.agent == "transformer":
        info["sight_attn_entropy_agent"] = jnp.zeros((cfg.model.depth,),
                                                     jnp.float32)
    if cfg.mixer == "transformer":
        info["sight_attn_entropy_mixer"] = jnp.zeros(
            (cfg.model.mixer_depth,), jnp.float32)
    return info


# --------------------------------------------------------------------------
# host side: the detector monitor
# --------------------------------------------------------------------------


class SightMonitor:
    """Windowed RL-health detectors over the fetched train-info stream.

    The driver calls :meth:`observe` once per log cadence with the
    (host-fetched) last train info; the monitor logs every ``sight_*``
    stat to the metric stream (full fidelity — the Logger degrades
    vectors to a summary only on the console), evaluates the detectors,
    and on a trip logs ``sight_alert_<name>``, marks the flight
    recorder, and returns the newly tripped names so the driver can
    persist the flight ring. ``/healthz`` checks registered via
    :meth:`wire_pulse` read the CURRENT verdicts — the endpoint flips
    503 naming the detector the moment one trips."""

    def __init__(self, sight_cfg, logger=None, rec=None,
                 member: Optional[int] = None):
        self.cfg = sight_cfg
        self.logger = logger
        self.rec = rec
        #: graftpop member index (docs/POPULATION.md): set by
        #: PopulationSightMonitor when P > 1 — logged stat keys gain a
        #: ``pop<i>_`` prefix and /healthz checks register as
        #: ``sight-pop<i>-<detector>``; None (solo runs, and the P=1
        #: population for metric-stream parity) keeps today's names
        self.member = member
        self._prefix = f"pop{member}_" if member is not None else ""
        self._window: deque = deque(maxlen=int(sight_cfg.window))
        self.status: Dict[str, dict] = {
            name: {"ok": True, "detail": "no data", "t_env": 0}
            for name in DETECTORS}
        self.trips_total = 0

    # -- ingestion -------------------------------------------------------

    @staticmethod
    def _scalarize(info: dict) -> dict:
        out = {}
        for k, v in info.items():
            a = np.asarray(v)
            out[k] = a if a.ndim else float(a)
        return out

    def observe(self, info: dict, t_env: int) -> List[str]:
        """One log-cadence observation → newly tripped detector names."""
        vals = self._scalarize(info)
        if self.logger is not None:
            for k in sorted(vals):
                if k.startswith("sight_"):
                    self.logger.log_stat(self._prefix + k, vals[k], t_env)
        self._window.append(vals)
        newly: List[str] = []
        for name, (ok, detail) in self._evaluate().items():
            prev = self.status[name]["ok"]
            self.status[name] = {"ok": ok, "detail": detail,
                                 "t_env": int(t_env)}
            if ok != prev and self.logger is not None:
                self.logger.log_stat(f"{self._prefix}sight_alert_{name}",
                                     0.0 if ok else 1.0, t_env)
            if prev and not ok:
                self.trips_total += 1
                newly.append(name if self.member is None
                             else f"pop{self.member}:{name}")
                if self.rec is not None:
                    mark_kw = ({} if self.member is None
                               else {"member": self.member})
                    self.rec.mark("sight", detector=name, t_env=t_env,
                                  detail=detail[:200], **mark_kw)
        return newly

    # -- detectors -------------------------------------------------------

    def _latest(self, key: str):
        for vals in reversed(self._window):
            if key in vals:
                return vals[key]
        return None

    def _series(self, key: str) -> List[float]:
        return [v[key] for v in self._window if key in v]

    def _evaluate(self) -> Dict[str, Tuple[bool, str]]:
        cfg = self.cfg
        out: Dict[str, Tuple[bool, str]] = {}

        # loss plateau: relative spread over a FULL window below the
        # threshold (informational-grade: a converged run plateaus too —
        # the detail carries the level so the reader can tell)
        losses = self._series("loss")
        if len(losses) >= self._window.maxlen:
            m = float(np.mean(np.abs(losses)))
            spread = float(np.max(losses) - np.min(losses))
            flat = spread <= cfg.plateau_rel * max(m, _EPS)
            out["loss_plateau"] = (
                not flat,
                f"spread={spread:.3g} over {len(losses)} cadences at "
                f"mean |loss|={m:.3g}"
                + (" — flat" if flat else ""))
        else:
            out["loss_plateau"] = (True, f"warming up "
                                         f"({len(losses)}/"
                                         f"{self._window.maxlen})")

        # Q divergence: NaN-free blow-up of the value scale
        qt, tg = self._latest("q_taken_mean"), self._latest("target_mean")
        worst = max(abs(qt or 0.0), abs(tg or 0.0))
        out["q_divergence"] = (
            worst <= cfg.q_div,
            f"|q_taken_mean|={abs(qt) if qt is not None else 0:.3g} "
            f"|target_mean|={abs(tg) if tg is not None else 0:.3g} "
            f"(threshold {cfg.q_div:g})")

        # PER priority collapse: sampling entropy or importance-weight
        # effective sample size through the floor
        pen = self._latest("sight_priority_entropy_norm")
        ess = self._latest("sight_per_ess")
        if pen is None and ess is None:
            out["priority_collapse"] = (True, "no PER telemetry")
        else:
            bad = []
            if pen is not None and pen < cfg.priority_entropy_min:
                bad.append(f"priority entropy {pen:.3g} < "
                           f"{cfg.priority_entropy_min:g} of log(n)")
            if ess is not None and ess < cfg.ess_min:
                bad.append(f"importance-weight ESS {ess:.3g} < "
                           f"{cfg.ess_min:g} of batch")
            out["priority_collapse"] = (
                not bad,
                "; ".join(bad) or f"entropy_norm="
                                  f"{pen if pen is not None else -1:.3g} "
                                  f"ess={ess if ess is not None else -1:.3g}")

        # attention collapse: any layer's normalized entropy at the floor
        layers: List[Tuple[str, int, float]] = []
        for side in ("agent", "mixer"):
            v = self._latest(f"sight_attn_entropy_{side}")
            if v is not None:
                for i, e in enumerate(np.asarray(v).reshape(-1)):
                    layers.append((side, i, float(e)))
        if not layers:
            out["attention_collapse"] = (True, "no attention telemetry")
        else:
            side, i, e = min(layers, key=lambda x: x[2])
            out["attention_collapse"] = (
                e >= cfg.attn_entropy_min,
                f"min layer entropy {e:.3g} ({side} layer {i}; "
                f"threshold {cfg.attn_entropy_min:g} of log(keys))")

        # per-module gradient starvation: one module's share of the
        # total gradient norm at the floor for a FULL window
        shares_hist: List[Dict[str, float]] = []
        for vals in self._window:
            norms = {k[len("sight_grad_norm_"):]: v
                     for k, v in vals.items()
                     if k.startswith("sight_grad_norm_")}
            total = sum(norms.values())
            if norms and total > 0:
                shares_hist.append({m: n / total for m, n in norms.items()})
            elif norms:
                # complete gradient death (total norm exactly 0) is
                # strictly WORSE than one starved module — count every
                # module at share 0 so a dead window trips instead of
                # reading as "warming up" forever
                shares_hist.append({m: 0.0 for m in norms})
        if len(shares_hist) < self._window.maxlen:
            out["grad_starvation"] = (
                True, f"warming up ({len(shares_hist)}/"
                      f"{self._window.maxlen})")
        else:
            starved = None
            for mod in shares_hist[-1]:
                ss = [s.get(mod, 1.0) for s in shares_hist]
                if all(s < cfg.grad_starvation for s in ss):
                    starved = (mod, max(ss))
                    break
            out["grad_starvation"] = (
                starved is None,
                (f"module {starved[0]!r} grad share <= {starved[1]:.3g} "
                 f"for {len(shares_hist)} cadences (threshold "
                 f"{cfg.grad_starvation:g})") if starved
                else "all modules receiving gradient")
        return out

    # -- surfaces --------------------------------------------------------

    def report(self) -> dict:
        """The stall-diagnosis / flight-recorder extra: current
        verdicts + trip count (host-cached — safe on wedged-backend
        paths, nothing here touches a device)."""
        return {"detectors": {k: dict(v) for k, v in self.status.items()},
                "trips_total": self.trips_total}

    def wire_pulse(self, hub) -> None:
        """Register one ``/healthz`` check per detector: the endpoint
        names the tripped check (``sight-<detector>``, or
        ``sight-pop<i>-<detector>`` for a population member) so a
        supervisor needs no JSON spelunking to know WHY the run
        degraded."""
        tag = f"pop{self.member}-" if self.member is not None else ""
        for name in DETECTORS:
            hub.health(
                f"sight-{tag}{name}",
                lambda name=name: (self.status[name]["ok"],
                                   self.status[name]["detail"]))


class PopulationSightMonitor:
    """graftpop (docs/POPULATION.md): one :class:`SightMonitor` PER
    population member over the same log-cadence fetch — the fetched
    train-info leaves carry a leading ``(P,)`` member axis (the
    population superstep's vmapped output; the in-graph reduces are
    rank-polymorphic since PR 14), and each member's slice feeds its
    own windowed detector state. Zero extra device traffic: the slice
    is host-side numpy indexing on the already-fetched arrays.

    At P > 1 each member's stats log under ``pop<i>_sight_*``, its
    ``/healthz`` checks register as ``sight-pop<i>-<detector>``, and
    trips report as ``pop<i>:<detector>``. At P == 1 the single member
    keeps the solo key/check names — the metric stream of a P=1
    population is the solo run's (the bit-parity contract)."""

    def __init__(self, sight_cfg, population: int, logger=None, rec=None):
        self.population = int(population)
        self.members = [
            SightMonitor(sight_cfg, logger=logger, rec=rec,
                         member=(m if self.population > 1 else None))
            for m in range(self.population)]

    def observe(self, info: dict, t_env: int) -> List[str]:
        newly: List[str] = []
        for m, mon in enumerate(self.members):
            sliced = {}
            for k, v in info.items():
                a = np.asarray(v)
                sliced[k] = a[m] if a.ndim else a
            newly.extend(mon.observe(sliced, t_env))
        return newly

    def report(self) -> dict:
        return {"population": self.population,
                "members": [mon.report() for mon in self.members]}

    def wire_pulse(self, hub) -> None:
        for mon in self.members:
            mon.wire_pulse(hub)


def make_monitor(obs_cfg, logger=None, rec=None, population: int = 0
                 ) -> Optional[object]:
    """Driver constructor: None unless ``obs.sight.enabled`` (the
    byte-identical off state — the driver hot loop stays one
    ``if sight_mon is not None`` away from today's). ``population=P``
    (graftpop) returns the per-member :class:`PopulationSightMonitor`
    over the ``(P,)``-leading fetched leaves."""
    sg = getattr(obs_cfg, "sight", None)
    if sg is None or not getattr(sg, "enabled", False):
        return None
    if population:
        return PopulationSightMonitor(sg, population, logger=logger,
                                      rec=rec)
    return SightMonitor(sg, logger=logger, rec=rec)


# --------------------------------------------------------------------------
# jax-free learning CLI (`python -m t2omca_tpu.obs learning <run_dir>`)
# --------------------------------------------------------------------------

#: ASCII sparkline ramp for histogram cells
_RAMP = " .:-=+*#%@"

#: health-table rows: (label, metrics key, decimals)
_HEALTH_ROWS = (
    ("loss", "loss", 4),
    ("grad norm (total)", "grad_norm", 3),
    ("grad norm agent-tf", "sight_grad_norm_agent_tf", 4),
    ("grad norm embed", "sight_grad_norm_embed", 4),
    ("grad norm mixer", "sight_grad_norm_mixer", 4),
    ("update norm agent-tf", "sight_update_norm_agent_tf", 5),
    ("update norm embed", "sight_update_norm_embed", 5),
    ("update norm mixer", "sight_update_norm_mixer", 5),
    ("q_taken mean", "q_taken_mean", 3),
    ("target mean", "target_mean", 3),
    ("PER weight ESS (of batch)", "sight_per_ess", 3),
    ("PER priority entropy / log n", "sight_priority_entropy_norm", 3),
    ("target drift (rel)", "sight_target_drift", 4),
    ("td error |mean|", "td_error_abs", 4),
)


def _series_from_metrics(events: List[dict]) -> Dict[str, list]:
    series: Dict[str, list] = {}
    for ev in events:
        if isinstance(ev, dict) and "key" in ev:
            series.setdefault(ev["key"], []).append(
                (ev.get("t", 0), ev.get("value")))
    return series


def _spark(vec) -> str:
    """ASCII sparkline; non-finite cells render ``!`` — the Logger
    deliberately keeps poisoned bins at full fidelity in the metric
    stream, and the post-mortem reader must survive (and SHOW) them,
    since pathological runs are exactly its use case."""
    v = np.asarray(vec, float).reshape(-1)
    finite = np.isfinite(v)
    if v.size == 0 or not finite.any():
        return "-"
    hi = float(np.max(v[finite]))
    out = []
    for x, ok in zip(v, finite):
        if not ok:
            out.append("!")
        elif hi <= 0:
            out.append(".")
        else:
            out.append(_RAMP[min(max(int(x / hi * (len(_RAMP) - 1)), 0),
                                 len(_RAMP) - 1)])
    return "".join(out)


def _downsample(points: list, n: int = 12) -> list:
    if len(points) <= n:
        return points
    idx = np.linspace(0, len(points) - 1, n).round().astype(int)
    return [points[i] for i in idx]


def render_learning(run_dir: str, series: Dict[str, list]) -> List[str]:
    """The learning-health report body (shared by the ``learning`` CLI
    and the ``report`` section): health table, histograms, detector
    verdicts, learning curves per scenario slice, and the one-line
    "is this run learning?" read."""
    from .report import SCENARIO_FAMILY_NAMES
    lines: List[str] = []
    lines.append(f"graftsight learning report — {run_dir}")
    last_t = max((pts[-1][0] for pts in series.values() if pts), default=0)
    lines.append(f"newest cadence: t_env={last_t}")

    lines.append("")
    lines.append("learning health (newest value per key)")
    hdr = f"{'metric':<30}{'value':>14}{'trend (last 12)':>20}"
    lines.append(hdr)
    lines.append("-" * len(hdr))
    any_row = False
    for label, key, nd in _HEALTH_ROWS:
        pts = series.get(key)
        if not pts:
            continue
        any_row = True
        v = pts[-1][1]
        cell = f"{v:,.{nd}f}" if isinstance(v, (int, float)) else str(v)
        trend = _spark([abs(p[1]) for p in _downsample(pts)
                        if isinstance(p[1], (int, float))])
        lines.append(f"{label:<30}{cell:>14}{trend:>20}")
    for side in ("agent", "mixer"):
        pts = series.get(f"sight_attn_entropy_{side}")
        if pts and isinstance(pts[-1][1], (list, tuple)):
            any_row = True
            ents = ", ".join(f"{float(e):.3f}" for e in pts[-1][1])
            lines.append(f"{'attn entropy ' + side + ' (per layer)':<30}"
                         f"{ents:>14}")
    if not any_row:
        lines.append("(no learner metrics — was the run recorded with "
                     "obs.sight.enabled?)")

    hists = [(k, series[k]) for k in
             ("sight_td_hist", "sight_q_taken_hist", "sight_target_hist")
             if series.get(k)]
    if hists:
        lines.append("")
        lines.append("value histograms (newest cadence; fixed bins, "
                     "outliers clip into the edge bins)")
        for k, pts in hists:
            v = pts[-1][1]
            if isinstance(v, (list, tuple)):
                lines.append(f"  {k[len('sight_'):]:<16}|{_spark(v)}|")

    alerts = {k[len("sight_alert_"):]: pts for k, pts in series.items()
              if k.startswith("sight_alert_")}
    lines.append("")
    lines.append("detector verdicts (sight_alert_* stream)")
    if alerts:
        for name in sorted(alerts):
            pts = alerts[name]
            tripped = pts[-1][1] not in (0, 0.0)
            last_trip = max((t for t, v in pts if v not in (0, 0.0)),
                            default=None)
            state = "TRIPPED" if tripped else "clear"
            extra = (f" (last trip t_env={last_trip})"
                     if last_trip is not None and not tripped else "")
            lines.append(f"  {name:<22}{state}{extra}")
    else:
        lines.append("  (no detector transitions recorded)")

    # graftpop per-member health (docs/POPULATION.md): pop<i>_* rows in
    # the metric stream mean a population > 1 ran — one line per member
    # joining its newest return/loss/health values and standing alerts
    pop_ids = sorted({
        int(k[3:k.index("_")]) for k in series
        if k.startswith("pop") and "_" in k
        and k[3:k.index("_")].isdigit()})
    if pop_ids:
        lines.append("")
        lines.append(f"population members ({len(pop_ids)} — newest "
                     f"value per member)")
        hdr = (f"{'member':<8}{'return':>12}{'loss':>12}"
               f"{'q_taken':>12}{'PER ESS':>10}  alerts")
        lines.append(hdr)
        lines.append("-" * len(hdr))

        def _newest(key):
            pts = series.get(key)
            v = pts[-1][1] if pts else None
            return v if isinstance(v, (int, float)) else None

        for m in pop_ids:
            cells = []
            for key, nd in ((f"pop{m}_return_mean", 2),
                            (f"pop{m}_loss", 4)):
                v = _newest(key)
                cells.append(f"{v:>12,.{nd}f}" if v is not None
                             else f"{'-':>12}")
            v = _newest(f"pop{m}_q_taken_mean")
            cells.append(f"{v:>12,.3f}" if v is not None else f"{'-':>12}")
            v = _newest(f"pop{m}_sight_per_ess")
            cells.append(f"{v:>10,.3f}" if v is not None else f"{'-':>10}")
            standing = sorted(
                k[len(f"pop{m}_sight_alert_"):]
                for k, pts in series.items()
                if k.startswith(f"pop{m}_sight_alert_") and pts
                and pts[-1][1] not in (0, 0.0))
            cells.append("  " + (", ".join(standing) or "none"))
            lines.append(f"pop{m:<5}" + "".join(cells))

    curve_keys = []
    for prefix in ("", "test_"):
        if series.get(prefix + "return_mean"):
            curve_keys.append((prefix + "return_mean",
                               "test" if prefix else "train"))
    slice_fams = sorted({
        int(k.split("_", 1)[0][len("slice"):])
        for k in series
        if k.startswith("slice") and k.endswith("_return_mean")
        and k[len("slice"):k.index("_")].isdigit()})
    if curve_keys or slice_fams:
        lines.append("")
        lines.append("learning curves (return_mean; downsampled)")
        cols = [label for _, label in curve_keys]
        cols += [(SCENARIO_FAMILY_NAMES[f]
                  if 0 <= f < len(SCENARIO_FAMILY_NAMES)
                  else f"family{f}") for f in slice_fams]
        hdr = f"{'t_env':>10}" + "".join(f"{c:>14}" for c in cols)
        lines.append(hdr)
        lines.append("-" * len(hdr))
        base = series.get("return_mean") or next(
            (series[k] for k, _ in curve_keys), [])
        for t, _ in _downsample(base):
            row = f"{t:>10}"
            for key, _label in curve_keys:
                row += _cell_at(series[key], t)
            for f in slice_fams:
                row += _cell_at(series.get(f"slice{f}_return_mean", []), t)
            lines.append(row)

    verdict = _learning_verdict(series)
    lines.append("")
    lines.append(f"verdict: {verdict}")
    return lines


def _cell_at(pts: list, t: int) -> str:
    """Newest value at-or-before ``t`` (the curves log on different
    cadences; exact-t joins would leave holes)."""
    best = None
    for pt, pv in pts:
        if pt <= t:
            best = pv
        else:
            break
    if best is None or not isinstance(best, (int, float)):
        return f"{'-':>14}"
    return f"{best:>14,.2f}"


def _learning_verdict(series: Dict[str, list]) -> str:
    """The "is this run learning?" one-liner: return trend (first vs
    last third of the curve) + standing detector alerts."""
    tripped = sorted(
        k[len("sight_alert_"):] for k, pts in series.items()
        if k.startswith("sight_alert_") and pts
        and pts[-1][1] not in (0, 0.0))
    pts = [v for _, v in (series.get("return_mean") or [])
           if isinstance(v, (int, float))]
    if len(pts) < 3:
        trend = "too little return data to call a trend"
    else:
        third = max(len(pts) // 3, 1)
        early, late = float(np.mean(pts[:third])), float(
            np.mean(pts[-third:]))
        span = max(abs(early), abs(late), _EPS)
        if late - early > 0.05 * span:
            trend = (f"return improving ({early:,.2f} -> {late:,.2f})")
        elif early - late > 0.05 * span:
            trend = (f"return REGRESSING ({early:,.2f} -> {late:,.2f})")
        else:
            trend = f"return flat around {late:,.2f}"
    if tripped:
        return f"{trend}; standing alerts: {', '.join(tripped)}"
    return f"{trend}; no standing alerts"


def learning_main(run_dir: str) -> int:
    """The ``learning`` subcommand body (``obs/__main__.py``). Exit
    codes match the obs CLI convention: 0 = report printed, 2 = usage
    error. Jax-free by construction; reads ``metrics.jsonl`` through
    the tolerant reader — the torn final line a killed run leaves is
    skipped with a warning, never raised on."""
    from ..utils.ioutil import read_jsonl_tolerant
    from .report import _warn_torn
    if not os.path.isdir(run_dir):
        print(f"graftsight: error: {run_dir!r} is not a directory",
              file=sys.stderr)
        return 2
    path = os.path.join(run_dir, "metrics.jsonl")
    try:
        events = read_jsonl_tolerant(path, on_bad=_warn_torn(path))
    except OSError as e:
        print(f"graftsight: error: no metrics.jsonl in {run_dir!r} "
              f"({e}); the learning report reads the run's metric "
              f"stream", file=sys.stderr)
        return 2
    print("\n".join(render_learning(
        run_dir, _series_from_metrics(events))))
    return 0
