"""graftscope report: join runtime telemetry against graftprog budgets.

``python -m t2omca_tpu.obs report <run_dir>`` reads a run's span
telemetry (``spans.jsonl``, written by the driver when
``obs.enabled``) plus its optional device-time attribution
(``device_times.json``, written by :class:`obs.device_time.
ProgramTraceWindow`) and joins them against graftprog's checked-in
FLOPs/bytes budgets (``analysis/programs.json``) into a roofline-style
per-program table: measured wall (and device) time per dispatch next
to the program's estimated FLOPs/bytes at the run's shapes, its
arithmetic intensity, and the achieved FLOP/s — the tool ROADMAP open
item 1 needs to pick between device-side PER sampling, Pallas
attention, and bf16 as the next perf target (a program far below the
intensity-implied bound is latency/dispatch-bound; one near it needs
less math or fewer bytes, not a faster driver).

Honesty about the join: programs.json budgets are measured at the
frozen audit config (``analysis/registry.audit_config``: B=2, T=6,
K=2, train batch 4). The run header mark in ``spans.jsonl`` carries the
run's shapes, and the report scales the audit budgets linearly with the
per-dispatch env-step/sample counts — a first-order estimate (marked
``~``): attention terms scale super-linearly with agents/tokens, so
cross-*scale* comparisons are indicative, cross-*program* comparisons
at one scale are solid. Pass ``--peak-gflops``/``--peak-gbps`` (the
chip's datasheet numbers) to add the roofline bound and the achieved
fraction.

stdlib-only on purpose (no jax import): the report must run on a host
that cannot even initialize the backend — that is the post-mortem case
it exists for.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional

from ..utils.ioutil import read_jsonl_tolerant

#: span phase -> graftprog program name (analysis/programs.json key).
#: ``dispatch.test`` dispatches the same compiled rollout program as
#: the train rollout (test_mode is a static arg of one jitted fn), so
#: it joins the same budgets on its own row.
PHASE_PROGRAMS = {
    "dispatch.superstep": "superstep",
    "dispatch.rollout": "rollout",
    "dispatch.train": "train_iter",
    "dispatch.test": "rollout",
    # serving runs (serve/frontend.py): the dispatch span joins the
    # serve program's graftprog budgets on its own row
    "serve.dispatch": "serve_step",
    # sebulba decoupled runs (run.run_sebulba): the re-homed rollout and
    # train dispatches join their own audit entries (2+2-device split)
    "actor.dispatch": "actor_step",
    "learner.dispatch": "learner_step",
}


#: scenario-family id -> name (graftworld per-slice eval). MIRRORED from
#: ``envs/graftworld.FAMILY_NAMES`` — this module must stay jax-free
#: (the post-mortem host may not even initialize a backend), and
#: graftworld imports jax for its samplers. Pinned against the source
#: tuple by tests/test_graftworld.py.
SCENARIO_FAMILY_NAMES = ("baseline", "hetfleet", "interference", "surge")

#: per-slice metric columns, (header, metrics-key) in render order:
#: the return plus utils/stats.SLICE_KEYS — pinned against SLICE_KEYS
#: by tests/test_graftworld.py (same mirror-and-pin policy as the
#: family names; this module must not import the jax-adjacent stats)
SLICE_METRICS = (("return", "return_mean"),
                 ("conflict", "conflict_ratio_mean"),
                 ("complete", "task_completion_rate_mean"),
                 ("dl-miss", "deadline_miss_rate_mean"))


def _warn_torn(path: str):
    """on_bad hook for the tolerant JSONL readers: a torn FINAL line is
    the expected artifact of a killed run (crash / SIGKILL / hard
    watchdog exit mid-write) — skipped with a warning, never raised on;
    a torn mid-file line is flagged as the oddity it is."""
    def _on_bad(line_no: int, is_last: bool) -> None:
        what = ("torn final line — the artifact a killed run leaves"
                if is_last else "unparseable mid-file line")
        print(f"graftscope: warning: {path}:{line_no}: {what}; skipped",
              file=sys.stderr)
    return _on_bad


def load_events(run_dir: str) -> List[dict]:
    path = os.path.join(run_dir, "spans.jsonl")
    events = read_jsonl_tolerant(path, on_bad=_warn_torn(path))
    return [e for e in events if isinstance(e, dict)]


def load_flight_events(run_dir: str) -> Optional[List[dict]]:
    """Degraded-input fallback: a run that died before (or without)
    flushing ``spans.jsonl`` may still have persisted its flight ring
    (``flight_recorder.json``, same event schema, bounded tail). None
    when absent/unreadable."""
    path = os.path.join(run_dir, "flight_recorder.json")
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    events = payload.get("events")
    if not isinstance(events, list):
        return None
    return [e for e in events if isinstance(e, dict)]


def load_device_times(run_dir: str) -> Dict[str, dict]:
    path = os.path.join(run_dir, "device_times.json")
    try:
        with open(path) as f:
            return dict(json.load(f).get("programs", {}))
    except (OSError, ValueError):
        return {}


def scenario_slices(run_dir: str) -> Dict[str, Dict[int, dict]]:
    """Per-scenario-slice eval metrics from the run's ``metrics.jsonl``
    (graftworld, docs/ENVS.md): the newest value of every
    ``[test_]slice<fam>_*`` key the stats accumulators logged, grouped
    as ``{prefix: {family_id: {metric: value}}}``. Empty when the run
    trained a single scenario (the accumulators only emit slice rows
    when more than one family was observed)."""
    path = os.path.join(run_dir, "metrics.jsonl")
    out: Dict[str, Dict[int, dict]] = {}
    try:
        events = read_jsonl_tolerant(path, on_bad=_warn_torn(path))
    except OSError:
        return out
    for ev in events:
        if isinstance(ev, dict):
            key = ev.get("key", "")
            prefix = ""
            if key.startswith("test_"):
                prefix, key = "test", key[5:]
            if not key.startswith("slice"):
                continue
            fam_s, _, metric = key[5:].partition("_")
            if not fam_s.isdigit() or not metric:
                continue
            out.setdefault(prefix, {}).setdefault(
                int(fam_s), {})[metric] = ev.get("value")
    return out


def render_slices(slices: Dict[str, Dict[int, dict]]) -> List[str]:
    """The per-scenario-slice table: one block per train/test prefix,
    one row per family — the generalization read ISSUE 11 asks for
    (mean return alone hides a family the policy sacrificed)."""

    def cell(v, nd=1):
        # NOT _fmt: that helper renders negatives as '-' (its callers
        # use -1 as an absent sentinel), but slice returns are routinely
        # negative (reward = delay gain - deadline penalties) and the
        # worst families are exactly the rows this table exists to show
        if v is None:
            return "-"
        return f"{v:,.{nd}f}" if isinstance(v, float) else str(v)

    lines: List[str] = []
    for prefix in sorted(slices):
        fams = slices[prefix]
        if not fams:
            continue
        lines.append("")
        lines.append(f"scenario slices ({prefix or 'train'}; newest "
                     f"cadence, graftworld per-family eval)")
        hdr = f"{'family':<16}{'n':>7}" + "".join(
            f"{label:>11}" for label, _ in SLICE_METRICS)
        lines.append(hdr)
        lines.append("-" * len(hdr))
        for fam in sorted(fams):
            m = fams[fam]
            name = (SCENARIO_FAMILY_NAMES[fam]
                    if 0 <= fam < len(SCENARIO_FAMILY_NAMES)
                    else f"family{fam}")
            row = f"{name:<16}{cell(m.get('n'), 0):>7}"
            for label, key in SLICE_METRICS:
                nd = 1 if key == "return_mean" else 3
                row += f"{cell(m.get(key), nd):>11}"
            lines.append(row)
    return lines


def run_header(events: List[dict]) -> Optional[dict]:
    for ev in events:
        if ev.get("event") == "mark" and ev.get("kind") == "run":
            return ev
    return None


def phase_summary(events: List[dict]) -> Dict[str, dict]:
    """Per-phase aggregate from raw span events (same shape as
    ``SpanRecorder.summary()``, recomputed from the durable JSONL)."""
    out: Dict[str, dict] = {}
    for ev in events:
        if ev.get("event") != "span" or ev.get("open"):
            continue
        phase, ms = ev.get("phase"), ev.get("wall_ms")
        if not isinstance(phase, str) or not isinstance(ms, (int, float)):
            continue
        a = out.setdefault(phase, {"n": 0, "total_ms": 0.0, "max_ms": 0.0,
                                   "first_ms": -1.0, "errors": 0})
        a["n"] += 1
        a["total_ms"] += ms
        a["max_ms"] = max(a["max_ms"], ms)
        if ev.get("first"):
            a["first_ms"] = ms
        if str(ev.get("outcome", "ok")).startswith("error"):
            a["errors"] += 1
    for a in out.values():
        rest_n = a["n"] - (1 if a["first_ms"] >= 0 else 0)
        rest_total = a["total_ms"] - max(a["first_ms"], 0.0)
        a["steady_ms"] = rest_total / rest_n if rest_n > 0 else -1.0
    return out


def _audit_shapes() -> dict:
    """The frozen audit-config shapes the budgets were measured at
    (jax-free: ``registry.audit_config`` only builds dataclasses)."""
    from ..analysis.registry import AUDIT_SUPERSTEP_K, audit_config
    cfg = audit_config()
    return {"batch_size_run": cfg.batch_size_run,
            "episode_limit": cfg.env_args.episode_limit,
            "batch_size": cfg.batch_size,
            "superstep": AUDIT_SUPERSTEP_K}


def scale_factor(program: str, header: Optional[dict],
                 audit: dict) -> Optional[float]:
    """First-order budget scale: run per-dispatch work / audit
    per-dispatch work. None when the header lacks the needed shapes."""
    if not header:
        return None
    try:
        b = float(header["batch_size_run"]) / audit["batch_size_run"]
        t = float(header["episode_limit"]) / audit["episode_limit"]
        if program in ("rollout", "insert", "actor_step"):
            return b * t
        if program in ("train_iter", "learner_step"):
            return (float(header["batch_size"]) / audit["batch_size"]) * t
        if program == "superstep":
            k = float(header.get("superstep", 1)) / audit["superstep"]
            return k * b * t
    except (KeyError, TypeError, ZeroDivisionError):
        return None
    return None


def build_rows(phases: Dict[str, dict], device_times: Dict[str, dict],
               programs: Dict[str, dict], header: Optional[dict]
               ) -> List[dict]:
    audit = _audit_shapes()
    rows: List[dict] = []
    for phase, prog_name in PHASE_PROGRAMS.items():
        p = phases.get(phase)
        if p is None or p["n"] == 0:
            continue
        entry = programs.get(prog_name, {})
        dev = device_times.get(prog_name, {})
        sf = scale_factor(prog_name, header, audit)
        flops = entry.get("flops")
        bytes_ = entry.get("bytes_accessed")
        row = {
            "phase": phase, "program": prog_name, "n": p["n"],
            "first_ms": p["first_ms"], "steady_ms": p["steady_ms"],
            "total_ms": p["total_ms"],
            "device_ms": dev.get("device_ms"),
            "device_events": dev.get("events"),
            "flops_audit": flops, "bytes_audit": bytes_,
            "intensity": (flops / bytes_ if flops and bytes_ else None),
            "gflop_disp": (flops * sf / 1e9
                           if flops is not None and sf else None),
            "gb_disp": (bytes_ * sf / 1e9
                        if bytes_ is not None and sf else None),
        }
        # achieved rate: device time when attributed, else the steady
        # wall per dispatch (which includes dispatch overhead — an
        # upper bound on time, lower bound on rate, stated in the table
        # legend). The trace window covers only its OWN dispatches (not
        # the whole run's span count), so per-dispatch device time is
        # the window's median event duration — robust to the compile-
        # inclusive first call on host tracks; mean over the window's
        # events is the fallback for older device_times.json files.
        per_disp_ms = None
        if dev.get("median_ms"):
            per_disp_ms = dev["median_ms"]
            row["time_source"] = "device"
        elif row["device_ms"] and row["device_events"]:
            per_disp_ms = row["device_ms"] / row["device_events"]
            row["time_source"] = "device"
        elif p["steady_ms"] and p["steady_ms"] > 0:
            per_disp_ms = p["steady_ms"]
            row["time_source"] = "wall"
        row["per_disp_ms"] = per_disp_ms
        row["achieved_gflops"] = (
            row["gflop_disp"] / (per_disp_ms / 1000.0)
            if row["gflop_disp"] and per_disp_ms else None)
        rows.append(row)
    # device-attributed programs with no dispatch span of their own —
    # the fused Pallas kernels (attn_pallas etc.) show up only as device
    # kernel launches inside a larger program's dispatch. Without this
    # they would silently vanish from the table (their device time
    # dropped into the unattributed bucket); budgets stay unscaled (no
    # run-shape mapping for a kernel fragment — stated via sf=None).
    spanned = {r["program"] for r in rows}
    for prog_name in sorted(set(device_times) - spanned):
        dev = device_times[prog_name]
        entry = programs.get(prog_name, {})
        flops = entry.get("flops")
        bytes_ = entry.get("bytes_accessed")
        per_disp_ms = dev.get("median_ms") or (
            dev["device_ms"] / dev["events"] if dev.get("events") else None)
        rows.append({
            "phase": "(trace-only)", "program": prog_name,
            "n": dev.get("events", 0), "first_ms": -1.0,
            "steady_ms": -1.0, "total_ms": dev.get("device_ms", 0.0),
            "device_ms": dev.get("device_ms"),
            "device_events": dev.get("events"),
            "flops_audit": flops, "bytes_audit": bytes_,
            "intensity": (flops / bytes_ if flops and bytes_ else None),
            "gflop_disp": None, "gb_disp": None,
            "per_disp_ms": per_disp_ms, "time_source": "device",
            "achieved_gflops": None,
        })
    return rows


def _fmt(v, nd=1, dash="-") -> str:
    if v is None or (isinstance(v, (int, float)) and v < 0):
        return dash
    if isinstance(v, float):
        return f"{v:,.{nd}f}"
    return str(v)


def render(run_dir: str, events: List[dict], rows: List[dict],
           phases: Dict[str, dict], header: Optional[dict],
           peak_gflops: Optional[float], peak_gbps: Optional[float]
           ) -> str:
    lines: List[str] = []
    lines.append(f"graftscope report — {run_dir}")
    if header:
        keys = ("backend", "batch_size_run", "episode_limit",
                "batch_size", "superstep")
        lines.append("run: " + "  ".join(
            f"{k}={header[k]}" for k in keys if k in header))
    else:
        lines.append("run: (no run header mark in spans.jsonl — budget "
                     "scaling disabled)")
    n_spans = sum(1 for e in events if e.get("event") == "span")
    lines.append(f"events: {len(events)} ({n_spans} spans)")
    lines.append("")
    if rows:
        hdr = (f"{'program':<13}{'phase':<20}{'n':>6}{'first ms':>10}"
               f"{'ms/disp':>10}{'src':>5}{'~GFLOP/d':>10}{'~GB/d':>8}"
               f"{'FLOP/B':>8}{'~GFLOP/s':>10}")
        lines.append(hdr)
        lines.append("-" * len(hdr))
        for r in rows:
            per_disp = r["per_disp_ms"]
            lines.append(
                f"{r['program']:<13}{r['phase']:<20}{r['n']:>6}"
                f"{_fmt(r['first_ms']):>10}{_fmt(per_disp):>10}"
                f"{r.get('time_source', '-'):>5}"
                f"{_fmt(r['gflop_disp'], 3):>10}{_fmt(r['gb_disp'], 3):>8}"
                f"{_fmt(r['intensity']):>8}"
                f"{_fmt(r['achieved_gflops']):>10}")
            if peak_gflops and peak_gbps and r["intensity"] \
                    and r["achieved_gflops"]:
                bound = min(peak_gflops, r["intensity"] * peak_gbps)
                lines.append(
                    f"{'':<11}  roofline bound {bound:,.1f} GFLOP/s "
                    f"({'compute' if bound == peak_gflops else 'memory'}"
                    f"-bound) — achieved "
                    f"{100.0 * r['achieved_gflops'] / bound:.1f}%")
        lines.append("")
        lines.append("~ = audit-config budgets (analysis/programs.json) "
                     "scaled linearly to the run shapes; src=wall "
                     "includes dispatch overhead (device attribution "
                     "off — obs.program_trace + profile_dir enable it)")
    else:
        lines.append("no program dispatch spans found (was the run "
                     "recorded with obs.enabled?)")
    other = {ph: a for ph, a in sorted(phases.items())
             if ph not in PHASE_PROGRAMS}
    if other:
        lines.append("")
        hdr = (f"{'phase':<22}{'n':>6}{'first ms':>10}{'mean ms':>10}"
               f"{'max ms':>10}{'total ms':>11}{'errors':>7}")
        lines.append(hdr)
        lines.append("-" * len(hdr))
        for ph, a in other.items():
            mean = a["total_ms"] / a["n"] if a["n"] else None
            lines.append(
                f"{ph:<22}{a['n']:>6}{_fmt(a['first_ms']):>10}"
                f"{_fmt(mean):>10}{_fmt(a['max_ms']):>10}"
                f"{_fmt(a['total_ms']):>11}{a['errors']:>7}")
    seb = sebulba_utilization(events, phases)
    if seb:
        lines.append("")
        lines.append("sebulba utilization (decoupled actor/learner run)")
        hdr = (f"{'side':<9}{'busy ms':>12}{'idle ms':>12}{'util %':>8}")
        lines.append(hdr)
        lines.append("-" * len(hdr))
        for side in ("actor", "learner"):
            u = seb[side]
            lines.append(f"{side:<9}{_fmt(u['busy_ms']):>12}"
                         f"{_fmt(u['idle_ms']):>12}"
                         f"{_fmt(u['util_pct']):>8}")
        lines.append(f"queue depth (last log cadence): "
                     f"{_fmt(seb.get('queue_depth'), 0)} "
                     f"of {_fmt(seb.get('queue_slots'), 0)} slots")
        lines.append("busy = dispatch span wall; idle = queue-wait span "
                     "wall (put = actor backpressure, get = learner "
                     "starvation); params.sync mixes the learner "
                     "publish with the actor's staleness wait and is "
                     "counted on neither side")
    slices = scenario_slices(run_dir)
    if any(slices.values()):
        lines.extend(render_slices(slices))
    else:
        # degraded input honesty: a metrics.jsonl that exists but holds
        # no slice rows (empty file, or a run killed before the first
        # cadence) renders a stated "no data" instead of silently
        # omitting the section a graftworld run's reader expects
        mpath = os.path.join(run_dir, "metrics.jsonl")
        try:
            empty = os.path.getsize(mpath) == 0
        except OSError:
            empty = False               # no metrics.jsonl at all: a
            # single-scenario run — the section stays absent, as before
        if empty:
            lines.append("")
            lines.append("scenario slices: no data (metrics.jsonl is "
                         "empty — run killed before the first log "
                         "cadence?)")
    return "\n".join(lines)


def sebulba_utilization(events: List[dict],
                        phases: Dict[str, dict]) -> Optional[dict]:
    """Actor/learner utilization for a decoupled run, from the span
    stream alone: each side's dispatch spans are its busy time and its
    queue-end waits its idle time (``run.run_sebulba`` records the
    waits inside the ``queue.put``/``queue.get`` spans). None when the
    run has no sebulba phases (classic/fused runs keep their report
    unchanged)."""
    a = phases.get("actor.dispatch")
    l = phases.get("learner.dispatch")
    if a is None and l is None and "queue.put" not in phases:
        return None
    zero = {"total_ms": 0.0}

    def util(busy, idle):
        busy_ms = busy.get("total_ms", 0.0)
        idle_ms = idle.get("total_ms", 0.0)
        denom = busy_ms + idle_ms
        return {"busy_ms": round(busy_ms, 1), "idle_ms": round(idle_ms, 1),
                "util_pct": (round(100.0 * busy_ms / denom, 1)
                             if denom > 0 else None)}

    test = phases.get("dispatch.test", zero)
    actor_busy = {"total_ms": (a or zero).get("total_ms", 0.0)
                  + test.get("total_ms", 0.0)}
    out = {"actor": util(actor_busy, phases.get("queue.put", zero)),
           "learner": util(l or zero, phases.get("queue.get", zero))}
    # queue depth / config from the run header + the last log-cadence
    # sebulba mark (the driver emits one per log interval)
    for ev in events:
        if ev.get("event") != "mark":
            continue
        if ev.get("kind") == "run" and "queue_slots" in ev:
            out["queue_slots"] = ev["queue_slots"]
        if ev.get("kind") == "sebulba":
            out["queue_depth"] = ev.get("queue_depth")
    return out


def render_comms_census(base: dict) -> List[str]:
    """Per-program collective census from the graftshard ``comms``
    sections of programs.json plus its ``transfers`` table — the static
    interconnect view joined into the report so "where did the time go"
    sits next to "what moves between devices each dispatch". Purely a
    baseline read (no jax, nothing compiled); empty when the baseline
    predates the comms audit (``--comms --write-programs``)."""
    comms = {n: e["comms"]
             for n, e in sorted(base.get("programs", {}).items())
             if "comms" in e}
    transfers = base.get("transfers", {})
    if not comms and not transfers:
        return []
    lines = ["", "collective census (graftshard --comms: static, "
                 "per dispatch, on the fixed audit meshes)"]
    hdr = (f"{'program':<17}{'mesh':<16}"
           f"{'collectives (count x kind[axes])':<40}{'bytes':>9}")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for name, c in comms.items():
        cols = ", ".join(
            f"{e['count']}x {kind}[{'/'.join(e['axes'])}]"
            for kind, e in sorted(c.get("collectives", {}).items())) \
            or "none"
        lines.append(f"{name:<17}{c.get('mesh', '-'):<16}{cols:<40}"
                     f"{c.get('bytes', 0):>9}")
    for name, t in sorted(transfers.items()):
        what = f"{t.get('leaves', 0)} leaves, {t.get('kind', '?')}"
        lines.append(f"{name:<17}{'transfer':<16}{what:<40}"
                     f"{t.get('bytes', 0):>9}")
    return lines


def report_main(run_dir: str, programs_json: Optional[str] = None,
                peak_gflops: Optional[float] = None,
                peak_gbps: Optional[float] = None) -> int:
    """The ``report`` subcommand body. Exit codes match the analysis
    CLI convention: 0 = report printed, 2 = usage error (missing run
    dir / unreadable telemetry)."""
    if not os.path.isdir(run_dir):
        print(f"graftscope: error: {run_dir!r} is not a directory",
              file=sys.stderr)
        return 2
    try:
        events = load_events(run_dir)
    except OSError as e:
        # degraded-input fallback: a run dir holding only the persisted
        # flight ring (crash before any spans flush) still reports from
        # that bounded tail — stated, so nobody mistakes it for the run
        events = load_flight_events(run_dir)
        if events is None:
            print(f"graftscope: error: no spans.jsonl in {run_dir!r} "
                  f"({e}) and no flight_recorder.json fallback; record "
                  f"the run with obs.enabled=true", file=sys.stderr)
            return 2
        print(f"graftscope: note: no spans.jsonl — reporting from the "
              f"flight-recorder tail ({len(events)} events; bounded "
              f"ring, not the full run)", file=sys.stderr)
    from ..analysis.baseline import DEFAULT_PROGRAMS, load_programs
    try:
        base = load_programs(programs_json or DEFAULT_PROGRAMS)
    except (OSError, ValueError, KeyError, TypeError) as e:
        print(f"graftscope: error: unreadable programs baseline: {e}",
              file=sys.stderr)
        return 2
    phases = phase_summary(events)
    rows = build_rows(phases, load_device_times(run_dir),
                      base["programs"], run_header(events))
    print(render(run_dir, events, rows, phases, run_header(events),
                 peak_gflops, peak_gbps))
    census = render_comms_census(base)
    if census:
        print("\n".join(census))
    # graftsight section: a run recorded with obs.sight.enabled carries
    # learning-dynamics keys in metrics.jsonl — append the learning-
    # health read so one `obs report` answers both "where did the time
    # go" and "was it learning" (full detail: `obs learning <run_dir>`)
    from .sight import _series_from_metrics, render_learning
    mpath = os.path.join(run_dir, "metrics.jsonl")
    try:
        mevents = read_jsonl_tolerant(mpath, on_bad=_warn_torn(mpath))
    except OSError:
        mevents = []
    series = _series_from_metrics(mevents)
    if any(k.startswith("sight_") for k in series):
        print()
        print("\n".join(render_learning(run_dir, series)))
    return 0
