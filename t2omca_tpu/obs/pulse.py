"""graftpulse: the live telemetry plane (metrics endpoint + triggers).

Everything graftscope (``spans.py``) records is post-mortem — spans,
flight rings and stall diagnoses are only readable after the run dies.
ROADMAP open item 1's hardest blocker is exactly that shape: five
consecutive TPU benches wedged at backend init with nobody watching.
Podracer-style decoupled layouts (PAPERS.md, arXiv 2104.06272) live or
die on actor/learner *utilization you can see while it runs*, and the
fleet-scale serving story (EnvPool's share-nothing engines) needs a
scrapeable per-engine metrics surface before any load balancer can
exist. This module is that surface:

* :class:`MetricsHub` — a thread-safe in-memory metric store: gauges,
  counters (both optionally labeled), bounded sliding-sample windows
  for quantile gauges (``<name>_p50``/``_p99`` at scrape time), live
  *probes* (callables evaluated per scrape — the watchdog heartbeat
  reads come from here, so the endpoint shows the armed phase WHILE
  the main thread is wedged inside it), and health checks that drive
  ``/healthz``.
* :class:`PulseServer` — a stdlib-only ``ThreadingHTTPServer``
  (config ``obs.pulse_port``, default 0 = no socket, driver
  byte-identical) with three routes: Prometheus-text ``GET /metrics``,
  JSON ``GET /healthz`` (HTTP 200 ok / 503 degraded — a scrape-side
  load balancer or supervisor needs no JSON parsing to act), and
  ``GET|POST /trace`` arming the on-demand trace capture below.
* :class:`TraceController` — on-demand device-time capture on a LIVE
  run: a ``<run_dir>/PULSE_TRACE`` file (touch it from any shell) or
  the ``/trace`` endpoint arms one bounded
  :class:`obs.device_time.ProgramTraceWindow` at the next iteration
  boundary, so a slow TPU session can be profiled without restart.
  The capture lands in ``<run_dir>/pulse_trace_*`` with
  ``device_times.json`` refreshed for the report CLI.

Stdlib-only at import (the bench daemon starts a hub before jax is
importable); the trace controller pulls jax lazily at arm time only.
Wiring lives in ``run.run_sequential`` / ``run.run_sebulba`` and
``serve/frontend.py`` — all behind ``pulse_port`` / ``hub`` guards, so
the off state is byte-identical (docs/OBSERVABILITY.md §pulse).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from .spans import NULL_RECORDER

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

#: metric-name prefix on the rendered endpoint (Prometheus convention:
#: one namespace per exporter)
PREFIX = "t2omca_"


def _sanitize(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _render_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{_sanitize(k)}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class MetricsHub:
    """Thread-safe metric store behind the endpoint. All writers are
    hot-path-adjacent (driver cadences, serve requests), so every
    operation is one uncontended lock acquire plus a dict/deque touch;
    rendering and probe evaluation happen on the scrape thread."""

    def __init__(self, window: int = 512) -> None:
        self.window = max(int(window), 16)
        self._lock = threading.Lock()
        # (name, ((k, v), ...)) -> float
        self._gauges: Dict[Tuple[str, tuple], float] = {}
        self._counters: Dict[Tuple[str, tuple], float] = {}
        self._windows: Dict[str, deque] = {}
        # probes: fn() -> iterable of (name, labels_dict, value); read
        # per scrape so the endpoint reports live state (watchdog
        # heartbeat age) even while every writer thread is wedged
        self._probes: List[Callable[[], Any]] = []
        self._health: Dict[str, Callable[[], Tuple[bool, str]]] = {}
        self._trace_req = threading.Event()
        self._beat = time.monotonic()

    # -- writers ---------------------------------------------------------

    @staticmethod
    def _key(name: str, labels: Dict[str, Any]) -> Tuple[str, tuple]:
        return (name, tuple(sorted((k, str(v)) for k, v in labels.items())))

    def set(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = float(value)

    def inc(self, name: str, delta: float = 1.0, **labels) -> None:
        key = self._key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + float(delta)

    def observe(self, name: str, value: float) -> None:
        """One sample into the bounded sliding window behind the
        ``<name>_p50``/``_p99``/``_count`` quantile gauges."""
        with self._lock:
            w = self._windows.get(name)
            if w is None:
                w = self._windows[name] = deque(maxlen=self.window)
            w.append(float(value))

    def beat(self) -> None:
        """Liveness heartbeat from a writer loop (the driver beats once
        per iteration; ``beat_age_seconds`` on the endpoint then reads
        as 'how long since the loop last moved')."""
        with self._lock:
            self._beat = time.monotonic()

    # -- probes / health -------------------------------------------------

    def probe(self, fn: Callable[[], Any]) -> None:
        """Register a scrape-time metric source: ``fn()`` returns an
        iterable of ``(name, labels_dict, value)`` rows (or None).
        Exceptions are swallowed per probe — telemetry must never take
        the endpoint down."""
        with self._lock:
            self._probes.append(fn)

    def health(self, name: str,
               fn: Callable[[], Tuple[bool, str]]) -> None:
        """Register one ``/healthz`` check: ``fn() -> (ok, detail)``."""
        with self._lock:
            self._health[name] = fn

    # -- trace trigger ---------------------------------------------------

    def request_trace(self) -> None:
        self._trace_req.set()

    def take_trace_request(self) -> bool:
        """Consume a pending ``/trace`` request (one window per arm)."""
        if self._trace_req.is_set():
            self._trace_req.clear()
            return True
        return False

    # -- scrape-side reads -----------------------------------------------

    def _probe_rows(self) -> List[Tuple[str, dict, float]]:
        with self._lock:
            probes = list(self._probes)
        rows: List[Tuple[str, dict, float]] = []
        for fn in probes:
            try:
                for name, labels, value in (fn() or ()):
                    rows.append((str(name), dict(labels), float(value)))
            except Exception:  # noqa: BLE001 — scrape must not crash
                continue
        return rows

    def render_prometheus(self) -> str:
        """The ``/metrics`` body: gauges + counters + quantile gauges
        from the windows + live probe rows, ``t2omca_``-prefixed and
        name-sanitized. Samples are grouped per metric FAMILY with
        exactly one ``# TYPE`` line each — the text-format parser
        rejects a second TYPE line for the same name, which would fail
        the whole scrape the first time a metric carries two label sets
        (two devices, actor+learner watchdog sides, two buckets)."""
        with self._lock:
            gauges = dict(self._gauges)
            counters = dict(self._counters)
            windows = {k: list(v) for k, v in self._windows.items()}
            beat_age = time.monotonic() - self._beat
        # family name -> (kind, [(labels_tuple, value), ...])
        families: Dict[str, Tuple[str, list]] = {}

        def add(name: str, labels, value, kind: str = "gauge") -> None:
            fam = families.setdefault(_sanitize(name), (kind, []))
            fam[1].append((labels, value))

        for (name, labels), v in gauges.items():
            add(name, labels, v)
        for (name, labels), v in counters.items():
            add(name, labels, v, kind="counter")
        for name, samples in windows.items():
            if not samples:
                continue
            s = sorted(samples)
            add(f"{name}_p50", (), s[len(s) // 2])
            add(f"{name}_p99", (), s[min(len(s) - 1,
                                         int(len(s) * 0.99))])
            add(f"{name}_count", (), float(len(s)))
        for name, labels, value in self._probe_rows():
            add(name, tuple(sorted((k, str(v))
                            for k, v in labels.items())), value)
        add("beat_age_seconds", (), beat_age)
        lines: List[str] = []
        for fam in sorted(families):
            kind, rows = families[fam]
            full = PREFIX + fam
            lines.append(f"# TYPE {full} {kind}")
            for labels, value in sorted(rows):
                lines.append(f"{full}{_render_labels(labels)} {value:g}")
        return "\n".join(lines) + "\n"

    def healthz(self) -> Tuple[bool, dict]:
        """→ ``(ok, payload)`` for ``/healthz``: every registered check
        evaluated now; a check that RAISES reports degraded with the
        error (a dead check must read as trouble, not as green)."""
        with self._lock:
            checks = dict(self._health)
            beat_age = time.monotonic() - self._beat
        results: Dict[str, dict] = {}
        ok = True
        for name, fn in sorted(checks.items()):
            try:
                c_ok, detail = fn()
            except Exception as e:  # noqa: BLE001 — degraded, not down
                c_ok, detail = False, f"check failed: {type(e).__name__}: {e}"
            ok = ok and bool(c_ok)
            results[name] = {"ok": bool(c_ok), "detail": str(detail)}
        return ok, {"status": "ok" if ok else "degraded",
                    "beat_age_s": round(beat_age, 3),
                    "checks": results}


def _watched(phase, rec, **meta):
    """One spanned endpoint boundary (the serve/frontend.py pattern —
    module-level and named so graftlint GL110 pins every literal phase
    here against ``obs/spans.KNOWN_PHASES``)."""
    return rec.span(phase, **meta)


class _PulseHandler(BaseHTTPRequestHandler):
    server_version = "graftpulse/1"
    protocol_version = "HTTP/1.1"

    # silence the default per-request stderr line — the scrape cadence
    # would spam the training console
    def log_message(self, fmt, *args):  # noqa: D102 — stdlib override
        pass

    def _reply(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _route(self) -> None:
        hub: MetricsHub = self.server.hub          # type: ignore[attr-defined]
        rec = self.server.rec                      # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            # _ring=False: a 5 s scrape cadence must not evict the
            # pre-stall phase history from the bounded flight ring
            with _watched("pulse.scrape", rec, endpoint="/metrics",
                          _ring=False):
                self._reply(200, hub.render_prometheus(),
                            "text/plain; version=0.0.4")
        elif path == "/healthz":
            with _watched("pulse.scrape", rec, endpoint="/healthz",
                          _ring=False):
                ok, payload = hub.healthz()
                self._reply(200 if ok else 503, json.dumps(payload),
                            "application/json")
        elif path == "/trace":
            if not getattr(self.server, "trace_supported", True):
                # no TraceController behind this endpoint (the jax-free
                # bench daemon): acking would leave the caller waiting
                # on a capture that can never happen
                self._reply(501, json.dumps(
                    {"armed": False,
                     "error": "no trace consumer on this endpoint"}),
                    "application/json")
                return
            with _watched("trace.trigger", rec, source="endpoint"):
                hub.request_trace()
                self._reply(200, json.dumps({"armed": True}),
                            "application/json")
        else:
            self._reply(404, json.dumps(
                {"error": f"unknown path {path!r}",
                 "routes": ["/metrics", "/healthz", "/trace"]}),
                "application/json")

    def do_GET(self) -> None:           # noqa: N802 — stdlib naming
        try:
            self._route()
        except (BrokenPipeError, ConnectionResetError):
            pass                        # scraper went away mid-reply

    do_POST = do_GET                    # /trace accepts both verbs


class PulseServer:
    """The endpoint: a daemon-threaded stdlib HTTP server over one
    :class:`MetricsHub`. ``port=0`` binds an ephemeral port (tests);
    the config layer only constructs a server for ``pulse_port > 0``.
    ``close()`` is idempotent and bounded — shutting the plane down
    must never hang the run's exit path."""

    def __init__(self, hub: MetricsHub, port: int,
                 host: str = "127.0.0.1", rec=NULL_RECORDER,
                 trace_supported: bool = True) -> None:
        self.hub = hub
        self._srv = ThreadingHTTPServer((host, port), _PulseHandler)
        self._srv.daemon_threads = True
        self._srv.hub = hub             # type: ignore[attr-defined]
        self._srv.rec = rec             # type: ignore[attr-defined]
        # False = no TraceController consumes this hub's trace requests
        # (the bench daemon): /trace then reports 501 instead of acking
        self._srv.trace_supported = trace_supported  # type: ignore[attr-defined]
        self.port = self._srv.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PulseServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        kwargs={"poll_interval": 0.2},
                                        daemon=True, name="t2omca-pulse")
        self._thread.start()
        return self

    def close(self) -> None:
        try:
            if self._thread is not None:
                # shutdown() handshakes with the serve_forever loop —
                # calling it on a constructed-but-never-started server
                # would block forever on an event only that loop sets
                self._srv.shutdown()
            self._srv.server_close()
        except Exception:  # noqa: BLE001 — exit path stays orderly
            pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


class PulseHandle:
    """What the driver holds: the hub, the server, and the wiring
    helpers. Every method is a no-op-safe single call so the driver's
    hot loop stays one ``if pulse is not None`` away from byte-
    identical."""

    def __init__(self, hub: MetricsHub, server: PulseServer) -> None:
        self.hub = hub
        self.server = server
        self._t0 = time.monotonic()
        self._start_t_env: Optional[int] = None

    # -- writers (driver cadences) --------------------------------------

    def set(self, name: str, value, **labels) -> None:
        self.hub.set(name, value, **labels)

    def tick_iteration(self, t_env: int, episode: int) -> None:
        """Once per driver iteration: liveness beat + the cheap
        cumulative-rate gauges, so ``/metrics`` answers before the
        first log cadence ever fires."""
        self.hub.beat()
        if self._start_t_env is None:
            self._start_t_env = int(t_env)
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        self.hub.set("t_env", t_env)
        self.hub.set("episode", episode)
        self.hub.set("env_steps_per_sec_avg",
                     (int(t_env) - self._start_t_env) / elapsed)

    def set_memwatch(self, snap: Optional[dict]) -> None:
        """Per-device HBM gauges from one memwatch snapshot."""
        if not snap:
            return
        for dev, s in snap.items():
            self.hub.set("hbm_bytes_in_use", s.get("bytes_in_use", 0),
                         device=dev)
            self.hub.set("hbm_peak_bytes", s.get("peak_bytes_in_use", 0),
                         device=dev)

    # -- wiring ----------------------------------------------------------

    def wire_watchdog(self, wd, side: str = "main") -> None:
        """Live watchdog gauges + a health check: the armed phase and
        its in-flight seconds are read PER SCRAPE from the watchdog's
        own lock-bounded snapshot — visible while the main thread is
        wedged inside the armed call (the read this plane exists for).
        ``/healthz`` degrades the moment a stall fires."""
        def rows():
            hb = wd.heartbeat()
            out = [("watchdog_heartbeat_age_seconds", {"side": side},
                    hb["beat_age_s"]),
                   ("watchdog_stalls_total", {"side": side},
                    hb["stall_count"]),
                   ("watchdog_armed", {"side": side},
                    1.0 if hb["armed_phase"] else 0.0)]
            if hb["armed_phase"]:
                out.append(("watchdog_armed_seconds",
                            {"side": side, "phase": hb["armed_phase"]},
                            hb["armed_s"]))
            return out

        self.hub.probe(rows)
        self.hub.health(
            f"watchdog-{side}",
            lambda: (wd.stall_count == 0,
                     f"stalls={wd.stall_count} "
                     f"armed={wd.heartbeat()['armed_phase']}"))

    def wire_guard(self, guard) -> None:
        self.hub.health(
            "shutdown-guard",
            lambda: (not guard.triggered,
                     f"triggered={guard.triggered} "
                     f"signame={getattr(guard, 'signame', None)}"))

    def close(self) -> None:
        self.server.close()


def make_pulse(obs_cfg, rec=NULL_RECORDER, log=None) -> Optional[PulseHandle]:
    """The driver's constructor: None unless ``obs.pulse_port`` is set
    (the byte-identical off state). Bind failures degrade to a warning
    — a busy port must not take training down. The default bind is
    LOOPBACK: ``/trace`` is an unauthenticated state-changing route
    (it arms profiler captures on the live run), so exposing it beyond
    the host is an explicit ``obs.pulse_host: 0.0.0.0`` decision, not
    a default."""
    port = int(getattr(obs_cfg, "pulse_port", 0) or 0)
    if port <= 0:
        return None
    host = getattr(obs_cfg, "pulse_host", "") or "127.0.0.1"
    hub = MetricsHub(window=getattr(obs_cfg, "pulse_window", 512))
    try:
        server = PulseServer(hub, port, host=host, rec=rec).start()
    except OSError as e:
        if log is not None:
            log.warning(f"graftpulse: could not bind {host}:{port} "
                        f"({e}); metrics endpoint disabled for this run")
        return None
    if log is not None:
        log.info(f"graftpulse: metrics endpoint on {host}:{server.port} "
                 f"(/metrics, /healthz, /trace)")
    return PulseHandle(hub, server)


class TraceController:
    """On-demand trace capture on a live run. ``poll(t_env)`` (called
    once per driver iteration, one ``os.path.exists`` when idle) arms a
    bounded :class:`~..obs.device_time.ProgramTraceWindow` when either
    trigger fires; ``tick`` drives the active window exactly like the
    static profiler window. Each capture lands in its own
    ``pulse_trace_<n>_t<t_env>`` directory and refreshes
    ``<run_dir>/device_times.json`` (newest capture wins — the report
    CLI reads the latest). A new trigger is accepted once the previous
    window closed."""

    #: hard bound on iterations per capture — a fat-fingered config
    #: must not leave the profiler running for the rest of the run
    MAX_ITERATIONS = 20

    def __init__(self, results_dir: str, rec=NULL_RECORDER,
                 hub: Optional[MetricsHub] = None, n_iterations: int = 3,
                 window_factory=None) -> None:
        self.results_dir = results_dir
        self.trigger_path = os.path.join(results_dir, "PULSE_TRACE")
        self._rec = rec
        self._hub = hub
        self.n_iterations = min(max(int(n_iterations), 1),
                                self.MAX_ITERATIONS)
        self._factory = window_factory
        self._win = None
        self.captures = 0

    def _make_window(self, trace_dir: str):
        if self._factory is not None:
            return self._factory(trace_dir, out_dir=self.results_dir,
                                 n_iterations=self.n_iterations)
        from .device_time import ProgramTraceWindow
        return ProgramTraceWindow(trace_dir, start_t_env=0,
                                  n_iterations=self.n_iterations,
                                  out_dir=self.results_dir)

    def poll(self, t_env: int) -> None:
        if self._win is not None:
            return
        source = None
        if self._hub is not None and self._hub.take_trace_request():
            source = "endpoint"
        elif os.path.exists(self.trigger_path):
            try:
                os.remove(self.trigger_path)    # consume the trigger
            except OSError:
                pass
            source = "file"
        if source is None:
            return
        self.captures += 1
        trace_dir = os.path.join(
            self.results_dir, f"pulse_trace_{self.captures:02d}_t{t_env}")
        with _watched("trace.trigger", self._rec, t_env=t_env,
                      source=source, capture=self.captures):
            try:
                win = self._make_window(trace_dir)
                win.maybe_start(t_env)
            except Exception:  # noqa: BLE001 — telemetry never kills a run
                return
        self._win = win
        if self._hub is not None:
            self._hub.set("trace_captures_total", self.captures)

    def tick(self, logger=None, t_env: int = 0) -> None:
        win = self._win
        if win is None:
            return
        try:
            win.tick(logger, t_env)
        except Exception:  # noqa: BLE001 — profiler stop must not crash
            self._win = None
            return
        if getattr(win, "_done", False):
            self._win = None            # window closed: accept new triggers
