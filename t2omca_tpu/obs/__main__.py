"""``python -m t2omca_tpu.obs`` — the graftscope/graftpulse CLI.

Subcommands:

``report <run_dir>``
    Join the run's span telemetry (``spans.jsonl``) and optional
    device-time attribution (``device_times.json``) against graftprog's
    FLOPs/bytes budgets (``analysis/programs.json``) into the per-
    program roofline table (docs/OBSERVABILITY.md). Exit 0 = report
    printed, 2 = usage error. Degraded inputs render instead of
    raising: a torn final JSONL line (killed run) is skipped with a
    warning, and a run dir holding only a ``flight_recorder.json``
    reports from the flight tail.

``timeline [BENCH_r*.json ...] [--runs <run_dir> ...]``
    The longitudinal perf-trajectory table over the repo's BENCH_r*
    records (all historical shapes) and recorded runs' metrics.jsonl,
    distinguishing measured numbers from wedged partials
    (docs/OBSERVABILITY.md §pulse).

``learning <run_dir>``
    The graftsight learning-health report (docs/OBSERVABILITY.md §6):
    per-module gradient norms, PER health, attention entropies, value
    histograms, detector verdicts and per-scenario-slice learning
    curves from the run's ``metrics.jsonl`` (tolerant reader — torn
    tails from killed runs are skipped with a warning). Answers "is
    this run learning?" post-mortem.

All are deliberately jax-free — the post-mortem host may not be able
to initialize a backend at all.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m t2omca_tpu.obs",
        description="graftscope/graftpulse: run telemetry tools "
                    "(docs/OBSERVABILITY.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser(
        "report", help="per-program roofline report for a recorded run")
    rep.add_argument("run_dir",
                     help="results directory of a run recorded with "
                          "obs.enabled=true (holds spans.jsonl)")
    rep.add_argument("--programs-json", default=None,
                     help="graftprog budgets to join against "
                          "(default: analysis/programs.json)")
    rep.add_argument("--peak-gflops", type=float, default=None,
                     help="chip peak GFLOP/s — adds the roofline bound "
                          "and achieved fraction per program")
    rep.add_argument("--peak-gbps", type=float, default=None,
                     help="chip peak memory bandwidth in GB/s (used "
                          "with --peak-gflops)")
    tl = sub.add_parser(
        "timeline", help="longitudinal perf-trajectory table over "
                         "BENCH_r*.json records and run dirs")
    tl.add_argument("paths", nargs="*",
                    help="BENCH record files (default: BENCH_r*.json "
                         "in the current directory)")
    tl.add_argument("--runs", nargs="*", default=[], metavar="RUN_DIR",
                    help="recorded run directories whose metrics.jsonl "
                         "joins the table (newest env-steps/s)")
    tl.add_argument("--json", action="store_true",
                    help="machine-readable rows instead of the table")
    ln = sub.add_parser(
        "learning", help="graftsight learning-health report for a "
                         "recorded run (docs/OBSERVABILITY.md §6)")
    ln.add_argument("run_dir",
                    help="results directory of a run (holds "
                         "metrics.jsonl; obs.sight.enabled adds the "
                         "learning-dynamics keys)")
    args = parser.parse_args(argv)
    if args.cmd == "learning":
        from .sight import learning_main
        return learning_main(args.run_dir)
    if args.cmd == "report":
        from .report import report_main
        return report_main(args.run_dir, args.programs_json,
                           args.peak_gflops, args.peak_gbps)
    if args.cmd == "timeline":
        from .timeline import timeline_main
        return timeline_main(args.paths, args.runs, as_json=args.json)
    parser.error(f"unknown command {args.cmd!r}")
    return 2


if __name__ == "__main__":
    sys.exit(main())
