"""graftscope — runtime observability for the training/bench stack.

Four pieces (docs/OBSERVABILITY.md):

* **span tracing** (``spans.py``) — a low-overhead host-side span
  recorder threaded through every device-facing boundary the watchdog
  stamps, emitting structured JSONL alongside the Logger sinks;
* **device-time attribution** (``device_time.py``) — a
  ``jax.profiler`` trace window that maps captured events back to the
  registry's named programs (``analysis/registry.TRACE_SYMBOLS``);
* **flight recorder** (``spans.py``) — a bounded ring of recent
  events persisted atomically on stall/crash/non-finite/SIGTERM and
  merged into the watchdog's ``stall_diagnosis.json``;
* **report CLI** (``python -m t2omca_tpu.obs report <run_dir>``) —
  joins the runtime telemetry against graftprog's FLOPs/bytes budgets
  into a roofline-style per-program breakdown.

Plus the **graftpulse live plane** (docs/OBSERVABILITY.md §pulse):
``pulse.py`` (Prometheus-text ``/metrics`` + ``/healthz`` + on-demand
``/trace`` behind ``obs.pulse_port``), ``memwatch.py`` (phase-
attributed HBM high-water snapshots merged into the flight/stall
artifacts), and ``timeline.py`` (the jax-free
``python -m t2omca_tpu.obs timeline`` longitudinal BENCH trajectory).

The span/report half is stdlib-only; ``device_time`` pulls in jax, so
its names resolve lazily — importing ``t2omca_tpu.obs`` must stay
cheap enough for the jax-free report CLI.
"""

from __future__ import annotations

from .spans import (KNOWN_PHASES, NULL_RECORDER, NullRecorder,
                    SpanRecorder, make_recorder, stacked)

_LAZY = {
    "ProgramTraceWindow": "device_time",
    "parse_trace_device_times": "device_time",
    "PHASE_PROGRAMS": "report",
    "report_main": "report",
    # graftpulse live telemetry plane (stdlib-only modules; lazy so the
    # jax-free CLIs pay nothing for what they don't use)
    "MetricsHub": "pulse",
    "PulseServer": "pulse",
    "TraceController": "pulse",
    "make_pulse": "pulse",
    "MemWatch": "memwatch",
    "make_memwatch": "memwatch",
    "timeline_main": "timeline",
    # graftsight learning-dynamics telemetry (stdlib+numpy at import;
    # the in-graph helpers pull jax lazily inside their bodies)
    "SightMonitor": "sight",
    "make_monitor": "sight",
    "learning_main": "sight",
}

__all__ = ["KNOWN_PHASES", "NULL_RECORDER", "NullRecorder",
           "SpanRecorder", "make_recorder", "stacked", *sorted(_LAZY)]


def __getattr__(name: str):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
