"""graftscope span tracing: the host-side runtime telemetry recorder.

ROADMAP open item 1 needs to know where a dispatch's wall-clock goes,
and BENCH_r03–r05 died at backend init leaving no trail — nothing in
the repo could say *which phase* a wedged run was in, or how long the
phases before it took. Podracer (arxiv 2104.06272) attributes its TPU
utilization wins to exactly this per-phase accounting. This module is
the host half of that story (device-time attribution lives in
``obs/device_time.py``):

* :class:`SpanRecorder` — a low-overhead span recorder. The driver
  wraps every device-facing boundary it already stamps for the
  watchdog (``run.run_sequential`` ``_watched``/``_sync_point`` sites,
  ``bench.py`` probe/measure phases, the checkpoint save) in
  ``rec.span(phase, t_env=..., **meta)``; each completed span becomes
  one structured JSONL event in ``<run_dir>/spans.jsonl`` alongside the
  ``Logger`` sinks. Overhead is a couple of ``perf_counter`` calls, a
  dict build and a deque append per span (measured < 20 µs on the CI
  box — docs/OBSERVABILITY.md) — well under 1% of any steady-state
  iteration.
* **flight recorder** — the same recorder keeps a bounded in-memory
  ring of the last ``ring_size`` events plus every still-open span.
  ``tail()`` returns them completed-first, open-last (so the hanging
  span of a stalled dispatch is the LAST entry), and
  ``persist(path)`` writes the tail atomically (tmp + rename) — the
  driver calls it on stall, crash, non-finite trip and SIGTERM, and
  merges it into the watchdog's ``stall_diagnosis.json``.
* :class:`NullRecorder` — the default. Telemetry is opt-in
  (``config.ObsConfig.enabled``); with it off every ``span()`` returns
  a shared no-op context and the driver path is behaviorally identical
  to a build without this module.

Event schema (docs/OBSERVABILITY.md): every line is one JSON object.

``{"event": "span", "seq": N, "phase": str, "t_env": int, "t0":
<epoch s>, "wall_ms": float, "outcome": "ok" | "error:<Type>",
"depth": <nesting>, ["first": true,] ...meta}``
    one completed span; ``first`` marks the first completion of the
    phase (it includes the XLA compile — the watchdog's compile
    exemption made measurable, so compile-vs-stall is distinguishable
    post-mortem). ``meta`` carries call-site context (``attempt``,
    ``k``, ...).
``{"event": "mark", "seq": N, "kind": str, "t0": <epoch s>, ...meta}``
    one point event (run header, ladder action, non-finite trip,
    shutdown). The ``kind == "run"`` mark is the run header the report
    CLI (``python -m t2omca_tpu.obs report``) uses to scale graftprog's
    audit-config FLOPs/bytes budgets to the run's shapes.

Everything here is stdlib-only and jit-free — the report CLI and the
tests must not pay jax import/backend startup for it.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils.ioutil import write_json_atomic

#: The span phases the driver/bench are allowed to use. graftlint rule
#: GL110 checks every ``_watched``/``_sync_point``/``_dispatch`` call
#: site with a literal phase against this set, so a NEW device-facing
#: boundary cannot silently appear without span (and therefore flight-
#: recorder) coverage. Keep in sync with the hook-point table in
#: ``utils/resilience.py`` and docs/RESILIENCE.md §5 — the phase names
#: ARE the fault-injection hook names where both exist.
KNOWN_PHASES = frozenset({
    # driver dispatch boundaries (run.py _dispatch via _watched)
    "dispatch.superstep", "dispatch.rollout", "dispatch.train",
    "dispatch.test",
    # driver sync/fetch boundaries (run.py _sync_point via _watched)
    "dispatch.wait", "fetch.train_infos", "fetch.train_stats",
    "fetch.test_stats",
    # sebulba decoupled-loop boundaries (run.run_sebulba,
    # parallel/sebulba.py): actor-mesh rollout dispatch, the trajectory
    # queue's two ends (put = actor-side d2d copy + slot scatter, its
    # wait is backpressure = actor idle; get = learner-side slot gather
    # + ring insert, its wait is starvation = learner idle), the
    # learner-mesh train dispatch, and the staleness-bounded
    # learner→actor parameter publish/adopt hop
    "actor.dispatch", "queue.put", "queue.get", "learner.dispatch",
    "params.sync",
    # checkpoint + startup boundaries. graftmorph (docs/RESILIENCE.md
    # §6) adds the elastic-resume routing boundary (checkpoint.elastic:
    # host read + topology reshape before placement), the coordinated-
    # preemption peer barrier (preempt.barrier: bounded KV-store
    # rendezvous agreeing on the cut step), and the degraded per-host
    # shard write (checkpoint.shard_save: the collective-free fallback
    # when a peer died mid-preemption)
    "checkpoint.save", "collective.gather", "backend.init",
    "checkpoint.elastic", "preempt.barrier", "checkpoint.shard_save",
    # bench.py phases (bench harness spans; embedded in BENCH_r*.json).
    # bench.probe is the RETRYABLE backend-init phase (per-attempt
    # budget split + backoff ladder); bench.probe.fallback is the
    # JAX_PLATFORMS='' auto-fallback probe that runs after it fails —
    # its outcome lands in the failure record's `fallback` block
    "bench.probe", "bench.probe.fallback", "bench.build",
    "bench.compile", "bench.warm", "bench.measure",
    # graftserve boundaries (serve/export.py, serve/frontend.py): the
    # exporter's lower/compile/export pass, artifact load, and the
    # three per-request front-end stages — `obs report` reads a
    # serving run's spans.jsonl exactly like a training run's
    "serve.export", "serve.load", "serve.pad", "serve.dispatch",
    "serve.unpad",
    # graftfleet multi-engine serving (serve/fleet.py): per-engine
    # artifact load, the supervised per-request dispatch envelope (the
    # watchdog-stamped boundary; serve.* spans nest inside it), the
    # engine health-check dispatch, a quarantined engine's restart
    # reload, and the rolling hot-param-refresh path (fold + roll
    # stages). bench.chaos is the chaos traffic leg's measure window
    # (bench.py --serve --chaos)
    "fleet.load", "fleet.dispatch", "fleet.selfcheck", "fleet.restart",
    "fleet.refresh", "bench.chaos",
    # graftpulse live telemetry plane (obs/pulse.py, obs/memwatch.py):
    # one /metrics-endpoint scrape, one per-device HBM snapshot, the
    # PULSE_TRACE-file / /trace-endpoint arming of a live trace window,
    # and the bench daemon's two orchestration boundaries (the backoff-
    # laddered backend-init probe and one A/B matrix leg subprocess)
    "pulse.scrape", "memwatch.snapshot", "trace.trigger",
    "bench.daemon.probe", "bench.daemon.leg",
    # graftsight (obs/sight.py): the host-side RL-health detector pass
    # over the log-cadence fetched train info — host-only (no device
    # traffic), spanned so a slow sink/detector shows up in the phase
    # tables instead of silently inflating the log cadence
    "sight.detect",
})

_NOOP = contextlib.nullcontext()


class _Span:
    """Stamp/record pair (plain class with slots, same reasoning as
    ``watchdog._Watch``: contextmanager generators hold frames other
    threads would race, and allocation cost is the overhead budget)."""

    __slots__ = ("_rec", "_ev", "_pc0")

    def __init__(self, rec: "SpanRecorder", ev: Dict[str, Any]):
        self._rec = rec
        self._ev = ev
        self._pc0 = 0.0

    def __enter__(self) -> None:
        self._pc0 = self._rec._begin(self._ev)

    def __exit__(self, exc_type, *exc) -> None:
        self._rec._end(self._ev, self._pc0, exc_type)


class _Stacked:
    """Enter ``outer`` then ``inner``; exit in reverse. The driver pairs
    the watchdog stamp (outer — it must cover the span bookkeeping too)
    with the span record (inner) without paying an ExitStack."""

    __slots__ = ("_outer", "_inner", "_entered")

    def __init__(self, outer, inner):
        self._outer, self._inner = outer, inner
        self._entered = False

    def __enter__(self):
        self._outer.__enter__()
        try:
            self._inner.__enter__()
            self._entered = True
        except BaseException:
            self._outer.__exit__(None, None, None)
            raise
        return None

    def __exit__(self, *exc) -> None:
        try:
            if self._entered:
                self._inner.__exit__(*exc)
        finally:
            self._outer.__exit__(*exc)


def stacked(outer, inner) -> _Stacked:
    return _Stacked(outer, inner)


class SpanRecorder:
    """Span + event recorder with a bounded flight ring and an optional
    JSONL sink. Thread-safe: the watchdog/stall threads may record
    marks while the main thread holds open spans."""

    enabled = True

    def __init__(self, ring_size: int = 256,
                 jsonl_path: Optional[str] = None,
                 flush_every: int = 32) -> None:
        self.ring_size = max(int(ring_size), 1)
        self.jsonl_path = jsonl_path
        self.flush_every = max(int(flush_every), 1)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.ring_size)
        self._open: Dict[int, Dict[str, Any]] = {}   # seq -> open span event
        self._open_pc: Dict[int, float] = {}         # seq -> perf_counter at begin
        self._seq = 0
        self._first_pending: set = set()             # phases never completed
        self._depth = threading.local()
        self._file = None
        self._unflushed = 0
        # per-phase aggregation for summary() — O(1) per span, no event
        # replay (the ring may have evicted early spans)
        self._agg: Dict[str, Dict[str, float]] = {}

    # -- recording -------------------------------------------------------

    def span(self, phase: str, t_env: int = 0, _ring: bool = True,
             **meta) -> _Span:
        """Context manager recording one span. ``meta`` must be
        JSON-serializable scalars (attempt counts, K, ...).
        ``_ring=False`` keeps the completed span OUT of the flight ring
        (it still lands in the JSONL sink and the per-phase aggregate):
        for high-frequency decorative spans — the pulse endpoint's
        per-scrape spans — which would otherwise evict the pre-stall
        phase history the ring exists to preserve (a 5 s scrape cadence
        fills a 256-slot ring in ~21 min, shorter than one
        compile-scale hang)."""
        ev: Dict[str, Any] = {"event": "span", "phase": phase,
                              "t_env": int(t_env)}
        if not _ring:
            ev["_ring"] = False
        if meta:
            ev.update(meta)
        return _Span(self, ev)

    def _begin(self, ev: Dict[str, Any]) -> float:
        d = getattr(self._depth, "n", 0)
        self._depth.n = d + 1
        ev["depth"] = d
        ev["t0"] = round(time.time(), 3)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._open[ev["seq"]] = ev
            pc0 = time.perf_counter()
            self._open_pc[ev["seq"]] = pc0
        return pc0

    def _end(self, ev: Dict[str, Any], pc0: float, exc_type) -> None:
        wall_ms = (time.perf_counter() - pc0) * 1000.0
        self._depth.n = getattr(self._depth, "n", 1) - 1
        phase = ev["phase"]
        with self._lock:
            # ev is still registered in _open until the pop below, and
            # tail() (called from the watchdog stall thread) copies
            # open-span dicts under this lock — inserting the
            # completion keys outside it would race that copy
            ev["wall_ms"] = round(wall_ms, 3)
            ev["outcome"] = ("ok" if exc_type is None
                             else f"error:{exc_type.__name__}")
            self._open.pop(ev["seq"], None)
            self._open_pc.pop(ev["seq"], None)
            a = self._agg.get(phase)
            if a is None:
                a = self._agg[phase] = {"n": 0, "total_ms": 0.0,
                                        "max_ms": 0.0, "first_ms": -1.0}
            a["n"] += 1
            a["total_ms"] += wall_ms
            a["max_ms"] = max(a["max_ms"], wall_ms)
            if exc_type is None and a["first_ms"] < 0:
                # first CLEAN completion = the compile-inclusive
                # occurrence (matches the watchdog's compile exemption:
                # an exception is not a completion)
                a["first_ms"] = wall_ms
                ev["first"] = True
            if ev.pop("_ring", True):
                self._ring.append(ev)
            self._sink(ev)

    def mark(self, kind: str, **meta) -> None:
        """Record one point event (run header, ladder action, ...)."""
        ev: Dict[str, Any] = {"event": "mark", "kind": kind,
                              "t0": round(time.time(), 3)}
        if meta:
            ev.update(meta)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
            self._sink(ev)

    # -- sink ------------------------------------------------------------

    def _sink(self, ev: Dict[str, Any]) -> None:
        """Append one event line (lock held). Best-effort: telemetry
        must never be the thing that crashes the run."""
        if self.jsonl_path is None:
            return
        try:
            # default=repr: a non-JSON meta value (numpy scalar, pytree
            # leaf) degrades to its repr instead of a TypeError out of
            # the hot-loop span bookkeeping
            line = json.dumps(ev, default=repr)
        except (TypeError, ValueError):     # circular refs etc.
            return                          # drop the event, keep the sink
        try:
            if self._file is None:
                os.makedirs(os.path.dirname(self.jsonl_path) or ".",
                            exist_ok=True)
                self._file = open(self.jsonl_path, "a")
            self._file.write(line + "\n")
            self._unflushed += 1
            if self._unflushed >= self.flush_every:
                self._file.flush()
                self._unflushed = 0
        except OSError:
            self.jsonl_path = None          # disk trouble: stop trying

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.flush()
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    # -- flight recorder -------------------------------------------------

    def tail(self) -> List[Dict[str, Any]]:
        """Flight-recorder tail: the last ``ring_size`` completed
        events in completion order, then every still-open span (start
        order) marked ``"open": true`` with its wall so far — so a
        stalled dispatch's hanging span is always the LAST entry."""
        now = time.perf_counter()
        with self._lock:
            out = [dict(ev) for ev in self._ring]
            for seq in sorted(self._open):
                ev = dict(self._open[seq])
                ev.pop("_ring", None)   # internal flag, not schema
                ev["open"] = True
                ev["wall_ms"] = round(
                    (now - self._open_pc[seq]) * 1000.0, 3)
                out.append(ev)
        return out

    def current_phase(self) -> Optional[str]:
        """Innermost still-open span's phase (None when idle) — the
        bench failure record's ``phase`` field."""
        with self._lock:
            if not self._open:
                return None
            return self._open[max(self._open)]["phase"]

    def persist(self, path: str,
                extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Atomically write the flight tail as JSON (tmp + rename).
        Best-effort; returns the path or None. ``extra`` is merged into
        the payload next to the events — the driver passes the HBM
        memwatch report (obs/memwatch.py) so an OOM/wedge flight dump
        says what held device memory."""
        try:
            # default=repr lives in the helper, same reason as _sink:
            # the flight dump runs on crash/stall paths where raising
            # is worst-case
            payload: Dict[str, Any] = {"version": 1, "events": self.tail()}
            if extra:
                payload.update(extra)
            return write_json_atomic(path, payload)
        except (OSError, TypeError, ValueError):
            return None

    # -- aggregation -----------------------------------------------------

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-phase aggregate: ``{phase: {n, total_ms, max_ms,
        first_ms, steady_ms}}``. ``first_ms`` is the compile-inclusive
        first clean completion (-1 when none completed cleanly);
        ``steady_ms`` is the mean over the rest (the warm rate)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for phase, a in self._agg.items():
                rest_n = a["n"] - (1 if a["first_ms"] >= 0 else 0)
                rest_total = a["total_ms"] - max(a["first_ms"], 0.0)
                out[phase] = {
                    "n": a["n"],
                    "total_ms": round(a["total_ms"], 3),
                    "max_ms": round(a["max_ms"], 3),
                    "first_ms": round(a["first_ms"], 3),
                    "steady_ms": (round(rest_total / rest_n, 3)
                                  if rest_n > 0 else -1.0),
                }
        return out


class NullRecorder:
    """The disabled-telemetry recorder: every operation is a no-op and
    ``span()`` returns one shared ``nullcontext`` — the driver hot loop
    pays a truthiness check and nothing else."""

    enabled = False
    jsonl_path = None

    def span(self, phase: str, t_env: int = 0, **meta):
        return _NOOP

    def mark(self, kind: str, **meta) -> None:
        pass

    def tail(self) -> List[Dict[str, Any]]:
        return []

    def current_phase(self) -> Optional[str]:
        return None

    def persist(self, path: str, extra=None) -> Optional[str]:
        return None

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {}

    def close(self) -> None:
        pass


#: shared disabled recorder (stateless — safe to share process-wide)
NULL_RECORDER = NullRecorder()


def make_recorder(obs_cfg, run_dir: Optional[str] = None):
    """Recorder for a run: :data:`NULL_RECORDER` unless
    ``obs_cfg.enabled``; the JSONL sink lands in
    ``<run_dir>/spans.jsonl`` when a run directory is given."""
    if obs_cfg is None or not getattr(obs_cfg, "enabled", False):
        return NULL_RECORDER
    path = (os.path.join(run_dir, "spans.jsonl")
            if run_dir else None)
    return SpanRecorder(ring_size=obs_cfg.ring_size, jsonl_path=path,
                        flush_every=obs_cfg.flush_every)
