"""graftscope device-time attribution: profiler trace → named programs.

``utils/profiling.TraceWindow`` captures a raw ``jax.profiler`` trace —
useful in TensorBoard/Perfetto, invisible to the metric stream. This
module closes the loop: :class:`ProgramTraceWindow` parses the captured
trace and maps its events back to the registry's named hot programs
(``analysis/registry.TRACE_SYMBOLS``: ``rollout`` / ``insert`` /
``train_iter`` / ``superstep``), so per-program device time becomes a
first-class stat (``device_ms_<program>`` in the Logger sinks) and a
run artifact (``<run_dir>/device_times.json``) the report CLI joins
against graftprog's FLOPs/bytes budgets.

Parsing notes (honesty about limits): jax writes Chrome-trace JSON
(``**/*.trace.json.gz``) whose complete events (``"ph": "X"``) carry a
``dur`` in microseconds. A program's executable shows up on several
tracks (host dispatch TraceMe, device computation lanes) under names
containing its jit symbol; summing across ALL of them would
double-count host + device, so the parser groups matches by lane
(``pid``, ``tid``) and attributes the single largest-total lane — on
TPU a device stream, on CPU the executor thread (wall-dominated, still
honest relative attribution). One lane, not one ``pid``: merging a
pid's streams would make the containment dedupe below drop legitimate
overlapping executions, so a program whose events split across device
streams is attributed from its busiest stream (an undercount, stated
here rather than silently mixed). No match (profiler version drift,
program renamed) yields an empty entry, never a crash — telemetry must
not take the run down.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Dict, Iterable, Optional, Tuple

from ..utils.ioutil import write_json_atomic
from ..utils.profiling import TraceWindow


def _iter_trace_events(trace_dir: str) -> Iterable[dict]:
    """Yield every traceEvent dict found under ``trace_dir`` (both
    ``.trace.json.gz`` and plain ``.trace.json`` files)."""
    patterns = (os.path.join(trace_dir, "**", "*.trace.json.gz"),
                os.path.join(trace_dir, "**", "*.trace.json"))
    for pat in patterns:
        for path in sorted(glob.glob(pat, recursive=True)):
            try:
                opener = gzip.open if path.endswith(".gz") else open
                with opener(path, "rt") as f:
                    data = json.load(f)
            except (OSError, ValueError):
                continue                # unreadable shard: skip, not crash
            for ev in data.get("traceEvents", []) or []:
                if isinstance(ev, dict):
                    yield ev


def parse_trace_device_times(
        trace_dir: str,
        symbols: Optional[Dict[str, Tuple[str, ...]]] = None,
) -> Dict[str, Dict[str, float]]:
    """→ ``{program: {"device_ms": float, "events": int}}`` for every
    program whose jit symbols match at least one complete event.
    ``symbols`` defaults to ``analysis.registry.TRACE_SYMBOLS``."""
    if symbols is None:
        from ..analysis.registry import TRACE_SYMBOLS
        symbols = TRACE_SYMBOLS
    # per program and symbol rank: pid -> [event_us, ...]. Symbol order
    # is a preference: rank 0 is the device-side XLA module name
    # (``jit__X``), later ranks host fallbacks (``PjitFunction(_X)``,
    # the only form a CPU trace has). A TPU trace carries both, and the
    # host call wall-time would out-total the device lane — rank wins
    # over size so device events are attributed when they exist.
    per_rank: Dict[str, list] = {
        p: [{} for _ in syms] for p, syms in symbols.items()}
    for ev in _iter_trace_events(trace_dir):
        if ev.get("ph") != "X":
            continue
        dur = ev.get("dur")
        name = ev.get("name")
        if not isinstance(dur, (int, float)) or not isinstance(name, str):
            continue
        for prog, syms in symbols.items():
            for rank, s in enumerate(syms):
                if s in name:
                    per_rank[prog][rank].setdefault(
                        (ev.get("pid"), ev.get("tid")), []).append(
                            (float(ev.get("ts", 0.0) or 0.0),
                             float(dur)))
                    break
    out: Dict[str, Dict[str, float]] = {}
    for prog, ranks in per_rank.items():
        lanes = next((r for r in ranks if r), None)
        if lanes is None:
            continue
        # dedupe self-nesting first: the profiler can record the same
        # call under nested same-name annotations (observed on the CPU
        # executor track: two identical-ts PjitFunction events per
        # call) — an event contained in the previously kept one on the
        # same lane is the same execution, not a second dispatch
        per_lane: Dict[object, list] = {}
        for lane, evs in lanes.items():
            evs.sort(key=lambda e: (e[0], -e[1]))
            kept: list = []
            end = -1.0
            for ts, dur in evs:
                if ts + dur <= end:
                    continue
                kept.append(dur)
                end = max(end, ts + dur)
            per_lane[lane] = kept
        # one track only: summing host dispatch + device lanes would
        # double-count the same execution
        durs = max(per_lane.values(), key=sum)
        durs.sort()
        # median event duration: robust to the compile-inclusive first
        # call on host executor tracks (a 30 s outlier next to 0.4 s
        # warm dispatches) and fair on device lanes where no such
        # outlier exists — the report's per-dispatch device time
        out[prog] = {"device_ms": round(sum(durs) / 1000.0, 3),
                     "events": len(durs),
                     "median_ms": round(durs[len(durs) // 2] / 1000.0,
                                        3)}
    return out


class ProgramTraceWindow(TraceWindow):
    """A :class:`TraceWindow` that, on stop, attributes the captured
    trace to the registry's named programs: logs ``device_ms_<prog>``
    through the metric stream and writes ``device_times.json`` into the
    run directory (the report CLI's device-time source). Identical to
    the base window while the trace is running (and a no-op when
    ``trace_dir`` is empty, like the base)."""

    def __init__(self, trace_dir: str, start_t_env: int = 0,
                 n_iterations: int = 3, out_dir: Optional[str] = None,
                 symbols: Optional[Dict[str, Tuple[str, ...]]] = None):
        super().__init__(trace_dir, start_t_env, n_iterations)
        self.out_dir = out_dir
        self.symbols = symbols
        self.device_times: Dict[str, Dict[str, float]] = {}

    def _on_stop(self, logger, t_env: int) -> None:
        super()._on_stop(logger, t_env)
        try:
            self.device_times = parse_trace_device_times(self.trace_dir,
                                                         self.symbols)
        except Exception:  # noqa: BLE001 — diagnostics only
            if logger is not None:
                logger.console_logger.exception(
                    "graftscope: trace attribution failed")
            return
        if logger is not None:
            for prog, d in sorted(self.device_times.items()):
                logger.log_stat(f"device_ms_{prog}", d["device_ms"],
                                t_env)
            if not self.device_times:
                logger.console_logger.info(
                    "graftscope: no registry-program events in the "
                    "trace (profiler version drift?)")
        if self.out_dir:
            try:
                write_json_atomic(
                    os.path.join(self.out_dir, "device_times.json"),
                    {"version": 1, "t_env": int(t_env),
                     "programs": self.device_times})
            except (OSError, TypeError, ValueError):
                pass                    # best-effort, like the spans sink
