"""The serving program: greedy ``select_actions`` as ONE jitted step.

Serving is a different program than training (ROADMAP open item 5): no
exploration, no schedules, no env — just ``q = forward(params, obs,
hidden)`` masked-argmaxed over ``avail``. This module is the single
definition every serve surface builds from: the exporter lowers/compiles
it per batch bucket, the front-end dispatches it, the graftprog registry
audits it, and ``bench.py --serve`` times it — so the program the
latency ratchet pins is the program traffic actually runs.

Bit-parity contract (the K=1-parity convention, pinned by
tests/test_serve.py): with f32 params the step's actions are
bit-identical to the training path's ``BasicMAC.select_actions(...,
test_mode=True)``. That holds by construction — in test mode both
selector families reduce to ``masked_argmax`` over the same
deterministic forward (epsilon is forced to 0; the noisy head takes its
mu-weight eval path), so the serve step simply drops the dead key
plumbing instead of re-deriving the math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..components.action_selectors import masked_argmax

#: batch bucket the compiled-program audit pins (analysis/registry.py):
#: small enough to lower in the tier-1 prelude budget, > 1 so the
#: batch axis is real
SERVE_AUDIT_BATCH = 4


def build_serve_step(mac):
    """→ jitted ``_serve_step(params, obs, avail, hidden) -> (actions,
    hidden')`` for a built ``BasicMAC``.

    ``params`` may be the raw agent variables or a
    ``prepare_acting_params`` pre-fold (the exporter ships the fold);
    ``obs (B, A, obs_dim)`` f32, ``avail (B, A, n_actions)`` bool/int,
    ``hidden (B, A, emb)``. Greedy and deterministic — no PRNG key in
    the signature, so the exported aval set is exactly the request
    surface. The entity-table acting path is deliberately NOT used:
    serving requests arrive as observation tensors, not env states, and
    the qslice forward is exact for the same params."""

    def _serve_step(params, obs, avail, hidden):
        # train-dtype forward (acting=False default): serving's dtype
        # story is the artifact's per-variant cast, NOT the training
        # run's model.act_dtype rollout knob — the exporter folds at the
        # train dtype for the same reason (export.py), so fold and
        # forward always agree and the f32 variant keeps its bit-parity
        # contract with the training path's greedy select_actions
        if mac.use_qslice:
            q, hidden = mac.forward_qslice(params, obs, hidden, key=None,
                                           deterministic=True)
        else:
            q, hidden = mac.forward(params, obs, hidden, key=None,
                                    deterministic=True)
        return masked_argmax(q, avail).astype(jnp.int32), hidden

    return jax.jit(_serve_step)


def serve_avals(mac, obs_dim: int, n_actions: int, batch: int):
    """The request-surface avals for one batch bucket: (obs, avail,
    hidden) ``ShapeDtypeStruct``s. One definition shared by the
    exporter, the audit hook and the front-end's padding, so the
    compiled fingerprint and the dispatched program can't drift."""
    a = mac.n_agents
    obs = jax.ShapeDtypeStruct((batch, a, obs_dim), jnp.float32)
    avail = jax.ShapeDtypeStruct((batch, a, n_actions), jnp.bool_)
    hidden = jax.eval_shape(lambda: mac.init_hidden(batch))
    return obs, avail, hidden


def register_audit_programs(ctx):
    """graftprog registry hook (analysis/registry.py): the greedy serve
    step at the audit config's scale, ratcheted like every other hot
    program — a FLOPs/bytes/fingerprint regression on the serving path
    fails the tier-1 gate statically, before any latency bench runs.
    ``compile=True``: serving is latency-bound, so the peak-memory and
    optimized-HLO budgets matter and the program is small enough to
    compile inside the prelude budget."""
    from ..analysis.registry import AuditProgram
    mac = ctx.exp.mac
    env_info = ctx.exp.env.get_env_info()
    step = build_serve_step(mac)
    # train-dtype fold, like the exporter (act_dtype never reaches serving)
    params = jax.eval_shape(
        lambda p: mac.prepare_acting_params(p, dtype=mac.agent.dtype),
        ctx.ts_shape.learner.params["agent"])
    obs, avail, hidden = serve_avals(mac, env_info["obs_shape"],
                                     env_info["n_actions"],
                                     SERVE_AUDIT_BATCH)
    return {"serve_step": AuditProgram(
        step, (params, obs, avail, hidden), compile=True,
        description=f"greedy AOT serving step (B={SERVE_AUDIT_BATCH} "
                    f"bucket, pre-folded acting params)")}
