"""Batched inference front-end: ragged requests → fixed compiled buckets.

The serving step is AOT-compiled at fixed batch shapes (the export's
power-of-2 buckets); traffic arrives as variable-size request batches.
This module is the host-side seam between the two — the EnvPool /
TF-Agents host-side batching pattern (PAPERS.md, arXiv 2206.10558):

* **bucketing** — a request batch of ``n`` rows pads up to the smallest
  bucket ≥ ``n`` (``pick_bucket``); batches larger than the biggest
  bucket split into max-bucket chunks plus a bucketed remainder, so any
  request size is served by at most ``len(buckets)`` compiled programs.
* **mask-correct padding** — pad rows get an avail mask with ONLY
  action 0 legal (never all-zero: the masked argmax stays well-defined
  with no ±inf edge cases), zero obs and zero hidden; their outputs are
  sliced away in unpad, so padding can never leak into real rows.
* **per-request hidden carry** — ``select`` threads the recurrent
  hidden state explicitly (None = fresh zeros); :class:`SessionStore`
  keys it by caller session ids for multi-turn traffic.
* **telemetry** — every boundary is spanned (``serve.pad`` /
  ``serve.dispatch`` / ``serve.unpad``; GL110 pins the names against
  ``obs/spans.KNOWN_PHASES``), so ``python -m t2omca_tpu.obs report``
  reads a serving run exactly like a training run.

The dispatched program is the export's own ``jax.export`` blob
(deserialized StableHLO — no Python re-trace), falling back to
rebuilding ``build_serve_step`` from the artifact's train config when a
blob is absent; either way the artifact's ``compile_cache/`` makes the
first dispatch a persistent-cache hit instead of a cold XLA compile.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..obs.spans import NULL_RECORDER
from .export import ARTIFACT_FORMAT, enable_compile_cache
from .program import build_serve_step

logger = logging.getLogger(__name__)


def _watched(phase, rec, **meta):
    """One spanned serving boundary. Module-level and named like the
    driver's wrapper so graftlint GL110 checks every literal phase here
    against ``obs/spans.KNOWN_PHASES`` — a new serving boundary cannot
    appear without flight/report coverage."""
    return rec.span(phase, **meta)


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket ≥ ``n`` (``buckets`` sorted ascending). ``n``
    above the largest bucket is the caller's chunking job — asking for
    a bucket for it is a bug, not a clamp."""
    if n < 1:
        raise ValueError(f"request batch must be >= 1 row, got {n}")
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(
        f"request batch {n} exceeds the largest bucket {buckets[-1]} — "
        f"chunk it first (ServeFrontend.select does)")


def pad_request(obs: np.ndarray, avail: np.ndarray, hidden: np.ndarray,
                bucket: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad ``(n, ...)`` request arrays up to ``bucket`` rows. Pad rows:
    zero obs/hidden and an avail mask legalizing ONLY action 0 — real
    rows' masks pass through untouched (cast to bool, the compiled
    aval), so padding is mask-correct by construction."""
    n = obs.shape[0]
    avail = avail.astype(np.bool_, copy=False)
    if n == bucket:
        return obs, avail, hidden
    pad = bucket - n
    pad_avail = np.zeros((pad,) + avail.shape[1:], np.bool_)
    pad_avail[..., 0] = True
    return (np.concatenate([obs, np.zeros((pad,) + obs.shape[1:],
                                          obs.dtype)]),
            np.concatenate([avail, pad_avail]),
            np.concatenate([hidden, np.zeros((pad,) + hidden.shape[1:],
                                             hidden.dtype)]))


class ServeFrontend:
    """Loaded serving artifact + batched dispatch. Build with
    :meth:`load`; thread-compatible with one dispatcher thread (the
    program cache is not locked — shard frontends per thread)."""

    def __init__(self, artifact_dir: str, meta: dict, mac, params,
                 dtype: str, use_exported: bool, rec,
                 hub=None) -> None:
        self.artifact_dir = artifact_dir
        self.meta = meta
        self.dtype = dtype
        self.buckets: List[int] = sorted(int(b) for b in meta["buckets"])
        self.n_agents = int(meta["n_agents"])
        self.obs_dim = int(meta["obs_dim"])
        self.n_actions = int(meta["n_actions"])
        self.emb = int(meta["emb"])
        self._mac = mac
        self._params = params
        self._rec = rec
        # graftpulse MetricsHub (obs/pulse.py, docs/OBSERVABILITY.md
        # §pulse): None (default) = zero extra work per request; set =
        # the scrapeable per-engine surface the fleet-scale story
        # (ROADMAP item 4, EnvPool share-nothing engines) load-balances
        # on — sliding-window select p50/p99, per-bucket request/row
        # counters (padding occupancy), session-LRU fill
        self._hub = hub
        self._use_exported = use_exported
        self._steps: Dict[int, object] = {}
        self._fallback = None

    # ------------------------------------------------------------- load

    @classmethod
    def load(cls, artifact_dir: str, dtype: str = "float32",
             use_exported: bool = True, compile_cache: bool = True,
             rec=NULL_RECORDER, hub=None) -> "ServeFrontend":
        """Load an exported artifact (``serve/export.py`` layout).
        ``dtype`` picks the param variant; ``compile_cache`` points the
        persistent compile cache at the artifact's warm entries
        (process-global jax config — the serving process owns it)."""
        import jax
        from flax import serialization

        with _watched("serve.load", rec, dtype=dtype):
            with open(os.path.join(artifact_dir, "meta.json")) as f:
                meta = json.load(f)
            fmt = meta.get("format", 0)
            if fmt > ARTIFACT_FORMAT:
                raise ValueError(
                    f"serve artifact {artifact_dir} has format v{fmt}, "
                    f"newer than this build's v{ARTIFACT_FORMAT} — "
                    f"upgrade the framework to load it")
            entry = meta.get("params", {}).get(dtype)
            if entry is None:
                raise ValueError(
                    f"artifact {artifact_dir} ships no {dtype!r} param "
                    f"variant (has: {sorted(meta.get('params', {}))})")
            cache_dir = os.path.join(artifact_dir, "compile_cache")
            if compile_cache and meta.get("compile_cache") \
                    and os.path.isdir(cache_dir):
                enable_compile_cache(cache_dir)

            with open(os.path.join(artifact_dir, entry["file"]), "rb") as f:
                blob = f.read()
            import hashlib
            digest = hashlib.sha256(blob).hexdigest()
            if entry.get("sha256") and digest != entry["sha256"]:
                raise ValueError(
                    f"param blob {entry['file']} fails its integrity "
                    f"check ({digest[:12]}… != recorded "
                    f"{entry['sha256'][:12]}…) — re-export the artifact")
            params = jax.device_put(serialization.msgpack_restore(blob))
            del blob

            # rebuild the exact MAC the trainer used — the fallback
            # (and validation) path; the exported blobs carry the
            # program itself
            from ..config import from_dict
            from ..controllers.basic_mac import MAC_REGISTRY
            from ..envs.registry import make_env
            cfg = from_dict(meta["train_config"])
            env_info = make_env(cfg.env_args).get_env_info()
            mac = MAC_REGISTRY[cfg.mac].build(cfg, env_info)
            if (mac.n_agents != meta["n_agents"]
                    or env_info["obs_shape"] != meta["obs_dim"]
                    or env_info["n_actions"] != meta["n_actions"]):
                raise ValueError(
                    f"artifact {artifact_dir} meta disagrees with its "
                    f"own train_config rebuild (agents/obs/actions "
                    f"{meta['n_agents']}/{meta['obs_dim']}/"
                    f"{meta['n_actions']} vs {mac.n_agents}/"
                    f"{env_info['obs_shape']}/{env_info['n_actions']}) "
                    f"— corrupt meta.json?")
        return cls(artifact_dir, meta, mac, params, dtype, use_exported,
                   rec, hub=hub)

    # --------------------------------------------------------- programs

    def _program(self, bucket: int):
        """The compiled step for one bucket: the deserialized
        ``jax.export`` blob when the artifact ships it, else the
        config-rebuilt ``build_serve_step`` (one jitted fn, retraced
        per bucket shape)."""
        fn = self._steps.get(bucket)
        if fn is not None:
            return fn
        import jax
        entry = (self.meta.get("programs", {}).get(self.dtype, {})
                 .get(str(bucket), {}))
        path = entry.get("file")
        if self._use_exported and path:
            from jax import export as jax_export
            with open(os.path.join(self.artifact_dir, path), "rb") as f:
                exported = jax_export.deserialize(f.read())
            fn = jax.jit(exported.call)
        else:
            if self._use_exported and not path:
                logger.warning(
                    "bucket %d has no exported program blob — rebuilding "
                    "the step from the artifact's train config", bucket)
            if self._fallback is None:
                self._fallback = build_serve_step(self._mac)
            fn = self._fallback
        self._steps[bucket] = fn
        return fn

    # ----------------------------------------------------------- serve

    def _validate(self, obs, avail, hidden) -> None:
        a, d, na = self.n_agents, self.obs_dim, self.n_actions
        if obs.ndim != 3 or obs.shape[1:] != (a, d):
            raise ValueError(f"obs must be (n, {a}, {d}), got {obs.shape}")
        if avail.shape != (obs.shape[0], a, na):
            raise ValueError(f"avail must be ({obs.shape[0]}, {a}, {na}), "
                             f"got {avail.shape}")
        if hidden.shape != (obs.shape[0], a, self.emb):
            raise ValueError(f"hidden must be ({obs.shape[0]}, {a}, "
                             f"{self.emb}), got {hidden.shape}")

    def select(self, obs, avail, hidden=None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Greedy actions for a ragged request batch: ``obs (n, A,
        obs_dim)``, ``avail (n, A, n_actions)``, optional carried
        ``hidden (n, A, emb)`` (None = episode start) → ``(actions
        (n, A) int32, hidden' (n, A, emb) f32)``. Blocks until the
        actions are on host — serving is a latency surface, not a
        pipeline."""
        obs = np.asarray(obs, np.float32)
        avail = np.asarray(avail)
        if obs.ndim != 3:
            raise ValueError(f"obs must be (n, {self.n_agents}, "
                             f"{self.obs_dim}), got shape {obs.shape}")
        n = obs.shape[0]
        if hidden is None:
            hidden = np.zeros((n, self.n_agents, self.emb), np.float32)
        else:
            hidden = np.asarray(hidden, np.float32)
        self._validate(obs, avail, hidden)

        bmax = self.buckets[-1]
        actions_out = np.empty((n, self.n_agents), np.int32)
        hidden_out = np.empty((n, self.n_agents, self.emb), np.float32)
        t_req0 = time.perf_counter() if self._hub is not None else 0.0
        for lo in range(0, n, bmax):
            hi = min(lo + bmax, n)
            cn = hi - lo
            bucket = pick_bucket(cn, self.buckets)
            with _watched("serve.pad", self._rec, bucket=bucket, n=cn):
                po, pa, ph = pad_request(obs[lo:hi], avail[lo:hi],
                                         hidden[lo:hi], bucket)
            with _watched("serve.dispatch", self._rec, bucket=bucket):
                a_dev, h_dev = self._program(bucket)(self._params, po,
                                                     pa, ph)
                a_host = np.asarray(a_dev)       # the blocking fetch
                h_host = np.asarray(h_dev, dtype=np.float32)
            with _watched("serve.unpad", self._rec, bucket=bucket):
                actions_out[lo:hi] = a_host[:cn]
                hidden_out[lo:hi] = h_host[:cn]
            if self._hub is not None:
                # per-bucket occupancy counters: rows/ (dispatches ×
                # bucket) is the padding-waste read the bucket tuning
                # needs — one inc pair per compiled dispatch
                self._hub.inc("serve_dispatches_total", bucket=bucket)
                self._hub.inc("serve_rows_total", cn, bucket=bucket)
        if self._hub is not None:
            # whole-request latency into the sliding window: /metrics
            # renders serve_select_ms_p50/_p99 at scrape time
            self._hub.observe(
                "serve_select_ms",
                (time.perf_counter() - t_req0) * 1000.0)
            self._hub.inc("serve_requests_total")
        return actions_out, hidden_out

    def warmup(self) -> None:
        """Dispatch one padded batch per bucket so every compiled
        program exists before traffic (persistent-cache hits when the
        artifact's ``compile_cache/`` is warm)."""
        for b in self.buckets:
            obs = np.zeros((b, self.n_agents, self.obs_dim), np.float32)
            avail = np.ones((b, self.n_agents, self.n_actions), np.bool_)
            self.select(obs, avail)


class SessionStore:
    """Per-session hidden-state carry over a :class:`ServeFrontend`:
    multi-turn traffic names each request row with a session id; the
    store gathers each row's carried hidden (zeros for new sessions),
    serves the batch, and scatters the new hiddens back. Call
    :meth:`end` when a session's episode finishes (or rely on
    ``max_sessions`` LRU eviction — an evicted session restarts from
    zeros, degraded but well-defined, and NOT silent: each eviction
    increments the ``serve_session_evicted`` stat, and :meth:`select`
    returns a per-row ``fresh`` sentinel so a caller who believes a
    session is live can detect the mid-conversation reset)."""

    def __init__(self, frontend: ServeFrontend,
                 max_sessions: int = 100_000) -> None:
        self._fe = frontend
        self._max = int(max_sessions)
        self._h: Dict[object, np.ndarray] = {}
        self.evicted = 0                # cumulative LRU evictions

    def __len__(self) -> int:
        return len(self._h)

    def select(self, session_ids: Sequence, obs, avail
               ) -> Tuple[np.ndarray, np.ndarray]:
        """→ ``(actions (n, A) int32, fresh (n,) bool)``. ``fresh[i]``
        is True when row i's session had NO carried hidden — a brand-new
        session, or a live one whose carry was LRU-evicted (the caller
        knows which ids it just created, so fresh on a supposedly-live
        id IS the eviction sentinel)."""
        if len(session_ids) != np.asarray(obs).shape[0]:
            raise ValueError(
                f"{len(session_ids)} session ids for "
                f"{np.asarray(obs).shape[0]} request rows")
        fe = self._fe
        zeros = np.zeros((fe.n_agents, fe.emb), np.float32)
        fresh = np.array([s not in self._h for s in session_ids], np.bool_)
        hidden = np.stack([self._h.get(s, zeros) for s in session_ids])
        actions, hidden2 = fe.select(obs, avail, hidden)
        for i, s in enumerate(session_ids):
            # move-to-end LRU semantics: re-insert on every touch
            self._h.pop(s, None)
            self._h[s] = hidden2[i]
        hub = getattr(fe, "_hub", None)     # duck-typed frontends (tests)
        while len(self._h) > self._max:
            self._h.pop(next(iter(self._h)))
            # an eviction drops a LIVE conversation's carry (the victim
            # was touched more recently than never) — count it where
            # the operator can see it instead of silently degrading
            self.evicted += 1
            if hub is not None:
                hub.inc("serve_session_evicted")
        if hub is not None:
            # LRU fill fraction: 1.0 means evictions are live and
            # long-lived sessions silently restart from zero hiddens —
            # the signal to widen max_sessions before quality decays
            hub.set("serve_sessions", len(self._h))
            hub.set("serve_session_lru_fill",
                    len(self._h) / self._max if self._max else 1.0)
        return actions, fresh

    def end(self, session_id) -> None:
        self._h.pop(session_id, None)
