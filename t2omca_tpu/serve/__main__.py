"""``python -m t2omca_tpu.serve`` — the serving CLI.

Subcommands::

    # export a training checkpoint as a serving artifact
    python -m t2omca_tpu.serve export results/models/<token> \
        --config configs/serve_smoke.yaml --out /path/to/artifact \
        [--buckets 1,2,4,8] [--dtypes float32,bfloat16] [--load-step N] \
        [--no-blobs] [--no-compile-cache] [key=value overrides ...]

    # inspect an artifact
    python -m t2omca_tpu.serve info /path/to/artifact

    # hot-refresh dry run: would a live fleet accept this checkpoint?
    # (host-side re-fold + per-bucket program fingerprint check —
    # exactly what ServeFleet.refresh runs before any engine is
    # touched; a live fleet arms the real thing via its
    # <artifact>/FLEET_REFRESH trigger file)
    python -m t2omca_tpu.serve refresh /path/to/artifact <ckpt_dir> \
        [--dtype float32]

Exit codes: 0 ok (export written / refresh compatible), 2 usage error
(missing checkpoint / bad artifact / refresh REFUSED). The export
config must be the TRAINING run's config (the exporter rebuilds the
exact MAC from it and shape-validates the checkpoint against it; a
mismatch is a hard error, not a silent re-init).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _parse_int_list(s: str):
    try:
        return [int(x) for x in s.split(",") if x.strip()]
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected a comma-separated int list, got {s!r}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m t2omca_tpu.serve",
        description="AOT policy-serving artifacts (docs/SERVING.md)")
    sub = parser.add_subparsers(dest="command", required=True)

    exp = sub.add_parser("export",
                         help="export a checkpoint as a serving artifact")
    exp.add_argument("ckpt_dir",
                     help="checkpoint directory (the training run's "
                          "results/models/<token>)")
    exp.add_argument("--config", default=None,
                     help="the TRAINING config (YAML/JSON)")
    exp.add_argument("--out", default=None,
                     help="artifact output dir (default: <ckpt_dir>/serve)")
    exp.add_argument("--buckets", type=_parse_int_list, default=None,
                     metavar="1,2,4,...",
                     help="batch buckets (default: powers of 2 up to 64)")
    exp.add_argument("--dtypes", default="float32,bfloat16",
                     help="param variants to write (comma-separated)")
    exp.add_argument("--load-step", type=int, default=0,
                     help="checkpoint step to export (0 = newest)")
    exp.add_argument("--no-blobs", action="store_true",
                     help="skip the per-bucket jax.export program blobs "
                          "(the front-end then rebuilds from the config)")
    exp.add_argument("--no-compile-cache", action="store_true",
                     help="skip the persistent compile cache warm-up")

    info = sub.add_parser("info", help="print an artifact's meta summary")
    info.add_argument("artifact_dir")

    ref = sub.add_parser("refresh",
                         help="hot-refresh dry run: fold a checkpoint "
                              "and fingerprint-check it against an "
                              "artifact's programs")
    ref.add_argument("artifact_dir")
    ref.add_argument("ckpt_dir",
                     help="checkpoint directory holding the NEW params")
    ref.add_argument("--dtype", choices=("float32", "bfloat16"),
                     default="float32",
                     help="the serving param variant to check")

    # key=value overrides ride as unrecognized trailing args (argparse
    # cannot mix a trailing nargs="*" positional with the option flags
    # above) — validate them here instead
    args, extra = parser.parse_known_args(argv)
    overrides = [a for a in extra if "=" in a and not a.startswith("-")]
    bad = [a for a in extra if a not in overrides]
    if bad:
        parser.error(f"unrecognized arguments: {' '.join(bad)}")
    if args.command != "export" and overrides:
        parser.error("key=value overrides only apply to `export`")
    args.overrides = overrides

    if args.command == "info":
        meta_path = os.path.join(args.artifact_dir, "meta.json")
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            print(f"serve: error: unreadable artifact meta {meta_path}: "
                  f"{e}", file=sys.stderr)
            return 2
        ck = meta.get("checkpoint", {})
        print(f"format v{meta.get('format')} — checkpoint "
              f"{ck.get('dir')} @ t_env={ck.get('t_env')}")
        print(f"model: {meta.get('n_agents')} agents x "
              f"{meta.get('n_actions')} actions, obs {meta.get('obs_dim')}"
              f", emb {meta.get('emb')}, "
              f"folded={meta.get('folded')}")
        print(f"buckets: {meta.get('buckets')}")
        for dt, p in sorted(meta.get("params", {}).items()):
            n_prog = len(meta.get("programs", {}).get(dt, {}))
            print(f"params[{dt}]: {p.get('bytes')} bytes "
                  f"sha256={str(p.get('sha256'))[:12]}… "
                  f"({n_prog} exported programs)")
        prov = meta.get("provenance", {})
        print(f"provenance: git={str(prov.get('git_commit'))[:12]} "
              f"jax={prov.get('jax')} backend={prov.get('backend')}")
        return 0

    if args.command == "refresh":
        if not os.path.isfile(os.path.join(args.artifact_dir,
                                           "meta.json")):
            print(f"serve: error: {args.artifact_dir} is not a serve "
                  f"artifact (no meta.json)", file=sys.stderr)
            return 2
        from .fleet import check_refresh
        out = check_refresh(args.artifact_dir, args.ckpt_dir,
                            dtype=args.dtype)
        if out["status"] != "compatible":
            print(f"serve: refresh REFUSED: {out.get('reason')}",
                  file=sys.stderr)
            return 2
        print(f"serve: refresh compatible (checkpoint "
              f"t_env={out.get('t_env')}, {out.get('buckets_checked')} "
              f"bucket programs fingerprint-checked)")
        return 0

    # ---- export ----
    from ..config import load_config
    try:
        cfg = load_config(args.config, tuple(args.overrides))
    except (OSError, KeyError, ValueError) as e:
        print(f"serve: error: bad config: {e}", file=sys.stderr)
        return 2
    from .export import DEFAULT_BUCKETS, PARAM_DTYPES, export_artifact
    out = args.out or os.path.join(args.ckpt_dir, "serve")
    try:
        meta = export_artifact(
            cfg, args.ckpt_dir, out,
            buckets=args.buckets or DEFAULT_BUCKETS,
            dtypes=tuple(d for d in args.dtypes.split(",") if d)
            or PARAM_DTYPES,
            load_step=args.load_step,
            compile_cache=not args.no_compile_cache,
            export_blobs=not args.no_blobs)
    except (FileNotFoundError, ValueError) as e:
        print(f"serve: error: {e}", file=sys.stderr)
        return 2
    ck = meta["checkpoint"]
    print(f"serve: artifact written to {out} (checkpoint "
          f"t_env={ck['t_env']}, buckets {meta['buckets']}, "
          f"variants {sorted(meta['params'])})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
