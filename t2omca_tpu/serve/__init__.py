"""graftserve — AOT-compiled policy serving (ROADMAP open item 5).

The first user-facing subsystem: a frozen-params greedy
``select_actions`` step exported ahead of traffic and fed by a
host-side batcher.

* ``serve/program.py`` — the ONE serving program definition (greedy
  step + request-surface avals + the graftprog registry hook).
* ``serve/export.py`` — ``python -m t2omca_tpu.serve export``: turn a
  training checkpoint into a self-contained artifact (stripped +
  pre-folded params in f32/bf16, per-bucket ``jax.export`` programs, a
  warm persistent compile cache, provenance meta).
* ``serve/frontend.py`` — the batched front-end: ragged request
  batches pad/bucket into the compiled shapes, with per-request hidden
  carry and full span telemetry.
* ``serve/fleet.py`` — graftfleet: N share-nothing frontends behind a
  bounded admission queue with per-engine supervision (watchdog +
  quarantine + backoff restart), hedged retries, explicit load
  shedding, a pressure-degradation ladder and rolling hot param
  refresh with fingerprint gate and auto-rollback (ROADMAP item 4).

Gated by the same static machinery as training: the serve step is
ratcheted in ``analysis/programs.json`` (FLOPs/bytes/fingerprint), the
span phases are pinned by GL110, and ``bench.py --serve`` measures
p50/p99 decision latency + decisions/s/chip. docs/SERVING.md is the
contract.
"""

from .export import (ARTIFACT_FORMAT, DEFAULT_BUCKETS, export_artifact,
                     load_acting_params)
from .fleet import (FleetConfig, FleetResult, RefreshRefused, ServeFleet,
                    check_refresh)
from .frontend import ServeFrontend, SessionStore, pad_request, pick_bucket
from .program import build_serve_step, serve_avals

__all__ = [
    "ARTIFACT_FORMAT", "DEFAULT_BUCKETS", "FleetConfig", "FleetResult",
    "RefreshRefused", "ServeFleet", "ServeFrontend", "SessionStore",
    "build_serve_step", "check_refresh", "export_artifact",
    "load_acting_params", "pad_request", "pick_bucket", "serve_avals",
]
