"""Export a verified checkpoint as a serving artifact.

``python -m t2omca_tpu.serve export <ckpt_dir>`` turns a training
checkpoint into the self-contained directory the inference front-end
(``serve/frontend.py``) and the serving bench (``bench.py --serve``)
load:

* ``params_float32.msgpack`` / ``params_bfloat16.msgpack`` — the agent
  parameters ONLY (optimizer, target net, mixer and replay state are
  stripped: acting needs none of them), with
  ``BasicMAC.prepare_acting_params`` applied — the qslice projection
  pre-fold, done once at export instead of once per dispatch. The bf16
  variant halves the artifact and the per-load host→device bytes; f32
  is the bit-parity variant (tests/test_serve.py).
* ``programs/serve_step_<dtype>_b<bucket>.jaxexport`` — the greedy
  ``serve_step`` AOT-lowered per batch bucket and serialized with
  ``jax.export`` (StableHLO): a portable, version-checked program the
  front-end deserializes instead of re-tracing Python. Each bucket is
  also compiled at export time — both a validation pass and the write
  that warms the artifact's persistent compile cache.
* ``compile_cache/`` — a ``jax_compilation_cache_dir`` populated by the
  export-time compiles, so a fresh serving process warm-starts instead
  of paying cold XLA compiles in front of traffic.
* ``meta.json`` — format version, bucket list, param digests, the full
  train config (the front-end rebuilds the exact MAC from it), and
  provenance: source checkpoint + its state SHA-256, git commit, jax
  version, and the per-bucket stable-HLO fingerprints/costs in the
  graftprog style (``analysis/graftprog.fingerprint_text``).

The checkpoint is read through ``utils.checkpoint.restore_host_state``
— the same host-side leaf loader the DP sharded resume uses — so the
export never allocates the replay ring on a device; it does pay one
host-RAM decode of the checkpoint blob (documented in
docs/SERVING.md).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from ..analysis.graftprog import fingerprint_text
from ..config import TrainConfig, sanity_check
from ..controllers.basic_mac import MAC_REGISTRY
from ..envs.registry import make_env
from ..obs.spans import NULL_RECORDER
from ..utils.checkpoint import find_checkpoint, restore_host_state
from ..utils.ioutil import write_bytes_atomic, write_json_atomic
from .program import build_serve_step, serve_avals

logger = logging.getLogger(__name__)

#: bump when the artifact layout changes incompatibly
ARTIFACT_FORMAT = 1

#: power-of-2 batch buckets (docs/SERVING.md bucket policy): every
#: request batch pads up to the smallest bucket ≥ its size, so at most
#: len(buckets) compiled programs serve any traffic mix and padding
#: waste is < 2x worst-case
DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)

#: the serialized-param variants an artifact ships
PARAM_DTYPES: Tuple[str, ...] = ("float32", "bfloat16")


def enable_compile_cache(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir`` (with
    the size/time floors dropped so the small serve programs qualify).
    Process-global jax config — callers opt in (``compile_cache=True``
    on export/load). Best-effort: an older jaxlib without the knobs
    just skips the warm-start."""
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # the cache singleton latches its directory at the process's
        # FIRST compile (proven on jax 0.4.37): a process that already
        # compiled anything would silently ignore the new dir — reset
        # so the next compile re-reads the config
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
        return True
    except Exception as e:  # noqa: BLE001 — cache is an optimization
        logger.warning("persistent compile cache unavailable: %r", e)
        return False


def _git_commit() -> Optional[str]:
    import subprocess
    try:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        out = subprocess.run(["git", "rev-parse", "HEAD"], cwd=root,
                             capture_output=True, text=True, timeout=10)
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:  # noqa: BLE001 — provenance is best-effort
        return None


def _sha256_bytes(blob: bytes) -> str:
    import hashlib
    return hashlib.sha256(blob).hexdigest()


def load_acting_params(cfg: TrainConfig, ckpt_dir: str, load_step: int = 0):
    """→ ``(acting_params, mac, env_info, ckpt_info)``: the checkpoint's
    agent parameters restored host-side (``restore_host_state`` — no
    device-resident replay ring), shape-validated against the config's
    own init, and pre-folded for acting."""
    found = find_checkpoint(ckpt_dir, load_step)
    if found is None:
        raise FileNotFoundError(
            f"no valid checkpoint under {ckpt_dir!r} (export needs a "
            f"published training checkpoint; run with save_model=true)")
    dirname, step = found
    env = make_env(cfg.env_args)
    env_info = env.get_env_info()
    mac = MAC_REGISTRY[cfg.mac].build(cfg, env_info)
    ckpt_meta, raw = restore_host_state(dirname, verify=False)
    try:
        agent_raw = raw["learner"]["params"]["agent"]
    except (KeyError, TypeError) as e:
        raise ValueError(
            f"checkpoint {dirname} has no learner/params/agent subtree "
            f"({e!r}) — not a t2omca_tpu training checkpoint?") from e
    del raw                         # drop the ring/optimizer host copy now
    template = mac.init_params(jax.random.PRNGKey(0),
                               env_info["obs_shape"])
    params = serialization.from_state_dict(template, agent_raw)
    t_leaves = jax.tree_util.tree_leaves_with_path(template)
    r_leaves = jax.tree_util.tree_leaves_with_path(params)
    bad = [jax.tree_util.keystr(kp)
           for (kp, lt), (_, lr) in zip(t_leaves, r_leaves)
           if getattr(lt, "shape", None) != getattr(lr, "shape", None)]
    if bad:
        raise ValueError(
            f"checkpoint {dirname} holds a different MODEL than the "
            f"export config: {len(bad)} agent leaves mismatch (first: "
            f"{bad[0]}) — pass the training run's config")
    # fold at the TRAIN dtype explicitly: model.act_dtype is a
    # training-run rollout knob, and letting it leak into the fold would
    # ship bf16 leaves inside the artifact's canonical "float32" variant
    # (voiding the f32 bit-parity serving contract above)
    acting = mac.prepare_acting_params(params, dtype=mac.agent.dtype)
    ckpt_info = {"dir": dirname, "t_env": int(step),
                 "state_sha256": (ckpt_meta or {}).get("sha256")}
    return acting, mac, env_info, ckpt_info


def _cast_variant(tree, dtype_name: str):
    """Param variant: floating leaves cast to the variant dtype
    (``float32`` keeps the canonical leaves untouched — including the
    pre-fold products, whose dtype is the model's compute dtype)."""
    if dtype_name == "float32":
        return tree
    dt = jnp.dtype(dtype_name)

    def cast(x):
        a = np.asarray(x) if not hasattr(x, "dtype") else x
        if jnp.issubdtype(getattr(a, "dtype", np.int32), jnp.floating):
            return jnp.asarray(a, dt)
        return x
    return jax.tree.map(cast, tree)


def export_artifact(cfg: TrainConfig, ckpt_dir: str, out_dir: str,
                    buckets: Sequence[int] = DEFAULT_BUCKETS,
                    dtypes: Sequence[str] = PARAM_DTYPES,
                    load_step: int = 0, compile_cache: bool = True,
                    export_blobs: bool = True, rec=NULL_RECORDER) -> dict:
    """Write the serving artifact for ``cfg``'s newest (or
    ``load_step``-nearest) checkpoint under ``ckpt_dir`` into
    ``out_dir``; → the ``meta.json`` dict. See the module docstring for
    the layout."""
    cfg = sanity_check(cfg)
    buckets = sorted({int(b) for b in buckets})
    if not buckets or buckets[0] < 1:
        raise ValueError(f"buckets must be positive ints, got {buckets}")
    for d in dtypes:
        jnp.dtype(d)                 # fail fast on a typo'd dtype
    # resolve + restore the checkpoint BEFORE any filesystem or
    # process-global (compile cache) side effect: a missing/mismatched
    # checkpoint must be a clean error, not a half-written artifact
    with rec.span("serve.export", phase_detail="load"):
        acting, mac, env_info, ckpt_info = load_acting_params(
            cfg, ckpt_dir, load_step)
    os.makedirs(out_dir, exist_ok=True)
    if compile_cache:
        enable_compile_cache(os.path.join(out_dir, "compile_cache"))
    step = build_serve_step(mac)
    obs_dim, n_actions = env_info["obs_shape"], env_info["n_actions"]

    params_meta: Dict[str, dict] = {}
    programs_meta: Dict[str, dict] = {}
    prog_dir = os.path.join(out_dir, "programs")
    if export_blobs:
        os.makedirs(prog_dir, exist_ok=True)
    for dtype_name in dtypes:
        variant = jax.device_put(_cast_variant(acting, dtype_name))
        blob = serialization.msgpack_serialize(
            jax.tree.map(lambda x: np.asarray(jax.device_get(x)), variant))
        fname = f"params_{dtype_name}.msgpack"
        # atomic (tmp+fsync+rename, like meta.json): a crash mid-export
        # must never publish a truncated blob at the final path — the
        # front-end's sha256 check would reject it, but only AFTER a
        # serving process trusted the artifact enough to load it
        write_bytes_atomic(os.path.join(out_dir, fname), blob)
        params_meta[dtype_name] = {"file": fname,
                                   "sha256": _sha256_bytes(blob),
                                   "bytes": len(blob)}
        del blob

        per_bucket: Dict[str, dict] = {}
        for b in buckets:
            obs, avail, hidden = serve_avals(mac, obs_dim, n_actions, b)
            with rec.span("serve.export", phase_detail="lower",
                          dtype=dtype_name, bucket=b):
                lowered = step.trace(variant, obs, avail, hidden).lower()
                fp = fingerprint_text(lowered.as_text())
                try:
                    cost = lowered.cost_analysis()
                    if isinstance(cost, (list, tuple)):
                        cost = cost[0] if cost else {}
                except Exception:  # noqa: BLE001 — backend-dependent
                    cost = {}
            entry = {"fingerprint": fp,
                     "flops": cost.get("flops"),
                     "bytes_accessed": cost.get("bytes accessed")}
            if export_blobs:
                from jax import export as jax_export
                with rec.span("serve.export", phase_detail="export",
                              dtype=dtype_name, bucket=b):
                    exported = jax_export.export(step)(variant, obs,
                                                       avail, hidden)
                    eblob = exported.serialize()
                    bname = f"serve_step_{dtype_name}_b{b}.jaxexport"
                    write_bytes_atomic(os.path.join(prog_dir, bname),
                                       eblob)
                    # validate + warm-start with the program the
                    # FRONT-END actually dispatches — jit over the
                    # deserialized call has its own cache key, so
                    # compiling the raw step here would warm nothing
                    # the serving process looks up
                    jax.jit(jax_export.deserialize(eblob).call).lower(
                        variant, obs, avail, hidden).compile()
                entry["file"] = f"programs/{bname}"
            else:
                # no blobs: the front-end falls back to rebuilding the
                # raw step, whose HLO (hence cache key) this compile
                # warms — and it validates the program end-to-end
                lowered.compile()
            per_bucket[str(b)] = entry
        programs_meta[dtype_name] = per_bucket
        logger.info("exported %s variant: %d buckets %s",
                    dtype_name, len(buckets), buckets)

    meta = {
        "format": ARTIFACT_FORMAT,
        "created": time.time(),
        "checkpoint": ckpt_info,
        "provenance": {"git_commit": _git_commit(),
                       "jax": jax.__version__,
                       "backend": jax.default_backend()},
        "train_config": dataclasses.asdict(cfg),
        "env_info": {k: int(v) for k, v in env_info.items()
                     if isinstance(v, (int, np.integer))},
        "n_agents": int(mac.n_agents),
        "obs_dim": int(obs_dim),
        "n_actions": int(n_actions),
        "emb": int(mac.emb),
        "folded": bool(mac.use_qslice),
        "buckets": buckets,
        "params": params_meta,
        "programs": programs_meta,
        "compile_cache": bool(compile_cache),
    }
    write_json_atomic(os.path.join(out_dir, "meta.json"), meta)
    logger.info("serve artifact written to %s (checkpoint t_env=%d)",
                out_dir, ckpt_info["t_env"])
    return meta
