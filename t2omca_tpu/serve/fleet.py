"""graftfleet — fault-tolerant multi-engine serving (ROADMAP item 4).

One :class:`~t2omca_tpu.serve.frontend.ServeFrontend` is one process,
one chip, params frozen at export — and a single wedged dispatch stalls
every caller forever. This module is the fleet layer over it: N
share-nothing engines (EnvPool's executor model, PAPERS.md arXiv
2206.10558 — each engine owns its OWN frontend, params and compiled
programs; nothing is shared but the admission queue) behind a single
bounded admission queue, composed entirely from existing machinery:

* **supervision** — each engine thread owns its OWN
  ``utils/watchdog.Watchdog`` (the PR 10 one-armed-stamp rule: a shared
  instance would let two engines' stamps overwrite each other), and
  engine health is the same predicate the pulse ``/healthz`` endpoint
  serves (``MetricsHub.health``). A stalled or crashed engine is
  quarantined, its in-flight request hedged onto a healthy peer, and it
  is restarted from the artifact with exponential backoff
  (``backoff_delay``) up to a permanent-eject cap.
* **request-level resilience** — per-request deadlines enforced by the
  supervisor (a request NEVER hangs: it completes, sheds, or deadline-
  errors even with every engine wedged), bounded in-place retry for
  transient faults (``retry_call``/``is_transient``), and hedged
  dispatch after a p99-derived delay (tail-latency hedging: the slow
  engine's request is duplicated onto a peer; first writer wins).
* **graceful degradation** — admission past the queue-depth bound
  returns an explicit ``SHED`` result immediately, and before shedding
  a pressure ladder (:class:`FleetLadder`, the mirror of PR 4's
  ``DegradationLadder``: same rung discipline, pressure-driven instead
  of failure-driven) steps the dispatch bucket cap down and falls back
  f32→bf16 param variants.
* **hot param refresh** — :meth:`ServeFleet.refresh`: re-fold the new
  checkpoint host-side (Podracer's decoupled discipline, arXiv
  2104.06272 — the fold/trace runs OFF the request path), fingerprint-
  check the refolded params against the artifact's per-bucket program
  fingerprints (refuse and keep serving on any mismatch), then swap
  engines one at a time — rolling, never fewer than N-1 serving — with
  a post-swap health check that rolls the WHOLE refresh back if it
  trips. A ``FLEET_REFRESH`` trigger file next to the artifact (content:
  a checkpoint dir) arms the same path from outside the process, the
  ``PULSE_TRACE`` idiom.

Telemetry: every boundary is spanned (``fleet.load`` /
``fleet.dispatch`` / ``fleet.selfcheck`` / ``fleet.restart`` /
``fleet.refresh``; GL110 pins the names against
``obs/spans.KNOWN_PHASES``) and the pulse plane carries queue depth,
per-engine state, shed/hedge/stall/refresh counters. Chaos hooks
(``utils/resilience.register_fault``): ``fleet.dispatch``,
``fleet.selfcheck``, ``fleet.refresh``. ``bench.py --serve --chaos``
drives the whole layer under bursty heavy-tailed open-loop traffic
plus a fault schedule; docs/SERVING.md §fleet is the contract.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.spans import NULL_RECORDER
from ..utils import resilience
from ..utils.watchdog import (Watchdog, backoff_delay, is_transient,
                              retry_call)
from .frontend import ServeFrontend

logger = logging.getLogger(__name__)


def _watched(phase, rec, **meta):
    """One spanned fleet boundary. Module-level and named like the
    driver's wrapper so graftlint GL110 checks every literal phase here
    against ``obs/spans.KNOWN_PHASES``."""
    return rec.span(phase, **meta)


# ---------------------------------------------------------------- statuses

#: request outcomes — every admitted request resolves to exactly one
OK = "ok"
SHED = "shed"            # admission control: queue past its bound
DEADLINE = "deadline"    # per-request deadline expired (queued OR in-flight)
ERROR = "error"          # non-transient failure after bounded bouncing

#: engine lifecycle states (gauge codes: ``fleet_engine_state``)
ENGINE_STATES = ("starting", "serving", "refreshing", "quarantined",
                 "restarting", "ejected", "stopped")
_STATE_CODE = {s: i for i, s in enumerate(ENGINE_STATES)}

#: FLEET_REFRESH trigger file (PULSE_TRACE idiom): drop a checkpoint
#: path into ``<artifact>/FLEET_REFRESH`` and the supervisor arms one
#: rolling refresh from it
REFRESH_TRIGGER = "FLEET_REFRESH"


class RefreshRefused(RuntimeError):
    """A hot param refresh that must NOT be applied: missing/mismatched
    checkpoint, a param fold that lowers to a different program than the
    artifact's per-bucket fingerprints. The fleet keeps serving the old
    params — refusal is the safe outcome, not a failure."""


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet policy knobs (all host-side — nothing here touches the
    compiled programs)."""

    queue_depth: int = 64            # admission bound → SHED past it
    deadline_s: float = 10.0         # default per-request deadline
    dispatch_timeout_s: float = 10.0  # per-engine watchdog (warm phases)
    first_dispatch_timeout_s: float = 0.0  # 0 = compile-exempt (PR 4)
    request_retries: int = 1         # extra in-place tries on transient
    retry_backoff_s: float = 0.02
    max_bounces: int = 2             # cross-engine re-dispatches per request
    hedge_after_s: float = 0.0       # 0 = derive from the p99 window
    hedge_p99_mult: float = 4.0
    hedge_min_s: float = 0.05
    restart_backoff_s: float = 0.1   # engine restart: exponential backoff
    restart_backoff_max_s: float = 5.0
    max_restarts: int = 5            # permanent-eject cap per engine
    ladder_high: float = 0.75        # queue fill fraction → step down
    ladder_low: float = 0.25         # queue fill fraction → step back up
    ladder_cooldown_s: float = 0.5   # min dwell between ladder moves
    max_bucket_steps: int = 2        # bucket-cap rungs before dtype rung
    selfcheck_timeout_s: float = 0.0  # 0 = compile-exempt selfcheck
    poll_s: float = 0.02             # supervisor/worker poll cadence


@dataclasses.dataclass
class FleetResult:
    """One resolved request. ``status`` is always one of
    ``ok``/``shed``/``deadline``/``error`` — a fleet request has no
    silent-hang outcome by construction."""

    status: str
    actions: Optional[np.ndarray] = None
    hidden: Optional[np.ndarray] = None
    engine: Optional[int] = None
    error: Optional[str] = None
    hedged: bool = False
    latency_ms: float = 0.0

    @property
    def ok(self) -> bool:
        return self.status == OK


class FleetRequest:
    """One admitted request: first completion wins (hedged duplicates
    and late unwedged dispatches resolve against the same slot)."""

    __slots__ = ("rid", "obs", "avail", "hidden", "born", "deadline",
                 "hedges", "bounces", "_event", "_lock", "result")

    def __init__(self, rid: int, obs, avail, hidden,
                 deadline: float) -> None:
        self.rid = rid
        self.obs = obs
        self.avail = avail
        self.hidden = hidden
        self.born = time.monotonic()
        self.deadline = deadline        # absolute monotonic
        self.hedges = 0
        self.bounces = 0
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.result: Optional[FleetResult] = None

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def complete(self, result: FleetResult) -> bool:
        """First writer wins; → True iff THIS call resolved the
        request (losers' results are dropped — the hedging contract)."""
        with self._lock:
            if self.result is not None:
                return False
            result.latency_ms = round(
                (time.monotonic() - self.born) * 1000.0, 3)
            result.hedged = self.hedges > 0
            self.result = result
        self._event.set()
        return True

    def wait(self, timeout: Optional[float] = None) -> FleetResult:
        """Block until resolved. With ``timeout=None`` the supervisor's
        deadline sweep bounds the wait — callers cannot hang on a
        wedged fleet."""
        self._event.wait(timeout)
        with self._lock:
            if self.result is None:     # timeout raced resolution
                self.result = FleetResult(
                    ERROR, error="request unresolved at wait timeout")
                self._event.set()
            return self.result


class _AdmissionQueue:
    """Unbounded deque + condvar. The admission BOUND lives in
    :meth:`ServeFleet.submit` (shed decision) — hedges and bounced
    in-flight requests re-enter at the FRONT past the bound, because
    they were already admitted once."""

    def __init__(self) -> None:
        self._dq: collections.deque = collections.deque()
        self._cv = threading.Condition()

    def __len__(self) -> int:
        return len(self._dq)

    def put(self, req: FleetRequest, front: bool = False) -> None:
        with self._cv:
            if front:
                self._dq.appendleft(req)
            else:
                self._dq.append(req)
            self._cv.notify()

    def get(self, timeout: float) -> Optional[FleetRequest]:
        with self._cv:
            if not self._dq:
                self._cv.wait(timeout)
            return self._dq.popleft() if self._dq else None

    def drain(self) -> List[FleetRequest]:
        with self._cv:
            out = list(self._dq)
            self._dq.clear()
            return out


class FleetLadder:
    """Pressure ladder — the serving mirror of PR 4's
    ``DegradationLadder``. Rung order under sustained queue pressure:
    **cap buckets** (dispatch in smaller compiled buckets: each dispatch
    risks/occupies less, the queue drains in finer quanta — the serving
    analogue of superstep K→1) for up to ``max_bucket_steps`` rungs,
    then **dtype fallback** (f32→bf16 variant: half the bytes per
    dispatch) when the artifact ships one; past the last rung admission
    control sheds. Hysteresis (high/low watermark + dwell) keeps one
    burst from thrashing the rungs; counters are cumulative like the
    train ladder's."""

    def __init__(self, buckets: Sequence[int], primary_dtype: str,
                 alt_dtype: Optional[str], high: float, low: float,
                 cooldown_s: float, max_bucket_steps: int = 2) -> None:
        bs = sorted(int(b) for b in buckets)
        rungs: List[Tuple[Optional[int], str]] = [(None, primary_dtype)]
        for cap in list(reversed(bs[:-1]))[:max(int(max_bucket_steps), 0)]:
            rungs.append((cap, primary_dtype))
        if alt_dtype and alt_dtype != primary_dtype:
            rungs.append((rungs[-1][0], alt_dtype))
        self.rungs = rungs
        self.high, self.low = float(high), float(low)
        self.cooldown_s = float(cooldown_s)
        self.level = 0
        self.degrades = 0
        self.restores = 0
        self._moved_at = -float("inf")

    def current(self) -> Tuple[Optional[int], str]:
        """→ ``(bucket_cap | None, dtype)`` for the active rung."""
        return self.rungs[self.level]

    def update(self, fill: float, now: float) -> Optional[str]:
        """Feed one queue-fill observation; → ``'degrade'``/``'restore'``
        when the level moved, else None."""
        if now - self._moved_at < self.cooldown_s:
            return None
        if fill >= self.high and self.level < len(self.rungs) - 1:
            self.level += 1
            self.degrades += 1
            self._moved_at = now
            return "degrade"
        if fill <= self.low and self.level > 0:
            self.level -= 1
            self.restores += 1
            self._moved_at = now
            return "restore"
        return None

    def describe(self) -> str:
        cap, dt = self.current()
        return (f"level={self.level}/{len(self.rungs) - 1} "
                f"cap={cap} dtype={dt} degrades={self.degrades} "
                f"restores={self.restores}")


class _Engine:
    """One supervised engine slot: its own frontend(s), its own
    watchdog, a generation counter that supersedes wedged workers."""

    def __init__(self, idx: int) -> None:
        self.idx = idx
        self.state = "starting"
        self.gen = 0                    # bumped on every (re)start/stall
        self.restarts = 0
        self.thread: Optional[threading.Thread] = None
        self.fe: Optional[ServeFrontend] = None
        self.fe_alt: Dict[str, object] = {}   # dtype -> lazy alt frontend
        self.wd: Optional[Watchdog] = None
        self.lock = threading.Lock()
        self.current: Optional[Tuple[FleetRequest, float]] = None
        # Event, not a bare bool: set/cleared by the refresh thread,
        # polled by the worker — Event carries the memory barrier
        self.pause_ev = threading.Event()
        self.idle = threading.Event()
        self.idle.set()
        self.restart_at = 0.0
        self.quarantined_at: Optional[float] = None
        self.last_error: Optional[str] = None

    def healthy(self) -> Tuple[bool, str]:
        """THE health predicate — served verbatim on ``/healthz``
        (``MetricsHub.health``) and consulted by the supervisor: one
        definition, two readers."""
        t = self.thread
        if self.state == "serving" and (t is None or not t.is_alive()):
            return False, "worker thread died"
        if self.state in ("serving", "refreshing"):
            return True, self.state
        return False, f"{self.state} ({self.last_error or 'no error'})"


class ServeFleet:
    """N share-nothing engines + supervisor behind one bounded
    admission queue. Construct, :meth:`start`, then :meth:`submit` /
    :meth:`select`; always :meth:`stop` (or use as a context manager).

    ``frontend_factory(dtype) -> frontend`` overrides artifact loading
    (tests inject stub engines); the default loads
    ``ServeFrontend.load(artifact_dir, dtype=...)`` per engine — each
    engine owns its params and program cache, nothing shared."""

    def __init__(self, artifact_dir: Optional[str], n_engines: int = 2,
                 dtype: str = "float32",
                 cfg: Optional[FleetConfig] = None,
                 rec=NULL_RECORDER, hub=None,
                 frontend_factory: Optional[Callable] = None,
                 use_exported: bool = True,
                 compile_cache: bool = True) -> None:
        if n_engines < 1:
            raise ValueError(f"n_engines must be >= 1, got {n_engines}")
        self.artifact_dir = artifact_dir
        self.n_engines = int(n_engines)
        self.dtype = dtype
        self.cfg = cfg or FleetConfig()
        self._rec = rec
        self._hub = hub
        self._use_exported = use_exported
        self._compile_cache = compile_cache
        self._factory = frontend_factory or self._load_frontend
        self.meta: Optional[dict] = None
        self.engines = [_Engine(i) for i in range(self.n_engines)]
        self._q = _AdmissionQueue()
        self._rid = itertools.count()
        self._inflight: Dict[int, FleetRequest] = {}
        self._inflight_lock = threading.Lock()
        self._lat = collections.deque(maxlen=512)   # ok latencies (s)
        self._stop_ev = threading.Event()
        self._sup: Optional[threading.Thread] = None
        self._refresh_lock = threading.Lock()
        self._live_params = None        # post-refresh params (per dtype)
        self._ladder: Optional[FleetLadder] = None
        self.recoveries: List[float] = []   # quarantine→rejoin seconds
        self.counters = collections.Counter()   # shed/hedge/stall/...
        self._counters_lock = threading.Lock()

    # ------------------------------------------------------------ lifecycle

    def start(self, wait_s: float = 120.0) -> "ServeFleet":
        """Spawn the engines + supervisor; block until every engine
        finished its startup attempt (serving or quarantined), at most
        ``wait_s``."""
        if self.artifact_dir is not None and self.meta is None:
            import json
            with open(os.path.join(self.artifact_dir, "meta.json")) as f:
                self.meta = json.load(f)
        alt = None
        if self.meta is not None and self.dtype == "float32" \
                and "bfloat16" in self.meta.get("params", {}):
            alt = "bfloat16"
        buckets = (sorted(int(b) for b in self.meta["buckets"])
                   if self.meta is not None else [1])
        self._ladder = FleetLadder(
            buckets, self.dtype, alt, self.cfg.ladder_high,
            self.cfg.ladder_low, self.cfg.ladder_cooldown_s,
            self.cfg.max_bucket_steps)
        if self._hub is not None:
            for eng in self.engines:
                self._hub.health(f"fleet_engine{eng.idx}", eng.healthy)
            self._hub.health("fleet", self._fleet_health)
        for eng in self.engines:
            with eng.lock:              # _spawn_worker's contract
                self._spawn_worker(eng)
        self._sup = threading.Thread(target=self._supervise, daemon=True,
                                     name="t2omca-fleet-supervisor")
        self._sup.start()
        deadline = time.monotonic() + wait_s
        for eng in self.engines:
            while eng.state == "starting" and time.monotonic() < deadline:
                time.sleep(self.cfg.poll_s)
        return self

    def stop(self) -> None:
        """Resolve everything outstanding (status ``error``,
        ``shutdown``), stop the supervisor, workers and watchdogs.
        Wedged workers are daemon threads — they cannot block exit."""
        if self._stop_ev.is_set():
            return
        self._stop_ev.set()
        for req in self._q.drain():
            req.complete(FleetResult(ERROR, error="fleet shutdown"))
        with self._inflight_lock:
            pending = list(self._inflight.values())
            self._inflight.clear()
        for req in pending:
            req.complete(FleetResult(ERROR, error="fleet shutdown"))
        for eng in self.engines:
            eng.gen += 1                # supersede every worker
            self._set_state(eng, "stopped")
            wd = eng.wd
            if wd is not None:
                wd.stop()
        if self._sup is not None:
            self._sup.join(timeout=2.0)
        for eng in self.engines:
            t = eng.thread
            if t is not None:
                t.join(timeout=0.5)

    def __enter__(self) -> "ServeFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ admission

    def submit(self, obs, avail, hidden=None,
               deadline_s: Optional[float] = None) -> FleetRequest:
        """Admit one request (non-blocking). Past the queue bound the
        request resolves ``SHED`` immediately — admission control never
        blocks and never hangs the caller."""
        obs = np.asarray(obs, np.float32)
        avail = np.asarray(avail)
        if hidden is not None:
            hidden = np.asarray(hidden, np.float32)
        ddl = time.monotonic() + float(deadline_s if deadline_s is not None
                                       else self.cfg.deadline_s)
        req = FleetRequest(next(self._rid), obs, avail, hidden, ddl)
        self._count("fleet_requests_total")
        if self._stop_ev.is_set():
            req.complete(FleetResult(ERROR, error="fleet stopped"))
            return req
        if all(e.state in ("ejected", "stopped") for e in self.engines):
            req.complete(FleetResult(
                ERROR, error="no engine can serve (all ejected)"))
            return req
        if len(self._q) >= self.cfg.queue_depth:
            self._count("fleet_shed_total")
            req.complete(FleetResult(SHED, error="admission queue full"))
            return req
        with self._inflight_lock:
            self._inflight[req.rid] = req
        self._q.put(req)
        return req

    def select(self, obs, avail, hidden=None,
               deadline_s: Optional[float] = None) -> FleetResult:
        """Synchronous request: submit + wait. Bounded by the request
        deadline plus supervisor slack — never an unbounded block."""
        req = self.submit(obs, avail, hidden, deadline_s)
        slack = max(req.deadline - time.monotonic(), 0.0) \
            + 10.0 * self.cfg.poll_s + 1.0
        return req.wait(timeout=slack)

    # ------------------------------------------------------------- engines

    def _load_frontend(self, dtype: str):
        fe = ServeFrontend.load(
            self.artifact_dir, dtype=dtype,
            use_exported=self._use_exported,
            compile_cache=self._compile_cache, rec=self._rec,
            hub=self._hub)
        live = self._live_params
        if live is not None and dtype == self.dtype:
            # a restart after a hot refresh must come back with the
            # REFRESHED params, not the artifact's — engines must agree
            fe._params = live
        return fe

    def _spawn_worker(self, eng: _Engine) -> None:
        """Caller holds ``eng.lock`` (start() and the supervisor both
        do): the gen bump is a read-modify-write racing the supervisor's
        stall path, and must not take the plain Lock itself."""
        eng.gen += 1
        gen = eng.gen
        self._set_state(eng, "starting" if eng.restarts == 0
                        else "restarting")
        t = threading.Thread(target=self._worker, args=(eng, gen),
                             daemon=True,
                             name=f"t2omca-fleet-engine{eng.idx}")
        eng.thread = t
        t.start()

    def _worker(self, eng: _Engine, gen: int) -> None:
        cfg = self.cfg
        wd = None
        try:
            # two literal call sites, not one computed phase: GL110's
            # AST scan must see both names
            if eng.restarts == 0:
                with _watched("fleet.load", self._rec, engine=eng.idx,
                              gen=gen):
                    fe = self._factory(self.dtype)
            else:
                with _watched("fleet.restart", self._rec, engine=eng.idx,
                              gen=gen):
                    fe = self._factory(self.dtype)
            wd = Watchdog(
                timeout_s=cfg.dispatch_timeout_s,
                first_timeout_s=cfg.first_dispatch_timeout_s,
                grace_s=0.0,            # NEVER hard-exit: quarantine+restart
                on_stall=lambda d, e=eng, g=gen: self._on_stall(e, g, d),
            ).start()
            with eng.lock:
                if eng.gen != gen:      # superseded during load
                    wd.stop()
                    return
                eng.fe = fe
                eng.fe_alt = {}
                eng.wd = wd
            self._selfcheck(eng, wd, fe, stage="start")
        except Exception as e:  # noqa: BLE001 — supervisor handles it
            eng.last_error = f"{type(e).__name__}: {e}"
            logger.warning("fleet engine %d startup failed: %s",
                           eng.idx, eng.last_error)
            if wd is not None:
                wd.stop()
            with eng.lock:
                if eng.gen == gen:
                    self._quarantine(eng, reason="startup")
            return
        with eng.lock:
            if eng.gen != gen:
                wd.stop()
                return
            self._set_state(eng, "serving")
            if eng.quarantined_at is not None:
                rec_s = time.monotonic() - eng.quarantined_at
                self.recoveries.append(rec_s)
                eng.quarantined_at = None
                logger.info("fleet engine %d rejoined after %.3fs",
                            eng.idx, rec_s)

        try:
            while not self._stop_ev.is_set() and eng.gen == gen:
                if eng.pause_ev.is_set():
                    eng.idle.set()
                    time.sleep(cfg.poll_s)
                    continue
                req = self._q.get(timeout=cfg.poll_s)
                if req is None:
                    eng.idle.set()
                    continue
                eng.idle.clear()
                if eng.pause_ev.is_set():  # pause landed mid-dequeue:
                    self._q.put(req, front=True)   # drain must not race
                    eng.idle.set()
                    continue
                if req.done:
                    continue            # hedge winner elsewhere
                now = time.monotonic()
                if now >= req.deadline:
                    req.complete(FleetResult(
                        DEADLINE, error="deadline before dispatch"))
                    self._count("fleet_deadline_total")
                    continue
                with eng.lock:
                    eng.current = (req, now)
                try:
                    actions, hidden2 = self._dispatch(eng, wd, fe, req)
                except Exception as e:  # noqa: BLE001 — engine failure
                    with eng.lock:
                        eng.current = None
                    eng.idle.set()
                    if eng.gen == gen:  # not superseded by a stall
                        self._engine_failed(eng, e, req)
                    return              # this worker generation is done
                with eng.lock:
                    eng.current = None
                eng.idle.set()
                # complete even when superseded mid-dispatch (a late
                # unwedge): the result is valid and first-writer-wins
                # dedupes against the hedge
                if req.complete(FleetResult(OK, actions, hidden2,
                                            engine=eng.idx)) \
                        and eng.gen == gen:
                    self._lat.append(time.monotonic() - now)
                if eng.gen != gen:
                    break
        finally:
            wd.stop()

    def _dispatch(self, eng: _Engine, wd: Watchdog, fe,
                  req: FleetRequest):
        """One request on one engine: chaos hook + watchdog stamp +
        span around the frontend select, with bounded in-place retries
        for transient faults. The ladder's rung picks the bucket cap
        and the param-dtype variant."""
        cap, dtype = self._ladder.current() if self._ladder is not None \
            else (None, self.dtype)
        fe_use = fe if dtype == self.dtype else self._alt(eng, wd, dtype)
        attempt = itertools.count(1)

        def once():
            a = next(attempt)
            with wd.watch("fleet.dispatch"):
                with _watched("fleet.dispatch", self._rec,
                              engine=eng.idx, attempt=a,
                              bucket_cap=cap or 0, dtype=dtype):
                    resilience.fire("fleet.dispatch", engine=eng.idx,
                                    attempt=a, rid=req.rid)
                    return self._select_capped(fe_use, req, cap)

        return retry_call(once, attempts=self.cfg.request_retries + 1,
                          backoff_s=self.cfg.retry_backoff_s,
                          retriable=is_transient,
                          label=f"fleet.engine{eng.idx}")

    def _select_capped(self, fe, req: FleetRequest, cap: Optional[int]):
        """Frontend select under the ladder's bucket cap: chunks of
        ``<= cap`` rows make every ``pick_bucket`` land at or below the
        cap (the cap IS a bucket), so no compiled program above it is
        dispatched while degraded."""
        if cap is None or cap >= fe.buckets[-1]:
            return fe.select(req.obs, req.avail, req.hidden)
        n = req.obs.shape[0]
        actions = np.empty((n, fe.n_agents), np.int32)
        hidden = np.empty((n, fe.n_agents, fe.emb), np.float32)
        for lo in range(0, n, cap):
            hi = min(lo + cap, n)
            h = req.hidden[lo:hi] if req.hidden is not None else None
            a, h2 = fe.select(req.obs[lo:hi], req.avail[lo:hi], h)
            actions[lo:hi] = a
            hidden[lo:hi] = h2
        return actions, hidden

    def _alt(self, eng: _Engine, wd: Watchdog, dtype: str):
        """Lazy degraded-dtype frontend for one engine (loaded + warmed
        off the watchdog clock: its first dispatch compiles)."""
        fe2 = eng.fe_alt.get(dtype)
        if fe2 is None:
            with _watched("fleet.load", self._rec, engine=eng.idx,
                          dtype=dtype):
                fe2 = self._factory(dtype)
            self._selfcheck(eng, None, fe2, stage="degrade")
            eng.fe_alt[dtype] = fe2
        return fe2

    def _selfcheck(self, eng: _Engine, wd: Optional[Watchdog], fe,
                   stage: str) -> None:
        """One smallest-bucket dispatch on zero obs: the health check
        run at engine start, after a restart, on the degraded variant's
        first use and after a refresh swap. Raises on anything
        non-finite or mis-shaped — the caller maps that to quarantine
        or refresh rollback."""
        with _watched("fleet.selfcheck", self._rec, engine=eng.idx,
                      stage=stage):
            resilience.fire("fleet.selfcheck", engine=eng.idx, stage=stage)
            b = fe.buckets[0]
            obs = np.zeros((b, fe.n_agents, fe.obs_dim), np.float32)
            avail = np.ones((b, fe.n_agents, fe.n_actions), np.bool_)
            if wd is not None:
                # stamped under the DISPATCH phase: its clean completion
                # marks fleet.dispatch warm, so the compile exemption
                # ends here and traffic stalls are bounded from the
                # first real request
                with wd.watch("fleet.dispatch"):
                    actions, hidden = fe.select(obs, avail)
            else:
                actions, hidden = fe.select(obs, avail)
            if actions.shape != (b, fe.n_agents) \
                    or not np.all((actions >= 0)
                                  & (actions < fe.n_actions)):
                raise RuntimeError(
                    f"selfcheck: actions out of range/shape "
                    f"{actions.shape}")
            if not np.all(np.isfinite(np.asarray(hidden, np.float32))):
                raise RuntimeError("selfcheck: non-finite hidden state")

    # ------------------------------------------------------- failure paths

    def _engine_failed(self, eng: _Engine, exc: BaseException,
                       req: FleetRequest) -> None:
        """Non-transient (or retry-exhausted) dispatch failure: the
        engine is quarantined and the request bounces to a peer —
        bounded by ``max_bounces`` so a poison request cannot cycle the
        whole fleet."""
        eng.last_error = f"{type(exc).__name__}: {exc}"
        logger.warning("fleet engine %d failed dispatching request %d: %s",
                       eng.idx, req.rid, eng.last_error)
        self._count("fleet_engine_failures_total")
        with eng.lock:
            self._quarantine(eng, reason="crash")
        self._bounce(req, eng.last_error)

    def _on_stall(self, eng: _Engine, gen: int, diag) -> None:
        """Watchdog callback (its own thread): the engine's dispatch
        exceeded its warm deadline. Supersede the wedged worker, hedge
        its in-flight request onto a peer, quarantine + schedule a
        restart. The stuck thread keeps its (now stale) generation: if
        it ever unwedges it observes the bump and exits."""
        with eng.lock:
            if eng.gen != gen or self._stop_ev.is_set():
                return
            eng.last_error = (f"stalled in {diag.phase} after "
                              f"{diag.elapsed_s:.3f}s")
            self._count("fleet_stalls_total")
            cur, eng.current = eng.current, None
            self._quarantine(eng, reason="stall")
        if cur is not None:
            req, _ = cur
            if not req.done:
                self._bounce(req, eng.last_error, front=True)

    def _bounce(self, req: FleetRequest, why: str,
                front: bool = False) -> None:
        req.bounces += 1
        if req.done:
            return
        if req.bounces > self.cfg.max_bounces:
            req.complete(FleetResult(
                ERROR, error=f"failed on {req.bounces} engines; "
                             f"last: {why}"))
            return
        if time.monotonic() >= req.deadline:
            req.complete(FleetResult(DEADLINE, error=why))
            self._count("fleet_deadline_total")
            return
        self._q.put(req, front=front)

    def _quarantine(self, eng: _Engine, reason: str) -> None:
        """Caller holds ``eng.lock``. Supersedes the current worker and
        schedules the restart (or ejects past the cap)."""
        eng.gen += 1
        if eng.quarantined_at is None:
            eng.quarantined_at = time.monotonic()
        if eng.restarts >= self.cfg.max_restarts:
            self._set_state(eng, "ejected")
            self._count("fleet_ejected_total")
            logger.error("fleet engine %d permanently ejected after %d "
                         "restarts (%s)", eng.idx, eng.restarts, reason)
            return
        eng.restarts += 1
        delay = backoff_delay(eng.restarts, self.cfg.restart_backoff_s,
                              max_s=self.cfg.restart_backoff_max_s)
        eng.restart_at = time.monotonic() + delay
        self._set_state(eng, "quarantined")
        self._count("fleet_restarts_total")
        self._rec.mark("fleet.quarantine", engine=eng.idx, reason=reason,
                       restart=eng.restarts, delay_s=round(delay, 3))

    # ----------------------------------------------------------- supervisor

    def _supervise(self) -> None:
        cfg = self.cfg
        while not self._stop_ev.wait(cfg.poll_s):
            now = time.monotonic()
            # 1) deadline sweep: NOTHING outstanding may outlive its
            # deadline, queued or wedged-in-flight alike
            with self._inflight_lock:
                reqs = list(self._inflight.items())
            for rid, req in reqs:
                if req.done:
                    with self._inflight_lock:
                        self._inflight.pop(rid, None)
                elif now >= req.deadline:
                    if req.complete(FleetResult(
                            DEADLINE, error="deadline exceeded")):
                        self._count("fleet_deadline_total")
            # 2) hedge sweep: duplicate the laggard's request onto a
            # peer after the p99-derived delay (once per request)
            hedge_after = self._hedge_delay()
            healthy = sum(e.state == "serving" for e in self.engines)
            if healthy >= 2:
                for eng in self.engines:
                    with eng.lock:
                        cur = eng.current
                    if cur is None:
                        continue
                    req, t0 = cur
                    if (not req.done and req.hedges == 0
                            and now - t0 >= hedge_after
                            and now < req.deadline):
                        req.hedges += 1
                        self._count("fleet_hedges_total")
                        self._rec.mark("fleet.hedge", rid=req.rid,
                                       engine=eng.idx,
                                       after_s=round(now - t0, 3))
                        self._q.put(req, front=True)
            # 3) restart sweep
            for eng in self.engines:
                with eng.lock:
                    t = eng.thread
                    if eng.state == "serving" \
                            and (t is None or not t.is_alive()):
                        # worker died without routing through
                        # _engine_failed (hard crash path)
                        eng.last_error = eng.last_error or "thread died"
                        self._quarantine(eng, reason="thread-death")
                    if eng.state == "quarantined" \
                            and now >= eng.restart_at:
                        self._spawn_worker(eng)
            # 4) pressure ladder
            if self._ladder is not None:
                fill = len(self._q) / max(cfg.queue_depth, 1)
                moved = self._ladder.update(fill, now)
                if moved:
                    self._rec.mark("fleet.ladder", action=moved,
                                   level=self._ladder.level,
                                   fill=round(fill, 3))
                    logger.info("fleet ladder %s → %s", moved,
                                self._ladder.describe())
            # 5) refresh trigger file (PULSE_TRACE idiom)
            self._poll_refresh_trigger()
            # 6) pulse gauges
            hub = self._hub
            if hub is not None:
                hub.set("fleet_queue_depth", len(self._q))
                if self._ladder is not None:
                    hub.set("fleet_ladder_level", self._ladder.level)
                for eng in self.engines:
                    hub.set("fleet_engine_state",
                            _STATE_CODE.get(eng.state, -1),
                            engine=eng.idx)
                    hub.set("fleet_engine_restarts", eng.restarts,
                            engine=eng.idx)
                with self._counters_lock:
                    for name, v in self.counters.items():
                        hub.set(name, v)

    def _hedge_delay(self) -> float:
        cfg = self.cfg
        if cfg.hedge_after_s > 0:
            return cfg.hedge_after_s
        lats = list(self._lat)
        if len(lats) < 16:
            # cold fleet: too few samples for an honest p99 — wait half
            # the watchdog budget rather than hedge-storm at startup
            return max(cfg.dispatch_timeout_s / 2.0, cfg.hedge_min_s)
        p99 = float(np.percentile(np.asarray(lats), 99))
        return min(max(p99 * cfg.hedge_p99_mult, cfg.hedge_min_s),
                   cfg.dispatch_timeout_s)

    def _poll_refresh_trigger(self) -> None:
        if self.artifact_dir is None:
            return
        path = os.path.join(self.artifact_dir, REFRESH_TRIGGER)
        if not os.path.isfile(path):
            return
        try:
            with open(path) as f:
                ckpt = f.read().strip()
            os.unlink(path)
        except OSError:
            return
        if not ckpt:
            return
        threading.Thread(target=self.refresh, args=(ckpt,), daemon=True,
                         name="t2omca-fleet-refresh").start()

    def _fleet_health(self) -> Tuple[bool, str]:
        serving = sum(e.state == "serving" for e in self.engines)
        ok = serving >= max(self.n_engines - 1, 1)
        return ok, f"{serving}/{self.n_engines} engines serving"

    # -------------------------------------------------------------- refresh

    def refresh(self, ckpt_dir: str) -> dict:
        """Hot param refresh: fold the new checkpoint host-side,
        fingerprint-check against the artifact's per-bucket programs,
        then roll the swap across engines one at a time — never fewer
        than N-1 serving. Any refusal or tripped post-swap health check
        leaves every engine on the params it had. → a summary dict with
        ``status`` in ``ok``/``refused``/``rolled_back``/``aborted``/
        ``busy``."""
        if not self._refresh_lock.acquire(blocking=False):
            return {"status": "busy"}
        try:
            with _watched("fleet.refresh", self._rec, stage="fold",
                          ckpt=ckpt_dir):
                try:
                    resilience.fire("fleet.refresh", stage="fold",
                                    ckpt=ckpt_dir)
                    new_params, info = self._fold_check(ckpt_dir)
                except Exception as e:  # noqa: BLE001 — refusal path
                    self._count("fleet_refresh_refused_total")
                    reason = f"{type(e).__name__}: {e}"
                    logger.warning("fleet refresh REFUSED (%s): %s",
                                   ckpt_dir, reason)
                    self._rec.mark("fleet.refresh_refused", ckpt=ckpt_dir,
                                   reason=reason[:200])
                    return {"status": "refused", "reason": reason}
            swapped: List[Tuple[_Engine, object]] = []
            with _watched("fleet.refresh", self._rec, stage="roll",
                          ckpt=ckpt_dir):
                for eng in self.engines:
                    if eng.state != "serving":
                        continue
                    others = sum(e.state == "serving" for e in self.engines
                                 if e is not eng)
                    if others < self.n_engines - 1:
                        # swapping this engine would drop the fleet
                        # below N-1 serving — abort, restore the swapped
                        self._rollback(swapped)
                        return {"status": "aborted",
                                "reason": "fleet below N-1 serving"}
                    old = getattr(eng.fe, "_params", None)
                    if not self._pause(eng):
                        self._rollback(swapped)
                        return {"status": "aborted",
                                "reason": f"engine {eng.idx} did not "
                                          f"drain in time"}
                    self._set_state(eng, "refreshing")
                    try:
                        eng.fe._params = new_params
                        self._selfcheck(eng, eng.wd, eng.fe,
                                        stage="refresh")
                    except Exception as e:  # noqa: BLE001 — rollback path
                        eng.fe._params = old
                        self._set_state(eng, "serving")
                        self._resume(eng)
                        self._rollback(swapped)
                        self._count("fleet_refresh_rollback_total")
                        reason = f"{type(e).__name__}: {e}"
                        logger.warning(
                            "fleet refresh ROLLED BACK at engine %d: %s",
                            eng.idx, reason)
                        self._rec.mark("fleet.refresh_rollback",
                                       engine=eng.idx, reason=reason[:200])
                        return {"status": "rolled_back",
                                "engine": eng.idx, "reason": reason}
                    self._set_state(eng, "serving")
                    self._resume(eng)
                    swapped.append((eng, old))
            self._live_params = new_params
            self._count("fleet_refresh_total")
            self._rec.mark("fleet.refresh_ok", ckpt=ckpt_dir,
                           engines=len(swapped))
            logger.info("fleet refresh OK: %d engines rolled to %s "
                        "(t_env=%s)", len(swapped), ckpt_dir,
                        info.get("t_env"))
            return {"status": "ok", "engines": len(swapped), **info}
        finally:
            self._refresh_lock.release()

    def _fold_check(self, ckpt_dir: str):
        """Host-side half of the refresh (OFF the request path): restore
        + re-fold the checkpoint's agent params with the artifact's OWN
        train config, cast to the serving variant, and verify each
        bucket's lowered program fingerprint still matches the
        artifact's. Raises :class:`RefreshRefused` (or the loader's own
        error) on any mismatch — param VALUES don't change a program,
        so a fingerprint drift means a different model/config reached
        the fold."""
        if self.meta is None:
            raise RefreshRefused("fleet has no artifact meta to check "
                                 "a refresh against")
        import jax

        from ..analysis.graftprog import fingerprint_text
        from ..config import from_dict
        from .export import _cast_variant, load_acting_params
        from .program import build_serve_step, serve_avals

        cfg = from_dict(self.meta["train_config"])
        acting, mac, env_info, ckpt_info = load_acting_params(
            cfg, ckpt_dir)
        variant = jax.device_put(_cast_variant(acting, self.dtype))
        progs = self.meta.get("programs", {}).get(self.dtype, {})
        checked = 0
        if progs:
            step = build_serve_step(mac)
            for b in sorted(int(x) for x in self.meta["buckets"]):
                expected = progs.get(str(b), {}).get("fingerprint")
                if not expected:
                    continue
                avals = serve_avals(mac, env_info["obs_shape"],
                                    env_info["n_actions"], b)
                fp = fingerprint_text(
                    step.trace(variant, *avals).lower().as_text())
                resilience.fire("fleet.refresh", stage="fingerprint",
                                bucket=b, fingerprint=fp)
                if fp != expected:
                    raise RefreshRefused(
                        f"bucket {b}: refolded program fingerprint "
                        f"{fp[:12]}… != artifact {expected[:12]}… — "
                        f"the checkpoint is not this artifact's model")
                checked += 1
        return variant, {"t_env": ckpt_info.get("t_env"),
                         "buckets_checked": checked}

    def _pause(self, eng: _Engine, timeout_s: float = 30.0) -> bool:
        """Take one engine out of rotation and wait until it is drained
        (idle, nothing in flight). Two consecutive idle observations a
        poll apart close the dequeue→idle.clear() race window."""
        eng.pause_ev.set()
        deadline = time.monotonic() + timeout_s
        quiet = 0
        while time.monotonic() < deadline:
            with eng.lock:
                busy = eng.current is not None
            if not busy and eng.idle.is_set():
                quiet += 1
                if quiet >= 2:
                    return True
            else:
                quiet = 0
            time.sleep(self.cfg.poll_s)
        eng.pause_ev.clear()
        return False

    def _resume(self, eng: _Engine) -> None:
        eng.pause_ev.clear()

    def _rollback(self, swapped: List[Tuple[_Engine, object]]) -> None:
        """Restore every already-swapped engine's old params (reverse
        order, pausing each): a partial refresh never survives."""
        for eng, old in reversed(swapped):
            self._pause(eng)
            eng.fe._params = old
            self._resume(eng)

    # ---------------------------------------------------------------- misc

    def _set_state(self, eng: _Engine, state: str) -> None:
        eng.state = state

    def _count(self, name: str, delta: int = 1) -> None:
        with self._counters_lock:
            self.counters[name] += delta
        if self._hub is not None:
            self._hub.inc(name, delta)

    def serving_engines(self) -> int:
        return sum(e.state == "serving" for e in self.engines)

    def warmup(self) -> None:
        """One padded dispatch per bucket on EVERY serving engine (each
        engine owns its own program cache, so warming one warms
        nothing the others look up). Call before traffic: compile
        costs land here, and the per-engine watchdog's warm deadline
        then bounds an honest steady state."""
        for eng in self.engines:
            fe = eng.fe
            if fe is not None and eng.state == "serving":
                fe.warmup()

    def stats(self) -> dict:
        """Snapshot for benches/tests: counters, ladder, per-engine
        state, recovery times."""
        with self._counters_lock:
            counters = dict(self.counters)
        return {
            "engines": [{"idx": e.idx, "state": e.state,
                         "restarts": e.restarts,
                         "last_error": e.last_error}
                        for e in self.engines],
            "serving": self.serving_engines(),
            "queue_depth": len(self._q),
            "ladder": (self._ladder.describe()
                       if self._ladder is not None else None),
            "ladder_level": (self._ladder.level
                             if self._ladder is not None else 0),
            "recoveries_s": [round(r, 3) for r in self.recoveries],
            **counters,
        }


# -------------------------------------------------------------- CLI helper

def check_refresh(artifact_dir: str, ckpt_dir: str,
                  dtype: str = "float32") -> dict:
    """The ``fleet refresh`` dry-run (``python -m t2omca_tpu.serve
    refresh``): run the host-side fold + fingerprint check a live
    fleet's :meth:`ServeFleet.refresh` would, without any engines. →
    ``{"status": "compatible"|"refused", ...}``."""
    import json
    with open(os.path.join(artifact_dir, "meta.json")) as f:
        meta = json.load(f)
    fleet = ServeFleet(artifact_dir, n_engines=1, dtype=dtype)
    fleet.meta = meta
    try:
        _, info = fleet._fold_check(ckpt_dir)
    except Exception as e:  # noqa: BLE001 — refusal is the result
        return {"status": "refused", "reason": f"{type(e).__name__}: {e}"}
    return {"status": "compatible", **info}
