"""graftprog — auditor for the *compiled* hot programs.

``graftlint`` reads source; this module reads what XLA was actually
handed. The fused superstep (docs/SPEC.md §8) concentrates the whole
rollout→insert→train pipeline into a handful of long-lived programs, so
one silent regression — an undonated buffer, a weight baked in as a
constant, a stray bf16→f32 upcast — doubles device memory or FLOPs with
every unit test still green (the PR 2 ``NormState`` donate-twice bug and
the 0.66 s-dispatch discovery both surfaced only by accident). Each
registered program (``analysis/registry.py``) is traced, lowered and —
for the donated hot programs — compiled, then checked at two levels:

**Jaxpr rules** (structural; exact):

========  ==============================================================
GP201     undonated donation: an argument the driver marks donated that
          XLA could NOT alias into an output (``input_output_aliases``
          miss) — the silent 2× device-memory bug class.
GP202     large array constant baked into the program: weights/buffers
          captured by closure instead of passed as arguments (≥ the
          ``const_bytes`` threshold) are duplicated into every
          executable and silently pin stale values.
GP203     dtype churn: ``convert_element_type`` UP from the configured
          compute dtype (bf16→f32/f64) inside the program — the
          accidental-upcast class that doubles FLOPs/bytes in the hot
          loop. Intentional upcasts (f32 loss math) are baselined by
          count.
GP204     host callback (``pure_callback``/``io_callback``/
          ``debug_callback``) reached a hot program: every dispatch now
          blocks on a host round-trip.
========  ==============================================================

**HLO budgets** (ratcheted against ``analysis/programs.json`` with
per-entry tolerances + justifications):

========  ==============================================================
GP300     program has no baseline entry (or the audit level changed) —
          new programs must be consciously baselined.
GP301     ``cost_analysis()`` FLOPs grew past the entry's tolerance.
GP302     ``cost_analysis()`` bytes-accessed grew past tolerance.
GP303     ``memory_analysis()`` peak new-allocation bytes (temp +
          output − alias) grew past tolerance (compiled entries only).
GP304     stable-HLO fingerprint drift: the program the driver builds
          is no longer the audited one — unintended retrace/aval drift
          (weak-typed scalar, shape wobble, changed static) or an
          unbaselined intentional change.
========  ==============================================================

Shrinkage (a metric now *below* tolerance, a baselined rule count no
longer reached) is reported as a stale note, never a failure — rerun
``--write-programs`` to tighten, exactly like the lint ratchet.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
import warnings
from typing import Dict, Iterator, List, Optional, Tuple

from .registry import AuditProgram, SkipProgram

#: rule id -> one-line summary (full catalog: docs/ANALYSIS.md)
GP_RULES: Dict[str, str] = {
    "GP201": "donated argument not aliased into any output (silent 2x memory)",
    "GP202": "large array constant baked into the program (closure capture)",
    "GP203": "convert_element_type up from the compute dtype (hidden upcast)",
    "GP204": "host callback inside a hot program",
    "GP300": "program missing from programs.json (unbaselined)",
    "GP301": "FLOPs grew past the baseline tolerance",
    "GP302": "bytes-accessed grew past the baseline tolerance",
    "GP303": "peak memory grew past the baseline tolerance",
    "GP304": "stable-HLO fingerprint drift (retrace/aval drift)",
}

#: GP202 threshold: constants at or above this many bytes are findings.
#: Small trace-time scalars/index tables are normal; a (256,256) f32
#: weight is 256 KiB — comfortably past this.
CONST_BYTES_DEFAULT = 16_384

#: default per-entry tolerances written for NEW programs.json entries
DEFAULT_TOLERANCE = {"flops": 0.10, "bytes_accessed": 0.10,
                     "peak_bytes": 0.25}

_DONATION_WARNING_RE = re.compile(r"donated buffers were not usable")


@dataclasses.dataclass(frozen=True)
class ProgFinding:
    """One auditor hit against a named program (the program takes the
    place of the lint finding's file:line — compiled programs have no
    lines)."""

    program: str
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.program}: {self.rule} {self.message}"


@dataclasses.dataclass
class ProgramReport:
    """Everything measured about one registered program."""

    name: str
    fingerprint: str = ""
    level: str = "lowered"             # "lowered" | "compiled"
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    peak_bytes: Optional[float] = None     # compiled entries only
    #: rule -> per-occurrence detail messages (len == occurrence count)
    rule_details: Dict[str, List[str]] = dataclasses.field(
        default_factory=dict)
    skipped: Optional[str] = None      # SkipProgram reason

    def rule_count(self, rule: str) -> int:
        return len(self.rule_details.get(rule, []))


# ------------------------------------------------------------- jaxpr walks

def _iter_closed_jaxprs(closed) -> Iterator[object]:
    """Yield ``closed`` and every ClosedJaxpr nested in equation params
    (pjit bodies, scan/cond/while branches, custom_* rules), each once.

    ``pallas_call`` equations are NOT descended into: their params hold
    the kernel jaxpr plus block-spec/index-map machinery (grid mapping,
    closed-over tile constants) that describes device-kernel plumbing,
    not host-side program structure — walking it would misreport the
    kernel's internal f32 accumulator casts as GP203 churn and its
    block-spec tables as GP202 baked constants. A Pallas kernel is
    audited as one opaque device op, like any other XLA custom call;
    pinned by tests/test_graftprog.py."""
    from jax.core import ClosedJaxpr
    seen = set()
    stack = [closed]
    while stack:
        cj = stack.pop()
        if id(cj) in seen:
            continue
        seen.add(id(cj))
        yield cj
        for eqn in cj.jaxpr.eqns:
            if "pallas" in eqn.primitive.name:
                continue                 # opaque device kernel (above)
            for v in eqn.params.values():
                if isinstance(v, ClosedJaxpr):
                    stack.append(v)
                elif isinstance(v, (tuple, list)):
                    stack.extend(u for u in v if isinstance(u, ClosedJaxpr))


def _const_findings(closed, const_bytes: int) -> List[str]:
    """GP202: array constants at/above the size threshold, anywhere in
    the program (each distinct buffer once)."""
    out, seen = [], set()
    for cj in _iter_closed_jaxprs(closed):
        for c in cj.consts:
            nbytes = getattr(c, "nbytes", 0)
            if id(c) in seen or nbytes < const_bytes:
                continue
            seen.add(id(c))
            shape = getattr(c, "shape", ())
            dtype = getattr(c, "dtype", "?")
            out.append(f"{dtype}{list(shape)} constant ({nbytes} bytes) "
                       f"baked into the program — pass it as an argument "
                       f"instead of capturing it by closure")
    return out


def _upcast_findings(closed, compute_dtype: str) -> List[str]:
    """GP203: convert_element_type from the compute dtype to a wider
    float anywhere in the program."""
    import jax.numpy as jnp
    import numpy as np
    try:
        cd = np.dtype(jnp.dtype(compute_dtype))
    except TypeError:
        return []
    out = []
    for cj in _iter_closed_jaxprs(closed):
        for eqn in cj.jaxpr.eqns:
            if eqn.primitive.name != "convert_element_type":
                continue
            src = eqn.invars[0].aval
            dst = eqn.outvars[0].aval
            if (src.dtype == cd
                    and jnp.issubdtype(dst.dtype, jnp.floating)
                    and dst.dtype.itemsize > cd.itemsize):
                out.append(f"{src.dtype}{list(src.shape)} -> {dst.dtype} "
                           f"upcast crossing the compute dtype "
                           f"({compute_dtype})")
    return out


def _callback_findings(closed) -> List[str]:
    """GP204: host-callback primitives anywhere in the program.
    ``pallas_call`` is explicitly exempt: it is a device kernel launch
    (Mosaic custom call on TPU, interpreter evaluation on CPU), not a
    host round-trip — name-matching must never misclassify it even if a
    future jax release renames the primitive toward the callback
    family."""
    out = []
    for cj in _iter_closed_jaxprs(closed):
        for eqn in cj.jaxpr.eqns:
            name = eqn.primitive.name
            if "pallas" in name:
                continue                 # device kernel, not a callback
            if "callback" in name:
                out.append(f"`{name}` inside the program: every dispatch "
                           f"blocks on a host round-trip")
    return out


# ----------------------------------------------------------------- metrics

def _cost_dict(stage) -> Dict[str, float]:
    """``cost_analysis()`` is a dict on some jaxlib versions and a
    one-element list of dicts on others — normalize."""
    try:
        ca = stage.cost_analysis()
    except Exception:  # noqa: BLE001 — backend may not implement it
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def fingerprint_text(text: str) -> str:
    """Stable-HLO fingerprint: sha256 over the lowered module text with
    line-edge whitespace normalized (formatting churn across jaxlib
    point releases must not read as a program change)."""
    norm = "\n".join(l.strip() for l in text.splitlines() if l.strip())
    return hashlib.sha256(norm.encode()).hexdigest()[:16]


# ------------------------------------------------------------------- audit

def audit_program(name: str, prog: AuditProgram, compute_dtype: str,
                  const_bytes: int = CONST_BYTES_DEFAULT) -> ProgramReport:
    """Trace + lower (+ optionally compile) one registered program and
    run every jaxpr-level rule. Never *executes* the program."""
    report = ProgramReport(name=name)
    if prog.skip is not None:
        report.skipped = prog.skip
        return report
    try:
        traced = prog.fn.trace(*prog.args, **prog.kwargs)
    except SkipProgram as e:
        report.skipped = str(e)
        return report
    closed = traced.jaxpr

    details: Dict[str, List[str]] = {}
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        lowered = traced.lower()
    text = lowered.as_text()
    # GP201 primary signal: donated flat leaves minus the args the
    # lowering accepted for donation — `tf.aliasing_output` (alias
    # resolved at lowering, unsharded programs) or `jax.buffer_donor`
    # (deferred to XLA, sharded programs); a REJECTED donation carries
    # neither marker. Counting the text is authoritative; jax's
    # "donated buffers were not usable" warning (mlir.py) is only used
    # for the per-leaf aval detail — the lowering cache suppresses it
    # on any re-lower of the same jit+avals in-process, so a
    # warning-only check silently reports clean on the second audit of
    # a genuinely-broken program.
    if prog.donate_argnums:
        import jax
        donated = jax.tree_util.tree_leaves(
            [prog.args[i] for i in prog.donate_argnums
             if i < len(prog.args)])
        missing = (len(donated) - text.count("tf.aliasing_output")
                   - text.count("jax.buffer_donor"))
        if missing > 0:
            unaliased: List[str] = []
            for w in caught:
                msg = str(w.message)
                if _DONATION_WARNING_RE.search(msg):
                    unaliased.extend(
                        re.findall(r"ShapedArray\([^)]*\)", msg))
            if len(unaliased) == missing:
                details["GP201"] = [
                    f"donated leaf {aval} has no input_output_alias — "
                    f"XLA copies instead of updating in place (donated "
                    f"args: {prog.donate_argnums})" for aval in unaliased]
            else:        # cached lowering: counts only, avals unknown
                details["GP201"] = [
                    f"donated leaf {i + 1}/{missing} (of {len(donated)} "
                    f"donated) has no input_output_alias — XLA copies "
                    f"instead of updating in place (donated args: "
                    f"{prog.donate_argnums})" for i in range(missing)]

    if (d := _const_findings(closed, const_bytes)):
        details["GP202"] = d
    if (d := _upcast_findings(closed, compute_dtype)):
        details["GP203"] = d
    if (d := _callback_findings(closed)):
        details["GP204"] = d
    report.rule_details = details
    report.fingerprint = fingerprint_text(text)

    if prog.compile:
        compiled = lowered.compile()
        report.level = "compiled"
        cost = _cost_dict(compiled)
        try:
            mem = compiled.memory_analysis()
            report.peak_bytes = float(
                mem.temp_size_in_bytes + mem.output_size_in_bytes
                - mem.alias_size_in_bytes)
        except Exception:  # noqa: BLE001 — not every backend reports it
            report.peak_bytes = None
    else:
        cost = _cost_dict(lowered)
    report.flops = cost.get("flops")
    report.bytes_accessed = cost.get("bytes accessed")
    return report


def audit_registry(reg: Dict[str, AuditProgram], compute_dtype: str,
                   const_bytes: int = CONST_BYTES_DEFAULT,
                   only: Optional[List[str]] = None) -> List[ProgramReport]:
    """Audit every (or the ``only``-selected) registered program."""
    names = list(reg) if not only else [n for n in reg if n in set(only)]
    if only:
        missing = set(only) - set(reg)
        if missing:
            raise KeyError(f"unknown audit program(s): {sorted(missing)}; "
                           f"registered: {sorted(reg)}")
    return [audit_program(n, reg[n], compute_dtype, const_bytes)
            for n in names]


# ----------------------------------------------------------------- ratchet

def _over(value: Optional[float], base: Optional[float],
          tol: float) -> bool:
    return (value is not None and base is not None
            and value > base * (1.0 + tol))


def _under(value: Optional[float], base: Optional[float],
           tol: float) -> bool:
    return (value is not None and base is not None
            and value < base * (1.0 - tol))


def compare_reports(reports: List[ProgramReport],
                    baseline: Dict[str, dict]
                    ) -> Tuple[List[ProgFinding], List[str]]:
    """-> (new_findings, stale_notes), the lint-ratchet contract:
    regressions past each entry's tolerance fail, improvements and
    vanished entries only warn (rerun ``--write-programs`` to tighten).
    """
    findings: List[ProgFinding] = []
    stale: List[str] = []
    seen = set()
    for rep in reports:
        seen.add(rep.name)
        if rep.skipped is not None:
            stale.append(f"{rep.name}: skipped ({rep.skipped})")
            continue
        entry = baseline.get(rep.name)
        if entry is None:
            findings.append(ProgFinding(
                rep.name, "GP300",
                "no baseline entry in programs.json — audit it and "
                "accept with --write-programs (plus a justification)"))
            # rule findings still surface raw so the report is actionable
            for rule, msgs in sorted(rep.rule_details.items()):
                findings.extend(ProgFinding(rep.name, rule, m)
                                for m in msgs)
            continue
        if entry.get("level", "lowered") != rep.level:
            findings.append(ProgFinding(
                rep.name, "GP300",
                f"audit level changed ({entry.get('level')!r} -> "
                f"{rep.level!r}) — costs are incomparable; re-baseline "
                f"with --write-programs"))
            continue
        tol = {**DEFAULT_TOLERANCE, **entry.get("tolerance", {})}
        base_fp = entry.get("fingerprint", "")
        if base_fp and rep.fingerprint != base_fp:
            findings.append(ProgFinding(
                rep.name, "GP304",
                f"stable-HLO fingerprint {rep.fingerprint} != baselined "
                f"{base_fp} — the driver now builds a different program "
                f"(aval drift? weak-typed scalar? intended change? "
                f"accept with --write-programs)"))
        for rule in ("GP201", "GP202", "GP203", "GP204"):
            allowed = int(entry.get("rules", {}).get(rule, {})
                          .get("count", 0))
            msgs = rep.rule_details.get(rule, [])
            if len(msgs) > allowed:
                for m in msgs[allowed:]:
                    findings.append(ProgFinding(rep.name, rule, m))
                findings.append(ProgFinding(
                    rep.name, rule,
                    f"{len(msgs)} occurrence(s) > {allowed} baselined"))
            elif len(msgs) < allowed:
                stale.append(f"{rep.name}: {rule} count dropped "
                             f"{allowed} -> {len(msgs)} (fixed? rerun "
                             f"--write-programs to tighten)")
        for metric, rule in (("flops", "GP301"),
                             ("bytes_accessed", "GP302"),
                             ("peak_bytes", "GP303")):
            value = getattr(rep, metric)
            base = entry.get(metric)
            t = tol.get(metric, 0.10)
            if _over(value, base, t):
                findings.append(ProgFinding(
                    rep.name, rule,
                    f"{metric} {value:.0f} > baselined {base:.0f} "
                    f"(+{(value / base - 1) * 100:.1f}%, tolerance "
                    f"{t * 100:.0f}%) — justify and --write-programs, "
                    f"or fix the regression"))
            elif _under(value, base, t):
                stale.append(f"{rep.name}: {metric} improved "
                             f"{base:.0f} -> {value:.0f} (rerun "
                             f"--write-programs to tighten)")
    for name in sorted(set(baseline) - seen):
        stale.append(f"{name}: baselined program no longer registered")
    return findings, stale
