"""graftrace — static thread-topology & lock-discipline auditor (GT1xx).

graftlint/graftprog/graftshard ratchet the traced/compiled plane; the
host concurrency plane that keeps those programs alive — the watchdog
monitor/on-stall/ExitDeadline threads, Sebulba's decoupled actor thread
(Podracer, PAPERS.md), graftfleet's engine/supervisor threads behind one
admission queue, the pulse HTTP scrape server, TraceController — had no
gate, and every thread-safety bug so far (the unsynchronized
``Logger.stats`` race, SpanRecorder completion-keys-outside-the-lock,
the shared-Watchdog-stamp gotcha, the unbounded ``save_lock.acquire()``
exit wedge) was found by hand in review passes. graftrace is the fourth
static plane and the first that guards the robustness layer itself:

1. **Thread topology** — spawn sites (``threading.Thread(target=...)``,
   ``threading.Timer``, ``Executor.submit``, ``*HTTPRequestHandler``
   subclasses) seed thread *roles*; roles propagate through the
   module-local call graph (``f()`` / ``self.m()``) to a fixpoint, so
   a helper called from both the main thread and a worker carries both
   roles. Everything not reachable from a spawn site runs as ``main``.
2. **Shared-state census** — ``self.<attr>`` accesses (incl. through
   class-annotated parameters/locals), module globals written via
   ``global``, and closure variables shared with a spawned nested
   function. Each access site records its role set, whether it writes,
   and the set of locks held (``with lock:`` blocks, statement-level
   ``acquire``/``release``, and ``if lock.acquire(timeout=...)``
   guards).
3. **Lock discipline** over the census (the GT rules below).

========  ==============================================================
GT101     Shared state written from one role and accessed from another
          with NO lock at any site: the ``Logger.stats`` race class.
GT102     Bare ``lock.acquire()`` without ``timeout=`` /
          ``blocking=False`` in a threaded module: a stuck holder
          wedges the thread with no watchdog escape — the PR 4
          ``save_lock`` exit wedge, package-wide and role-aware
          (GL111 covers only LOCK_PATH_GLOBS).
GT103     Mixed discipline on one attribute: some sites hold a lock,
          others don't (or hold a different one) — the lock protects
          nothing (SpanRecorder completion-keys class).
GT104     Lock-ordering cycle: somewhere ``A`` is held while taking
          ``B`` and elsewhere ``B`` is held while taking ``A`` — the
          classic ABBA deadlock, detected on the acquisition graph.
GT105     One ``Watchdog`` instance stamped (``stamp``/``clear``/
          ``watch``) from >= 2 roles: stamps interleave and a stall in
          one thread is masked by the other's heartbeat — each thread
          needs its own watchdog (the Sebulba shared-stamp gotcha).
GT106     Blocking/device-facing call (``device_get``,
          ``block_until_ready``, unbounded ``join()``/``wait()``,
          socket ops, ``time.sleep``) while holding a lock that
          another role contends: every contender stalls behind the
          device/socket, watchdogs can't preempt a held lock.
========  ==============================================================

Scope and honesty about limits: analysis is **per module** — a thread
spawned in one module running a function from another is invisible, as
is state shared through an object handed across modules. Call-graph
propagation resolves ``f()`` against the lexical scope chain and
``self.m()`` against the enclosing class; calls through arbitrary
attributes (``self.hub.gauge(...)``) are not tracked, so roles are an
under-approximation and lock inference (``with self._lock``) is
name-based. Writes in ``__init__``/``__post_init__`` and — for closure
state — lexically before the first spawn in the owning function are
treated as pre-thread (happens-before the spawn) and exempt.
False positives are expected and cheap: suppress a line with
``# graftrace: disable=GT1xx`` (``# graftrace: skip-file`` at the top
skips a module) or accept it into ``analysis/baseline.json`` with a
justification — GT findings share the graftlint ratchet file, keyed by
(rule, path, code-line text) so unrelated edits don't churn entries.
CLI: ``python -m t2omca_tpu.analysis --threads`` (jax-free, < 5 s;
``scripts/lint.sh --threads``; a tier-1 prelude in ``scripts/t1.sh``).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .graftlint import Finding, _dotted

#: rule id -> one-line summary (the full catalog lives in docs/ANALYSIS.md)
GT_RULES: Dict[str, str] = {
    "GT101": "unlocked cross-thread write to shared state",
    "GT102": "bare lock acquire() without timeout in a threaded module",
    "GT103": "mixed locked/unlocked access to one shared attribute",
    "GT104": "lock-ordering cycle across the acquisition graph",
    "GT105": "one Watchdog instance stamped from >= 2 thread roles",
    "GT106": "blocking call while holding a lock another role contends",
}

_SUPPRESS_RE = re.compile(r"#\s*graftrace:\s*disable(?:=(?P<rules>\S+))?")
_SKIP_FILE_RE = re.compile(r"#\s*graftrace:\s*skip-file")

#: constructors whose result is a lock-like object (trackable identity;
#: ``with``/``acquire`` on one participates in the discipline checks)
_LOCK_FACTORIES = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
})
#: constructors whose result is internally synchronized — excluded from
#: the shared-state census (deque append/popleft are CPython-atomic and
#: used as such throughout the repo; Thread handles are control-plane)
_SAFE_FACTORIES = frozenset({
    "threading.Event", "threading.Barrier", "threading.local",
    "threading.Thread", "threading.Timer",
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "collections.deque",
})
#: constructors that build a Watchdog (GT105 identity tracking). The
#: tail-match also catches ``from ..utils.watchdog import Watchdog``
#: (relative imports resolve to a bare name).
_WATCHDOG_TAILS = frozenset({"Watchdog"})
#: Watchdog methods that stamp the shared liveness channel
_STAMP_METHODS = frozenset({"stamp", "clear", "watch"})

#: method names whose call mutates the receiver (census write markers)
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "add", "clear", "update",
    "setdefault", "sort", "reverse", "put", "put_nowait",
})
#: methods whose writes are pre-thread setup: accesses here are exempt
#: from GT101/GT103 (object construction happens-before the spawn)
_INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__"})
#: handler base classes whose methods run on server threads
_HANDLER_BASES = frozenset({
    "BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
    "CGIHTTPRequestHandler", "BaseRequestHandler",
    "StreamRequestHandler", "DatagramRequestHandler",
})
#: always-blocking calls for GT106 (canonical dotted names)
_BLOCKING_NAMES = frozenset({
    "jax.device_get", "jax.block_until_ready", "time.sleep",
})
#: attribute calls that block the calling thread: socket/file ops are
#: unconditional; join/wait only when unbounded (no timeout)
_BLOCKING_SOCKET_ATTRS = frozenset({
    "recv", "recvfrom", "accept", "connect", "sendall", "sendto",
    "serve_forever", "handle_request", "getconn", "select",
})
_BLOCKING_IF_UNBOUNDED = frozenset({"join", "wait"})


def _has_timeout(call: ast.Call) -> bool:
    """``acquire``/``join``/``wait`` call carries a bound: a ``timeout=``
    kw, ``blocking=False``, or a positional argument (the timeout for
    join/wait, the blocking flag for acquire — ``acquire(False)``)."""
    if any(kw.arg in ("timeout", "blocking") for kw in call.keywords):
        return True
    return bool(call.args)


def _is_bounded_acquire(call: ast.Call) -> bool:
    """GT102 boundedness: ``acquire(timeout=...)``, ``acquire(
    blocking=False)`` or positional ``acquire(False)`` — mirrors
    GL111's definition so the two rules never disagree on a site."""
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is False:
        return True
    return False


# --------------------------------------------------------------- structure

@dataclasses.dataclass
class _FnInfo:
    """One function-like scope (def / async def / spawned lambda)."""

    node: ast.AST
    qualname: str
    name: str
    cls: Optional[str]                  # enclosing class name, if a method
    parent: Optional[int]               # id(node) of the enclosing function
    bound: Set[str] = dataclasses.field(default_factory=set)
    nonlocals: Set[str] = dataclasses.field(default_factory=set)
    globals_decl: Set[str] = dataclasses.field(default_factory=set)
    children: Dict[str, int] = dataclasses.field(default_factory=dict)
    roles: Set[str] = dataclasses.field(default_factory=set)
    spawn_target: bool = False
    #: names this scope shares with a nested function IT spawns
    shared: Set[str] = dataclasses.field(default_factory=set)
    #: lexically first spawn statement line in this scope (None = none):
    #: closure accesses before it happen-before the thread exists
    first_spawn_line: Optional[int] = None
    #: local name -> lock id (``l = threading.Lock()`` at this scope)
    local_locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: local name -> watchdog id
    local_wds: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: local names bound to internally-synchronized objects
    local_safe: Set[str] = dataclasses.field(default_factory=set)
    #: local/param name -> module class name (annotation or constructor)
    typed: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _Access:
    """One shared-state access site."""

    key: Tuple                           # census key (kind, owner, name)
    write: bool
    init: bool                           # pre-thread (exempt) site
    roles: frozenset
    held: frozenset                      # lock ids held at the site
    node: ast.AST
    fn: str                              # qualname, for messages


@dataclasses.dataclass
class _Acquire:
    """One lock acquisition event (``with`` or ``.acquire``)."""

    lock: str
    roles: frozenset
    held: frozenset                      # locks already held (GT104 edges)
    node: ast.AST


@dataclasses.dataclass
class _Blocking:
    """One blocking call made while >= 1 lock was held."""

    what: str
    roles: frozenset
    held: frozenset
    node: ast.AST


class _ModuleTracer:
    """One parsed module: topology discovery, census, discipline rules.
    Produces a deduplicated, line-sorted :class:`Finding` list."""

    def __init__(self, src: str, path: str):
        self.src = src
        self.path = path
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        #: local alias -> canonical dotted path (same scheme as graftlint)
        self.modmap: Dict[str, str] = {}
        #: id(fn node) -> info
        self.fns: Dict[int, _FnInfo] = {}
        #: class name -> {method name -> fn id}
        self.methods: Dict[str, Dict[str, int]] = {}
        #: module-level def name -> fn id
        self.top_fns: Dict[str, int] = {}
        self.classes: Set[str] = set()
        self.handler_classes: Set[str] = set()
        #: (class, attr) -> lock id  /  safe-attr set  /  watchdog ids
        self.lock_attrs: Dict[Tuple[str, str], str] = {}
        self.safe_attrs: Set[Tuple[str, str]] = set()
        self.wd_attrs: Dict[Tuple[str, str], str] = {}
        #: module-global name -> lock / watchdog id, safe set
        self.global_locks: Dict[str, str] = {}
        self.global_wds: Dict[str, str] = {}
        self.global_safe: Set[str] = set()
        #: globals written via a ``global`` declaration somewhere
        self.written_globals: Set[str] = set()
        #: call edges: caller fn id (None = module level) -> callee ids
        self.calls: Dict[Optional[int], Set[int]] = {}
        #: recorded events
        self.accesses: List[_Access] = []
        self.acquires: List[_Acquire] = []
        self.blockings: List[_Blocking] = []
        self.findings: Set[Finding] = set()
        self.has_spawns = False
        self._collect_imports()

    # ------------------------------------------------------------ aliases

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.modmap[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        self.modmap[root] = root
            elif isinstance(node, ast.ImportFrom):
                if node.module is None or node.level:
                    continue            # relative imports: package-internal
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.modmap[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def canonical(self, node: ast.AST) -> Optional[str]:
        dotted = _dotted(node)
        if dotted is None:
            return None
        root, _, rest = dotted.partition(".")
        base = self.modmap.get(root)
        if base is None:
            return dotted
        return f"{base}.{rest}" if rest else base

    # ---------------------------------------------------------- emission

    def emit(self, node: ast.AST, rule: str, message: str) -> None:
        line, col = node.lineno, node.col_offset + 1
        code = (self.lines[line - 1].strip()
                if 0 < line <= len(self.lines) else "")
        m = _SUPPRESS_RE.search(self.lines[line - 1]) \
            if 0 < line <= len(self.lines) else None
        if m:
            named = m.group("rules")
            if named is None or rule in {r.strip().upper()
                                         for r in named.split(",")}:
                return
        self.findings.add(Finding(path=self.path, line=line, col=col,
                                  rule=rule, message=message, code=code))

    # --------------------------------------------------- pass 1: structure

    def build(self) -> None:
        """Scope tree, class/method tables, lock/safe/watchdog identity,
        spawn sites and role seeding + propagation."""
        self._walk_structure(self.tree, parent=None, cls=None)
        self._collect_identities()
        self._collect_spawns()
        self._collect_calls()
        self._propagate_roles()
        self._collect_closure_shared()

    def _walk_structure(self, node: ast.AST, parent: Optional[int],
                        cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if parent is not None:
                    qual = f"{self.fns[parent].qualname}.{child.name}"
                elif cls is not None:
                    qual = f"{cls}.{child.name}"
                else:
                    qual = child.name
                info = _FnInfo(node=child, qualname=qual, name=child.name,
                               cls=cls, parent=parent)
                a = child.args
                for p in (a.posonlyargs + a.args + a.kwonlyargs):
                    info.bound.add(p.arg)
                    ann = p.annotation
                    tname = None
                    if isinstance(ann, ast.Name):
                        tname = ann.id
                    elif isinstance(ann, ast.Constant) and \
                            isinstance(ann.value, str):
                        tname = ann.value.strip("'\"")
                    if tname:
                        info.typed[p.arg] = tname
                for extra in (a.vararg, a.kwarg):
                    if extra is not None:
                        info.bound.add(extra.arg)
                self.fns[id(child)] = info
                if parent is not None:
                    self.fns[parent].children[child.name] = id(child)
                    self.fns[parent].bound.add(child.name)
                elif cls is not None:
                    self.methods.setdefault(cls, {})[child.name] = \
                        id(child)
                else:
                    self.top_fns[child.name] = id(child)
                # class bodies don't form closure scopes: a method's
                # enclosing function scope skips the class
                self._walk_structure(child, parent=id(child), cls=cls)
                continue
            if isinstance(child, ast.ClassDef):
                self.classes.add(child.name)
                for base in child.bases:
                    d = _dotted(base) or ""
                    if d.rsplit(".", 1)[-1] in _HANDLER_BASES:
                        self.handler_classes.add(child.name)
                self._walk_structure(child, parent=parent,
                                     cls=child.name)
                continue
            if isinstance(child, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                                  ast.For, ast.With, ast.AsyncWith)):
                if parent is not None:
                    self._bind_targets(child, self.fns[parent])
            if isinstance(child, (ast.Global, ast.Nonlocal)) and \
                    parent is not None:
                info = self.fns[parent]
                if isinstance(child, ast.Global):
                    info.globals_decl.update(child.names)
                    self.written_globals.update(child.names)
                else:
                    info.nonlocals.update(child.names)
            self._walk_structure(child, parent=parent, cls=cls)

    @staticmethod
    def _bind_targets(stmt: ast.AST, info: _FnInfo) -> None:
        targets: List[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.For):
            targets = [stmt.target]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            targets = [i.optional_vars for i in stmt.items
                       if i.optional_vars is not None]
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    info.bound.add(n.id)

    # ----------------------------------------------- identity discovery

    def _collect_identities(self) -> None:
        """Lock / safe / watchdog / class-typed bindings, all scopes."""

        def classify(value: ast.expr) -> Tuple[Optional[str], str]:
            """-> (kind, detail): kind in lock/safe/wd/class/None."""
            if not isinstance(value, ast.Call):
                return None, ""
            name = self.canonical(value.func)
            if name in _LOCK_FACTORIES:
                return "lock", name
            if name in _SAFE_FACTORIES:
                return "safe", name
            tail = (name or "").rsplit(".", 1)[-1]
            if tail in _WATCHDOG_TAILS:
                return "wd", tail
            if name in self.classes:
                return "class", name
            return None, ""

        for scope_id, stmts in self._iter_scopes():
            info = self.fns.get(scope_id) if scope_id is not None else None
            for stmt in stmts:
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                value = stmt.value
                if value is None:
                    continue
                kind, detail = classify(value)
                if kind is None:
                    continue
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    if isinstance(t, ast.Name):
                        if info is None:            # module scope
                            if kind == "lock":
                                self.global_locks[t.id] = t.id
                            elif kind == "safe":
                                self.global_safe.add(t.id)
                            elif kind == "wd":
                                self.global_wds[t.id] = t.id
                        else:
                            lid = f"{info.qualname}.{t.id}"
                            if kind == "lock":
                                info.local_locks[t.id] = lid
                            elif kind == "safe":
                                info.local_safe.add(t.id)
                            elif kind == "wd":
                                info.local_wds[t.id] = lid
                            elif kind == "class":
                                info.typed[t.id] = detail
                    elif isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self" and info is not None \
                            and info.cls is not None:
                        key = (info.cls, t.attr)
                        aid = f"{info.cls}.{t.attr}"
                        if kind == "lock":
                            self.lock_attrs[key] = aid
                        elif kind == "safe":
                            self.safe_attrs.add(key)
                        elif kind == "wd":
                            self.wd_attrs[key] = aid

    def _iter_scopes(self):
        """(scope id | None for module, its direct statement list) —
        statement lists include nested compound bodies but stop at
        nested function/class boundaries for binding attribution."""

        def stmts_of(body, acc):
            for s in body:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    continue
                acc.append(s)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(s, attr, None)
                    if sub:
                        stmts_of(sub, acc)
                for h in getattr(s, "handlers", []):
                    stmts_of(h.body, acc)
            return acc

        yield None, stmts_of(list(self.tree.body), [])
        for fid, info in self.fns.items():
            body = getattr(info.node, "body", None)
            if isinstance(body, list):
                yield fid, stmts_of(list(body), [])
            # spawned lambdas have an expression body — no statements

    # ------------------------------------------------------- spawn sites

    def _enclosing_fn(self, node: ast.AST) -> Optional[int]:
        """fn id whose body lexically contains ``node`` (None = module
        level). Precomputed containment map, built on first use."""
        if not hasattr(self, "_owner"):
            owner: Dict[int, Optional[int]] = {}

            def walk(n: ast.AST, fid: Optional[int]) -> None:
                for c in ast.iter_child_nodes(n):
                    nid = id(c) if id(c) in self.fns else fid
                    owner[id(c)] = fid
                    walk(c, nid)

            walk(self.tree, None)
            self._owner = owner
        return self._owner.get(id(node))

    def _resolve_fn_name(self, name: str,
                         from_fn: Optional[int]) -> Optional[int]:
        """Lexical resolution of a bare function name: nested defs of
        enclosing scopes first, then module-level defs."""
        fid = from_fn
        while fid is not None:
            info = self.fns[fid]
            if name in info.children:
                return info.children[name]
            fid = info.parent
        return self.top_fns.get(name)

    def _resolve_target(self, expr: ast.expr,
                        site_fn: Optional[int]) -> Tuple[Optional[int],
                                                         str]:
        """Spawn-target expression -> (fn id | None, role name)."""
        if isinstance(expr, ast.Name):
            fid = self._resolve_fn_name(expr.id, site_fn)
            return fid, expr.id.lstrip("_") or expr.id
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and site_fn is not None:
                cls = self.fns[site_fn].cls
                if cls is not None:
                    fid = self.methods.get(cls, {}).get(expr.attr)
                    return fid, expr.attr.lstrip("_") or expr.attr
            return None, expr.attr.lstrip("_") or expr.attr
        if isinstance(expr, ast.Lambda):
            # synthesize a scope for the lambda body so its accesses
            # are attributed to the spawned role, not the spawner
            site = self.fns.get(site_fn) if site_fn is not None else None
            qual = (f"{site.qualname}.<lambda>" if site is not None
                    else "<lambda>")
            info = _FnInfo(node=expr, qualname=qual, name="<lambda>",
                           cls=site.cls if site is not None else None,
                           parent=site_fn)
            for p in (expr.args.posonlyargs + expr.args.args
                      + expr.args.kwonlyargs):
                info.bound.add(p.arg)
            self.fns[id(expr)] = info
            if hasattr(self, "_owner"):
                del self._owner        # containment map must see the
            return id(expr), "lambda"  # new scope on next lookup
        return None, "thread"

    def _collect_spawns(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self.canonical(node.func)
            target: Optional[ast.expr] = None
            if name in ("threading.Thread", "threading.Timer"):
                for kw in node.keywords:
                    if kw.arg in ("target", "function"):
                        target = kw.value
                if target is None and name == "threading.Timer" and \
                        len(node.args) >= 2:
                    target = node.args[1]
                if target is None:
                    for a in node.args:     # Thread(target=...) is the
                        if not isinstance(a, ast.Constant):  # repo idiom,
                            target = a      # positional is a fallback
                            break
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "submit" and node.args:
                target = node.args[0]
            if target is None:
                continue
            self.has_spawns = True
            site_fn = self._enclosing_fn(node)
            fid, role = self._resolve_target(target, site_fn)
            if fid is not None:
                info = self.fns[fid]
                info.roles.add(role)
                info.spawn_target = True
            # happens-before marker: the spawn's lexical line, on the
            # spawning scope AND every enclosing scope — straight-line
            # setup above the spawn point happens-before the thread
            # exists even when the spawn lives in a nested helper
            fid_up: Optional[int] = site_fn
            while fid_up is not None:
                site = self.fns[fid_up]
                if site.first_spawn_line is None or \
                        node.lineno < site.first_spawn_line:
                    site.first_spawn_line = node.lineno
                fid_up = site.parent
        # HTTP handler classes: every method runs on a server thread
        for cls in self.handler_classes:
            self.has_spawns = True
            for fid in self.methods.get(cls, {}).values():
                self.fns[fid].roles.add("http")
                self.fns[fid].spawn_target = True

    # -------------------------------------------------------- call graph

    def _collect_calls(self) -> None:
        for fid in list(self.fns) + [None]:
            self.calls.setdefault(fid, set())
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            caller = self._enclosing_fn(node)
            callee: Optional[int] = None
            if isinstance(node.func, ast.Name):
                callee = self._resolve_fn_name(node.func.id, caller)
            elif isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name):
                base = node.func.value.id
                if base == "self" and caller is not None and \
                        self.fns[caller].cls is not None:
                    callee = self.methods.get(
                        self.fns[caller].cls, {}).get(node.func.attr)
                elif caller is not None:
                    tcls = self._typed_class(base, caller)
                    if tcls is not None:
                        callee = self.methods.get(tcls, {}).get(
                            node.func.attr)
            if callee is not None:
                self.calls.setdefault(caller, set()).add(callee)

    def _typed_class(self, name: str, fn_id: int) -> Optional[str]:
        """Class of a local/param name, via annotations / constructor
        assignment, searched up the lexical chain."""
        fid: Optional[int] = fn_id
        while fid is not None:
            info = self.fns[fid]
            if name in info.typed and info.typed[name] in self.classes:
                return info.typed[name]
            if name in info.bound:
                return None
            fid = info.parent
        return None

    def _propagate_roles(self) -> None:
        # incoming-edge count: entry points (no module-local caller, not
        # a spawn target, not a handler method) run on the main thread
        called: Set[int] = set()
        for callees in self.calls.values():
            called.update(callees)
        for fid, info in self.fns.items():
            if info.spawn_target:
                continue
            if fid not in called or None in [
                    c for c, callees in self.calls.items()
                    if fid in callees]:
                info.roles.add("main")
        changed = True
        while changed:
            changed = False
            for caller, callees in self.calls.items():
                roles = (self.fns[caller].roles if caller is not None
                         else {"main"})
                for callee in callees:
                    info = self.fns[callee]
                    if not roles <= info.roles:
                        info.roles |= roles
                        changed = True
        for info in self.fns.values():
            if not info.roles:
                info.roles.add("main")

    # ------------------------------------------------ closure shared sets

    def _collect_closure_shared(self) -> None:
        """For every scope F that spawns a nested function G: the names
        free in G (and its descendants) that are bound in F are shared
        state between role(F) and role(G)."""
        for fid, info in self.fns.items():
            if not info.spawn_target or info.parent is None:
                continue
            free = self._free_names(fid)
            anc = info.parent
            remaining = set(free)
            while anc is not None and remaining:
                a = self.fns[anc]
                hit = remaining & a.bound
                a.shared |= hit
                remaining -= hit
                anc = a.parent

    def _free_names(self, fid: int) -> Set[str]:
        info = self.fns[fid]
        free: Set[str] = set(info.nonlocals)
        for n in ast.walk(info.node):
            if isinstance(n, ast.Name) and n.id not in info.bound and \
                    n.id not in info.globals_decl:
                free.add(n.id)
        return free

    # --------------------------------------------- pass 2: held-lock walk

    def scan(self) -> None:
        for fid, info in self.fns.items():
            body = getattr(info.node, "body", None)
            if isinstance(body, list):
                self._walk_block(body, frozenset(), info)
            else:                                  # spawned lambda body
                self._scan_expr(info.node.body, frozenset(), info)
        # module level: role main, pre-thread by definition
        self._walk_block(
            [s for s in self.tree.body
             if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))],
            frozenset(), None)

    def _lock_id(self, expr: ast.expr,
                 info: Optional[_FnInfo]) -> Optional[str]:
        """Resolve a ``with X`` / ``X.acquire()`` receiver to a known
        lock identity (None when X isn't a trackable lock)."""
        if isinstance(expr, ast.Name):
            fid = id(info.node) if info is not None else None
            while fid is not None:
                f = self.fns[fid]
                if expr.id in f.local_locks:
                    return f.local_locks[expr.id]
                if expr.id in f.bound:
                    return None
                fid = f.parent
            return self.global_locks.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base = expr.value.id
            if base == "self" and info is not None and \
                    info.cls is not None:
                return self.lock_attrs.get((info.cls, expr.attr))
            if info is not None:
                tcls = self._typed_class(base, id(info.node))
                if tcls is not None:
                    return self.lock_attrs.get((tcls, expr.attr))
        return None

    def _wd_id(self, expr: ast.expr,
               info: Optional[_FnInfo]) -> Optional[str]:
        if isinstance(expr, ast.Name):
            fid = id(info.node) if info is not None else None
            while fid is not None:
                f = self.fns[fid]
                if expr.id in f.local_wds:
                    return f.local_wds[expr.id]
                if expr.id in f.bound:
                    return None
                fid = f.parent
            return self.global_wds.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base = expr.value.id
            if base == "self" and info is not None and \
                    info.cls is not None:
                return self.wd_attrs.get((info.cls, expr.attr))
            if info is not None:
                tcls = self._typed_class(base, id(info.node))
                if tcls is not None:
                    return self.wd_attrs.get((tcls, expr.attr))
        return None

    def _roles_of(self, info: Optional[_FnInfo]) -> frozenset:
        return frozenset(info.roles) if info is not None \
            else frozenset({"main"})

    def _record_acquire(self, lock: str, held: frozenset,
                        node: ast.AST, info: Optional[_FnInfo]) -> None:
        self.acquires.append(_Acquire(lock=lock, roles=self._roles_of(info),
                                      held=held, node=node))

    def _walk_block(self, stmts: Sequence[ast.stmt], held: frozenset,
                    info: Optional[_FnInfo]) -> frozenset:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue                       # own scan pass
            if isinstance(s, (ast.With, ast.AsyncWith)):
                newly: Set[str] = set()
                for item in s.items:
                    self._scan_expr(item.context_expr, held, info)
                    lid = self._lock_id(item.context_expr, info)
                    if lid is not None:
                        self._record_acquire(lid, held | newly,
                                             item.context_expr, info)
                        newly.add(lid)
                self._walk_block(s.body, held | newly, info)
                continue
            if isinstance(s, ast.If):
                self._scan_expr(s.test, held, info)
                guard = self._acquire_in_test(s.test, info)
                body_held = held | ({guard[0]} if guard else set())
                if guard:
                    self._record_acquire(guard[0], held, guard[1], info)
                self._walk_block(s.body, body_held, info)
                self._walk_block(s.orelse, held, info)
                continue
            if isinstance(s, (ast.For, ast.AsyncFor)):
                self._scan_expr(s.iter, held, info)
                self._walk_block(s.body, held, info)
                self._walk_block(s.orelse, held, info)
                continue
            if isinstance(s, ast.While):
                self._scan_expr(s.test, held, info)
                self._walk_block(s.body, held, info)
                self._walk_block(s.orelse, held, info)
                continue
            if isinstance(s, ast.Try):
                self._walk_block(s.body, held, info)
                for h in s.handlers:
                    self._walk_block(h.body, held, info)
                self._walk_block(s.orelse, held, info)
                self._walk_block(s.finalbody, held, info)
                continue
            # simple statement: scan expressions, then track explicit
            # acquire/release transitions for subsequent statements
            for e in self._stmt_exprs(s):
                self._scan_expr(e, held, info)
            held = self._transition(s, held, info)
        return held

    @staticmethod
    def _stmt_exprs(s: ast.stmt) -> List[ast.expr]:
        out: List[ast.expr] = []
        for field in ("value", "test", "msg", "exc", "cause"):
            v = getattr(s, field, None)
            if isinstance(v, ast.expr):
                out.append(v)
        for field in ("targets",):
            for v in getattr(s, field, []) or []:
                if isinstance(v, ast.expr):
                    out.append(v)
        v = getattr(s, "target", None)
        if isinstance(v, ast.expr):
            out.append(v)
        return out

    def _acquire_in_test(self, test: ast.expr, info: Optional[_FnInfo]
                         ) -> Optional[Tuple[str, ast.AST]]:
        """``if lock.acquire(timeout=...):`` — the body runs with the
        lock held (the bounded-acquire idiom the repo standardizes on)."""
        for n in ast.walk(test):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "acquire":
                lid = self._lock_id(n.func.value, info)
                if lid is not None:
                    return lid, n
        return None

    def _transition(self, s: ast.stmt, held: frozenset,
                    info: Optional[_FnInfo]) -> frozenset:
        call: Optional[ast.Call] = None
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
            call = s.value
        elif isinstance(s, ast.Assign) and isinstance(s.value, ast.Call):
            call = s.value
        if call is None or not isinstance(call.func, ast.Attribute):
            return held
        if call.func.attr == "acquire":
            lid = self._lock_id(call.func.value, info)
            if lid is not None:
                self._record_acquire(lid, held, call, info)
                return held | {lid}
        elif call.func.attr == "release":
            lid = self._lock_id(call.func.value, info)
            if lid is not None:
                return held - {lid}
        return held

    # ------------------------------------------------- expression scan

    def _scan_expr(self, expr: ast.expr, held: frozenset,
                   info: Optional[_FnInfo]) -> None:
        parents: Dict[int, ast.AST] = {}
        nodes: List[ast.AST] = []

        def walk(n: ast.AST) -> None:
            nodes.append(n)
            for c in ast.iter_child_nodes(n):
                if isinstance(c, ast.Lambda) and id(c) in self.fns:
                    continue           # spawned lambda: its own scope
                parents[id(c)] = n
                walk(c)

        walk(expr)
        roles = self._roles_of(info)
        for n in nodes:
            if isinstance(n, ast.Call):
                self._scan_call(n, held, info, roles)
            if isinstance(n, ast.Attribute):
                self._scan_attr_access(n, parents, held, info, roles)
            elif isinstance(n, ast.Name):
                self._scan_name_access(n, parents, held, info, roles)

    def _scan_call(self, call: ast.Call, held: frozenset,
                   info: Optional[_FnInfo], roles: frozenset) -> None:
        name = self.canonical(call.func)
        attr = call.func.attr if isinstance(call.func, ast.Attribute) \
            else None
        # GT102: unbounded acquire anywhere a thread topology exists
        if attr == "acquire" and not _is_bounded_acquire(call):
            lid = self._lock_id(call.func.value, info)
            if self.has_spawns or lid is not None:
                where = f" on `{lid}`" if lid else ""
                self.emit(
                    call, "GT102",
                    f"bare `.acquire()`{where} without a timeout in "
                    f"threaded code (role(s) "
                    f"{', '.join(sorted(roles))}): a stuck holder "
                    f"wedges this thread with no watchdog escape — "
                    f"use `acquire(timeout=...)` and handle the False "
                    f"return, or `with lock:` for short sections")
        # GT105 stamp census
        if attr in _STAMP_METHODS and \
                isinstance(call.func, ast.Attribute):
            wid = self._wd_id(call.func.value, info)
            if wid is not None:
                self._wd_stamps.setdefault(wid, []).append(
                    (roles, call, info.qualname if info else "<module>"))
        # GT106 blocking-call census (classified after contention known)
        blocking: Optional[str] = None
        if name in _BLOCKING_NAMES:
            blocking = name
        elif attr in ("device_get", "block_until_ready"):
            blocking = attr
        elif attr in _BLOCKING_SOCKET_ATTRS:
            blocking = f".{attr}()"
        elif attr in _BLOCKING_IF_UNBOUNDED and not _has_timeout(call):
            # cond.wait() while holding cond RELEASES it — the one
            # sanctioned blocking-under-lock idiom, never flagged
            lid = self._lock_id(call.func.value, info) \
                if isinstance(call.func, ast.Attribute) else None
            if lid is None or lid not in held:
                blocking = f".{attr}()"
        if blocking is not None and held:
            self.blockings.append(_Blocking(what=blocking, roles=roles,
                                            held=held, node=call))

    # census access recording -------------------------------------------

    def _access_kind(self, n: ast.AST,
                     parents: Dict[int, ast.AST]) -> Optional[bool]:
        """True = write, False = read, None = not a state access (a
        plain method call on the object)."""
        ctx = getattr(n, "ctx", None)
        if isinstance(ctx, (ast.Store, ast.Del)):
            return True
        cur = n
        while True:
            p = parents.get(id(cur))
            if p is None:
                return False
            if isinstance(p, ast.Subscript) and p.value is cur:
                if isinstance(p.ctx, (ast.Store, ast.Del)):
                    return True
                cur = p
                continue
            if isinstance(p, ast.Attribute) and p.value is cur:
                gp = parents.get(id(p))
                if isinstance(gp, ast.Call) and gp.func is p:
                    if p.attr in _MUTATORS:
                        return True
                    return False
                return False
            if isinstance(p, ast.AugAssign) and p.target is cur:
                return True
            return False

    def _record(self, key: Tuple, write: bool, init: bool,
                roles: frozenset, held: frozenset, node: ast.AST,
                info: Optional[_FnInfo]) -> None:
        self.accesses.append(_Access(
            key=key, write=write, init=init, roles=roles, held=held,
            node=node, fn=info.qualname if info else "<module>"))

    def _scan_attr_access(self, n: ast.Attribute,
                          parents: Dict[int, ast.AST], held: frozenset,
                          info: Optional[_FnInfo],
                          roles: frozenset) -> None:
        if not isinstance(n.value, ast.Name):
            return
        base = n.value.id
        cls: Optional[str] = None
        if base == "self" and info is not None and info.cls is not None:
            cls = info.cls
        elif info is not None:
            cls = self._typed_class(base, id(info.node))
        if cls is None:
            return
        key2 = (cls, n.attr)
        if key2 in self.lock_attrs or key2 in self.safe_attrs or \
                key2 in self.wd_attrs:
            return
        # skip method references: calls resolve through the call graph
        if n.attr in self.methods.get(cls, {}):
            return
        kind = self._access_kind(n, parents)
        if kind is None:
            return
        init = (info is not None and info.cls == cls
                and info.name in _INIT_METHODS)
        self._record(("attr", cls, n.attr), kind, init, roles, held,
                     n, info)

    def _scan_name_access(self, n: ast.Name,
                          parents: Dict[int, ast.AST], held: frozenset,
                          info: Optional[_FnInfo],
                          roles: frozenset) -> None:
        name = n.id
        # closure census: resolve to the nearest enclosing binder; if
        # that scope shares the name with a spawned nested fn, census it
        if info is not None:
            fid: Optional[int] = id(info.node)
            while fid is not None:
                f = self.fns[fid]
                if name in f.bound and name not in f.nonlocals:
                    if name in f.shared and \
                            name not in f.local_locks and \
                            name not in f.local_safe and \
                            name not in f.local_wds:
                        kind = self._access_kind(n, parents)
                        if kind is None:
                            return
                        # pre-spawn accesses in the owning scope
                        # happen-before the thread exists
                        init = (id(info.node) == fid
                                and f.first_spawn_line is not None
                                and n.lineno < f.first_spawn_line)
                        self._record(("closure", f.qualname, name),
                                     kind, init, roles, held, n, info)
                    return
                fid = f.parent
        # module-global census: only names some function writes via
        # ``global`` (read-only module constants are not shared state)
        if name in self.written_globals and \
                name not in self.global_locks and \
                name not in self.global_safe and \
                name not in self.global_wds:
            kind = self._access_kind(n, parents)
            if kind is None:
                return
            self._record(("global", self.path, name), kind,
                         info is None, roles, held, n, info)

    # ----------------------------------------------- pass 3: classify

    def classify(self) -> None:
        self._classify_census()
        self._classify_cycles()
        self._classify_watchdogs()
        self._classify_blocking()

    @staticmethod
    def _describe(key: Tuple) -> str:
        kind, owner, name = (key + ("",))[:3]
        if kind == "attr":
            return f"`self.{name}` ({owner})"
        if kind == "closure":
            return f"closure var `{name}` (in {owner})"
        return f"module global `{name}`"

    def _classify_census(self) -> None:
        by_key: Dict[Tuple, List[_Access]] = {}
        for a in self.accesses:
            by_key.setdefault(a.key, []).append(a)
        for key, sites in sorted(by_key.items(),
                                 key=lambda kv: str(kv[0])):
            live = [s for s in sites if not s.init]
            if not live:
                continue
            role_union: Set[str] = set()
            for s in live:
                role_union |= set(s.roles)
            if len(role_union) < 2:
                continue
            writes = [s for s in live if s.write]
            if not writes:
                continue
            locked = [s for s in live if s.held]
            desc = self._describe(key)
            rs = ", ".join(sorted(role_union))
            if not locked:
                for w in writes:
                    self.emit(
                        w.node, "GT101",
                        f"{desc} is written here and accessed from "
                        f"role(s) {rs} with no lock at any site — "
                        f"cross-thread data race; guard every access "
                        f"with one lock (or baseline with a "
                        f"justification for why this is safe)")
                continue
            common = frozenset.intersection(*[s.held for s in live]) \
                if all(s.held for s in live) else frozenset()
            if common:
                continue                        # uniformly protected
            lock_counts: Dict[str, int] = {}
            for s in locked:
                for lid in s.held:
                    lock_counts[lid] = lock_counts.get(lid, 0) + 1
            dominant = max(sorted(lock_counts), key=lock_counts.get)
            for s in live:
                if dominant not in s.held:
                    self.emit(
                        s.node, "GT103",
                        f"{desc} is {'written' if s.write else 'read'} "
                        f"here without `{dominant}` but "
                        f"{lock_counts[dominant]} other site(s) hold "
                        f"it (roles {rs}) — the lock protects nothing "
                        f"unless every cross-thread access takes it")

    def _classify_cycles(self) -> None:
        edges: Dict[str, Set[str]] = {}
        sites: Dict[Tuple[str, str], ast.AST] = {}
        for a in self.acquires:
            for h in a.held:
                if h == a.lock:
                    continue                   # RLock re-entry, not ABBA
                edges.setdefault(h, set()).add(a.lock)
                sites.setdefault((h, a.lock), a.node)

        def reaches(src: str, dst: str) -> bool:
            seen: Set[str] = set()
            stack = [src]
            while stack:
                cur = stack.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(edges.get(cur, ()))
            return False

        for (a, b), node in sorted(sites.items()):
            if reaches(b, a):
                self.emit(
                    node, "GT104",
                    f"lock-ordering cycle: `{b}` is acquired here "
                    f"while `{a}` is held, but elsewhere the order is "
                    f"reversed — two threads taking the ends in "
                    f"opposite order deadlock; pick one global order")

    def _classify_watchdogs(self) -> None:
        for wid, stamps in sorted(self._wd_stamps.items()):
            role_union: Set[str] = set()
            for roles, _, _ in stamps:
                role_union |= set(roles)
            if len(role_union) < 2:
                continue
            rs = ", ".join(sorted(role_union))
            for roles, node, fn in stamps:
                self.emit(
                    node, "GT105",
                    f"Watchdog `{wid}` is stamped from role(s) {rs} "
                    f"(here: {fn}) — interleaved stamps mask a stall "
                    f"in either thread behind the other's heartbeat; "
                    f"give each thread its own Watchdog (the Sebulba "
                    f"per-thread-watchdog discipline)")

    def _classify_blocking(self) -> None:
        contention: Dict[str, Set[str]] = {}
        for a in self.acquires:
            contention.setdefault(a.lock, set()).update(a.roles)
        for b in self.blockings:
            contended = [l for l in sorted(b.held)
                         if len(contention.get(l, set())) >= 2]
            if not contended:
                continue
            lock = contended[0]
            others = ", ".join(sorted(contention[lock] - set(b.roles))
                               or sorted(contention[lock]))
            self.emit(
                b.node, "GT106",
                f"blocking call `{b.what}` while holding `{lock}`, "
                f"which role(s) {others} also acquire — a device/"
                f"socket stall here wedges every contender and no "
                f"watchdog can preempt a held lock; move the blocking "
                f"call outside the critical section")

    # ------------------------------------------------------------- drive

    def run(self) -> List[Finding]:
        if any(_SKIP_FILE_RE.search(l) for l in self.lines[:10]):
            return []
        self._wd_stamps: Dict[str, List[Tuple[frozenset, ast.AST,
                                              str]]] = {}
        self.build()
        self.scan()
        self.classify()
        return sorted(self.findings,
                      key=lambda f: (f.path, f.line, f.col, f.rule))


# ---------------------------------------------------------------- frontend

def trace_source(src: str, path: str = "<memory>") -> List[Finding]:
    """Audit one source string (fixture entry point for the tests)."""
    return _ModuleTracer(src, path).run()


def trace_file(path: Path, root: Path) -> List[Finding]:
    rel = path.resolve().relative_to(root.resolve()).as_posix()
    return trace_source(path.read_text(), rel)


def trace_package(root: Path,
                  paths: Optional[Sequence[Path]] = None
                  ) -> List[Finding]:
    """Audit every ``*.py`` under ``paths`` (default:
    ``root/t2omca_tpu``), reporting paths relative to ``root``."""
    root = Path(root)
    if paths is None:
        paths = [root / "t2omca_tpu"]
    findings: List[Finding] = []
    for p in paths:
        p = Path(p)
        files: Iterable[Path] = (sorted(p.rglob("*.py")) if p.is_dir()
                                 else [p])
        for f in files:
            findings.extend(trace_file(f, root))
    return findings
