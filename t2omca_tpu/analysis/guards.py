"""Runtime tracing-hygiene enforcement: compile budgets + transfer guards.

The static side (``graftlint``) catches hazards visible in the AST; this
module catches the two failure modes that are only observable at run
time and that PR 2's superstep made expensive:

* **Silent retraces.** ``superstep_program`` amortizes ~0.66 s of
  dispatch overhead over K iterations (BASELINE.md) — ONE compile, many
  dispatches. A weak-typed scalar, a shape wobble, or a changed static
  arg silently recompiles the whole fused program every iteration and
  erases the win (the exact bug class ``run._strong`` exists to stop).
  ``compile_budget(n)`` turns that into a hard test failure: it counts
  XLA compiles (via the ``jax.log_compiles`` log stream) inside the
  ``with`` block and raises ``CompileBudgetExceeded`` past ``n``.

* **Implicit host transfers.** The fused K>1 path promises "no host
  round-trip between dispatch boundaries". ``no_transfer()`` wraps
  ``jax.transfer_guard`` so any implicit device→host fetch (and, by
  default, any implicit host→device upload — a Python scalar sneaking
  into dispatch args is also a weak-type retrace hazard) raises instead
  of silently stalling. Explicit ``jax.device_get`` at cadence
  boundaries stays allowed — the guards police *implicit* traffic. On
  the CPU backend device→host copies are zero-copy and never trip the
  guard; the host→device direction still enforces, so the tests keep
  teeth under ``JAX_PLATFORMS=cpu`` and gain the full check on device.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
from typing import Iterator, List, Optional

import jax

#: loggers that carry the per-compile "Compiling <fn> ..." records
#: (jax._src.interpreters.pxla emits them for both the jit and the
#: pjit/sharded paths on JAX 0.4.x; dispatch kept for fallback coverage)
_COMPILE_LOGGERS = ("jax._src.interpreters.pxla", "jax._src.dispatch")


class CompileBudgetExceeded(RuntimeError):
    """More XLA compiles than the budget allows inside a
    ``compile_budget`` block — something is retracing."""


@dataclasses.dataclass
class CompileEvents:
    """Live view of compiles seen so far inside a ``compile_budget``
    block. ``names`` holds the jitted-function names in compile order
    (every jnp op outside jit is itself a tiny jitted program, hence the
    ``match`` filter on the budget)."""

    match: Optional[str] = None
    names: List[str] = dataclasses.field(default_factory=list)

    @property
    def count(self) -> int:
        if self.match is None:
            return len(self.names)
        return sum(self.match in n for n in self.names)


class _CompileCapture(logging.Handler):
    def __init__(self, events: CompileEvents):
        super().__init__(level=logging.DEBUG)
        self.events = events

    def emit(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        if not msg.startswith("Compiling "):
            return
        name = (str(record.args[0]) if record.args
                else msg.split(" ", 2)[1])
        self.events.names.append(name)


@contextlib.contextmanager
def compile_budget(n: int, match: Optional[str] = None
                   ) -> Iterator[CompileEvents]:
    """Assert at most ``n`` XLA compiles (of functions whose name
    contains ``match``, when given) happen inside the block.

    ::

        superstep = exp.superstep_program(k, donate=True)
        with compile_budget(1, match="_superstep") as log:
            for _ in range(10):
                ts, stats, infos = superstep(ts, keys, t0)
        assert log.count == 1          # also enforced on exit

    Without ``match`` EVERY compile counts — including the tiny
    per-primitive programs bare jnp ops build outside jit — so pin a
    specific program by its (inner) function name. Raises
    ``CompileBudgetExceeded`` on block exit when the matched count
    exceeds ``n``; nested budgets compose (each keeps its own counter).
    """
    events = CompileEvents(match=match)
    handler = _CompileCapture(events)
    loggers = [logging.getLogger(nm) for nm in _COMPILE_LOGGERS]
    for lg in loggers:
        lg.addHandler(handler)
    try:
        with jax.log_compiles(True):
            yield events
    finally:
        for lg in loggers:
            lg.removeHandler(handler)
    if events.count > n:
        what = f" of {match!r}" if match else ""
        raise CompileBudgetExceeded(
            f"{events.count} XLA compiles{what} inside a "
            f"compile_budget({n}) block — something is retracing "
            f"(weak-typed scalar? shape wobble? changed static arg?); "
            f"compile order: {events.names}")


@contextlib.contextmanager
def no_transfer(host_to_device: bool = True) -> Iterator[None]:
    """Raise on any *implicit* device→host transfer (and, unless
    ``host_to_device=False``, any implicit host→device upload) inside
    the block. Explicit transfers — ``jax.device_put``,
    ``jax.device_get`` — stay allowed: the driver's cadence-boundary
    fetches are deliberate, it's the silent ones that stall the
    pipeline (the PR 2 priority-feedback ``device_get`` cost ~0.66 s
    per train iteration before it was made async)."""
    with contextlib.ExitStack() as stack:
        stack.enter_context(jax.transfer_guard_device_to_host("disallow"))
        if host_to_device:
            stack.enter_context(
                jax.transfer_guard_host_to_device("disallow"))
        yield
